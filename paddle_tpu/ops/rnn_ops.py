"""Recurrent ops: lstm / lstmp / gru / gru_unit / lstm_unit / cudnn_lstm.

Reference kernels: paddle/fluid/operators/{lstm,lstmp,gru,gru_unit,lstm_unit,
cudnn_lstm}_op.* over math/detail/{lstm,gru}_kernel.h.  The reference
re-orders ragged batches by descending length (math/sequence2batch.h) and
shrinks the active batch each step; the TPU lowering instead runs a
`lax.scan` over the padded time axis with per-step validity masks — static
shapes, one fused XLA while-loop, MXU-friendly [N, 4H] matmuls per step.

Gate layouts (must match the reference numerics exactly):
  lstm/lstmp 4H buffer = [c-candidate, input, forget, output]
    (math/detail/lstm_cpu_kernel.h:44-47: value_in, value_ig, value_fg,
     value_og), peephole bias is [b(4H), checkI, checkF, checkO]
    (lstm_op.cc:75 enforces 7H).
  lstm_unit 4H buffer = [i, f, o, g] with forget_bias on f
    (lstm_unit_op.h:63-66).
  gru/gru_unit 3H buffer = [update, reset, candidate]; h = (1-u)*h_prev +
    u*c-tilde (math/detail/gru_kernel.h gru_finalOutput; gru_unit_op.h:99-113).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.lod import LoDValue
from ..core.proto import DataType
from ..core.registry import register_op
from .common import ACTS, data, in_desc, lengths, set_output, wrap_lod

def _act(name):
    return ACTS[name or "identity"]


# gru_unit encodes activations as ints (gru_unit_op.h:34 GRUActivationType)
_INT_ACTS = {0: "identity", 1: "sigmoid", 2: "tanh", 3: "relu"}


def _act_attr(v, default):
    if v is None:
        return _act(default)
    if isinstance(v, int):
        return _act(_INT_ACTS[v])
    return _act(v)


def _seq_reverse_valid(d, l):
    """Reverse each row's first l_i tokens in place (pad slots untouched)."""
    t = d.shape[1]
    ar = jnp.arange(t)[None, :]
    idx = jnp.where(ar < l[:, None], l[:, None] - 1 - ar, ar)
    return jnp.take_along_axis(
        d, idx.reshape(idx.shape + (1,) * (d.ndim - 2)).astype(jnp.int32), axis=1
    )


def _scan_time_major(step, carry, xs_nt, mask_nt):
    """Run `step` over the time axis of [N, T, ...] inputs with [N, T] mask;
    returns (final_carry, stacked [N, T, ...] pytree of per-step outputs)."""
    xs_t = jax.tree_util.tree_map(lambda a: jnp.swapaxes(a, 0, 1), xs_nt)
    mask_t = jnp.swapaxes(mask_nt, 0, 1)  # [T, N]

    def body(c, inp):
        x_t, m_t = inp
        return step(c, x_t, m_t[:, None])

    final, ys_t = jax.lax.scan(body, carry, (xs_t, mask_t))
    ys = jax.tree_util.tree_map(lambda a: jnp.swapaxes(a, 0, 1), ys_t)
    return final, ys


# ---------------------------------------------------------------------------
# lstm / lstmp
# ---------------------------------------------------------------------------
def _lstm_infer(op, block):
    x = in_desc(op, block, "Input")
    w = in_desc(op, block, "Weight")
    if x is None or w is None:
        return
    h = w.shape[0]
    set_output(block, op, "Hidden", [-1, h], x.dtype, lod_level=1)
    set_output(block, op, "Cell", [-1, h], x.dtype, lod_level=1)
    for slot in ("BatchGate", "BatchCellPreAct"):
        if op.output(slot) and op.output(slot)[0]:
            set_output(block, op, slot, [-1, 4 * h], x.dtype, lod_level=1)


def _lstm_core(ctx, ins, attrs, proj_weight=None):
    x = ins["Input"][0]
    d = data(x)
    l = lengths(x)
    if l is None:
        l = jnp.full((d.shape[0],), d.shape[1], dtype=jnp.int32)
    w = data(ins["Weight"][0])  # [H or P, 4H]
    hid = w.shape[1] // 4
    bias = data(ins["Bias"][0]) if ins.get("Bias") and ins["Bias"][0] is not None else None
    use_peepholes = attrs.get("use_peepholes", True)
    act_gate = _act(attrs.get("gate_activation", "sigmoid"))
    act_cell = _act(attrs.get("cell_activation", "tanh"))
    act_cand = _act(attrs.get("candidate_activation", "tanh"))
    act_proj = _act(attrs.get("proj_activation", "tanh"))

    b4 = ci = cf = co = None
    if bias is not None:
        b = bias.reshape(-1)
        b4 = b[: 4 * hid]
        if use_peepholes and b.shape[0] >= 7 * hid:
            ci = b[4 * hid : 5 * hid]
            cf = b[5 * hid : 6 * hid]
            co = b[6 * hid : 7 * hid]

    if attrs.get("is_reverse", False):
        d = _seq_reverse_valid(d, l)

    n = d.shape[0]
    h0 = data(ins["H0"][0]) if ins.get("H0") and ins["H0"][0] is not None else jnp.zeros(
        (n, proj_weight.shape[1] if proj_weight is not None else hid), d.dtype
    )
    c0 = data(ins["C0"][0]) if ins.get("C0") and ins["C0"][0] is not None else jnp.zeros((n, hid), d.dtype)

    mask = jnp.arange(d.shape[1])[None, :] < l[:, None]

    def step(carry, x_t, m):
        h_prev, c_prev = carry
        gates = x_t + h_prev @ w
        if b4 is not None:
            gates = gates + b4
        g_in, g_i, g_f, g_o = jnp.split(gates, 4, axis=-1)
        cand = act_cand(g_in)
        i = act_gate(g_i + (c_prev * ci if ci is not None else 0.0))
        f = act_gate(g_f + (c_prev * cf if cf is not None else 0.0))
        c = cand * i + c_prev * f
        o = act_gate(g_o + (c * co if co is not None else 0.0))
        h = o * act_cell(c)
        if proj_weight is not None:
            h = act_proj(h @ proj_weight)
        mf = m.astype(d.dtype)
        h_new = h * mf + h_prev * (1 - mf)
        c_new = c * mf + c_prev * (1 - mf)
        gates_act = jnp.concatenate([cand, i, f, o], axis=-1)
        return (h_new, c_new), (h * mf, c * mf, gates_act * mf, g_in * mf)

    (_, _), (hs, cs, gates_seq, preact) = _scan_time_major(
        step, (h0, c0), d, mask
    )
    if attrs.get("is_reverse", False):
        hs = _seq_reverse_valid(hs, l)
        cs = _seq_reverse_valid(cs, l)
    return hs, cs, gates_seq, preact, l


@register_op("lstm", infer_shape=_lstm_infer, diff_inputs=["Input", "Weight", "Bias", "H0", "C0"])
def _lstm(ctx, ins, attrs):
    """Sequence LSTM (reference: operators/lstm_op.cc)."""
    hs, cs, gates, preact, l = _lstm_core(ctx, ins, attrs)
    return {
        "Hidden": [LoDValue(hs, l)],
        "Cell": [LoDValue(cs, l)],
        "BatchGate": [LoDValue(gates, l)],
        "BatchCellPreAct": [LoDValue(preact, l)],
    }


def _lstmp_infer(op, block):
    x = in_desc(op, block, "Input")
    pw = in_desc(op, block, "ProjWeight")
    w = in_desc(op, block, "Weight")
    if x is None or pw is None or w is None:
        return
    set_output(block, op, "Projection", [-1, pw.shape[1]], x.dtype, lod_level=1)
    set_output(block, op, "Cell", [-1, w.shape[1] // 4], x.dtype, lod_level=1)


@register_op("lstmp", infer_shape=_lstmp_infer, diff_inputs=["Input", "Weight", "ProjWeight", "Bias", "H0", "C0"])
def _lstmp(ctx, ins, attrs):
    """LSTM with recurrent projection (reference: operators/lstmp_op.cc)."""
    pw = data(ins["ProjWeight"][0])
    hs, cs, gates, preact, l = _lstm_core(ctx, ins, attrs, proj_weight=pw)
    return {
        "Projection": [LoDValue(hs, l)],
        "Cell": [LoDValue(cs, l)],
        "BatchGate": [LoDValue(gates, l)],
        "BatchCellPreAct": [LoDValue(preact, l)],
    }


# ---------------------------------------------------------------------------
# gru
# ---------------------------------------------------------------------------
def _gru_infer(op, block):
    x = in_desc(op, block, "Input")
    w = in_desc(op, block, "Weight")
    if x is None or w is None:
        return
    h = w.shape[0]
    set_output(block, op, "Hidden", [-1, h], x.dtype, lod_level=1)
    for slot in ("BatchGate", "BatchResetHiddenPrev", "BatchHidden"):
        if op.output(slot) and op.output(slot)[0]:
            width = 3 * h if slot == "BatchGate" else h
            set_output(block, op, slot, [-1, width], x.dtype, lod_level=1)


@register_op("gru", infer_shape=_gru_infer, diff_inputs=["Input", "Weight", "Bias", "H0"])
def _gru(ctx, ins, attrs):
    """Sequence GRU (reference: operators/gru_op.cc)."""
    x = ins["Input"][0]
    d = data(x)
    l = lengths(x)
    if l is None:
        l = jnp.full((d.shape[0],), d.shape[1], dtype=jnp.int32)
    w = data(ins["Weight"][0])  # [H, 3H]
    hid = w.shape[0]
    w_ur = w[:, : 2 * hid]
    w_c = w[:, 2 * hid :]
    bias = data(ins["Bias"][0]).reshape(-1) if ins.get("Bias") and ins["Bias"][0] is not None else None
    act_gate = _act(attrs.get("gate_activation", "sigmoid"))
    act_node = _act(attrs.get("activation", "tanh"))

    if attrs.get("is_reverse", False):
        d = _seq_reverse_valid(d, l)
    n = d.shape[0]
    h0 = data(ins["H0"][0]) if ins.get("H0") and ins["H0"][0] is not None else jnp.zeros((n, hid), d.dtype)
    mask = jnp.arange(d.shape[1])[None, :] < l[:, None]

    def step(h_prev, x_t, m):
        g = x_t + (bias if bias is not None else 0.0)
        ur = g[:, : 2 * hid] + h_prev @ w_ur
        u = act_gate(ur[:, :hid])
        r = act_gate(ur[:, hid:])
        rh = r * h_prev
        c = act_node(g[:, 2 * hid :] + rh @ w_c)
        h = h_prev - u * h_prev + u * c
        mf = m.astype(d.dtype)
        h_new = h * mf + h_prev * (1 - mf)
        gates = jnp.concatenate([u, r, c], axis=-1)
        return h_new, (h * mf, rh * mf, gates * mf)

    _, (hs, rhs, gates_seq) = _scan_time_major(step, h0, d, mask)
    if attrs.get("is_reverse", False):
        hs = _seq_reverse_valid(hs, l)
    return {
        "Hidden": [LoDValue(hs, l)],
        "BatchGate": [LoDValue(gates_seq, l)],
        "BatchResetHiddenPrev": [LoDValue(rhs, l)],
        "BatchHidden": [LoDValue(hs, l)],
    }


# ---------------------------------------------------------------------------
# gru_unit / lstm_unit (single step)
# ---------------------------------------------------------------------------
def _gru_unit_infer(op, block):
    hp = in_desc(op, block, "HiddenPrev")
    if hp is None:
        return
    h = hp.shape[-1]
    set_output(block, op, "Hidden", list(hp.shape), hp.dtype)
    set_output(block, op, "Gate", list(hp.shape[:-1]) + [3 * h], hp.dtype)
    set_output(block, op, "ResetHiddenPrev", list(hp.shape), hp.dtype)


@register_op("gru_unit", infer_shape=_gru_unit_infer, diff_inputs=["Input", "HiddenPrev", "Weight", "Bias"])
def _gru_unit(ctx, ins, attrs):
    """One GRU step (reference: operators/gru_unit_op.h:99-113)."""
    x = data(ins["Input"][0])
    h_prev = data(ins["HiddenPrev"][0])
    w = data(ins["Weight"][0])
    hid = h_prev.shape[-1]
    bias = data(ins["Bias"][0]).reshape(-1) if ins.get("Bias") and ins["Bias"][0] is not None else 0.0
    act_gate = _act_attr(attrs.get("gate_activation", 1), "sigmoid")
    act_node = _act_attr(attrs.get("activation", 2), "tanh")
    g = x + bias
    ur = g[:, : 2 * hid] + h_prev @ w[:, : 2 * hid]
    u = act_gate(ur[:, :hid])
    r = act_gate(ur[:, hid:])
    rh = r * h_prev
    c = act_node(g[:, 2 * hid :] + rh @ w[:, 2 * hid :])
    h = h_prev - u * h_prev + u * c
    return {
        "Hidden": [h],
        "Gate": [jnp.concatenate([u, r, c], axis=-1)],
        "ResetHiddenPrev": [rh],
    }


def _lstm_unit_infer(op, block):
    c = in_desc(op, block, "C_prev")
    if c is None:
        return
    set_output(block, op, "C", list(c.shape), c.dtype)
    set_output(block, op, "H", list(c.shape), c.dtype)


@register_op("lstm_unit", infer_shape=_lstm_unit_infer, diff_inputs=["X", "C_prev"])
def _lstm_unit(ctx, ins, attrs):
    """One LSTM step, [i, f, o, g] gate order with forget_bias
    (reference: operators/lstm_unit_op.h:63-71)."""
    x = data(ins["X"][0])
    c_prev = data(ins["C_prev"][0])
    fb = attrs.get("forget_bias", 0.0)
    d = c_prev.shape[-1]
    i = jax.nn.sigmoid(x[:, :d])
    f = jax.nn.sigmoid(x[:, d : 2 * d] + fb)
    o = jax.nn.sigmoid(x[:, 2 * d : 3 * d])
    g = jnp.tanh(x[:, 3 * d :])
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return {"C": [c], "H": [h]}


# ---------------------------------------------------------------------------
# cudnn_lstm: dense multi-layer (bi)LSTM over padded [N, T, D]
# ---------------------------------------------------------------------------
def _cudnn_lstm_infer(op, block):
    x = in_desc(op, block, "Input")
    if x is None:
        return
    h = op.attr("hidden_size", 100)
    bidi = 2 if op.attr("is_bidirec", False) else 1
    set_output(block, op, "Out", list(x.shape[:-1]) + [h * bidi], x.dtype, lod_level=x.lod_level)
    set_output(block, op, "last_h", [-1, h], x.dtype)
    set_output(block, op, "last_c", [-1, h], x.dtype)


@register_op("cudnn_lstm", infer_shape=_cudnn_lstm_infer, random=True,
             diff_inputs=["Input", "W", "InitH", "InitC"])
def _cudnn_lstm(ctx, ins, attrs):
    """Multi-layer (bi)LSTM over a dense [T, N, D] batch — TPU replacement
    for the cuDNN fused path (reference: operators/cudnn_lstm_op.cu.cc).
    The flat weight W packs, per layer and direction, [Wx (D_in x 4H),
    Wh (H x 4H), b (4H)] in order; gate order matches cuDNN (i, f, g, o)."""
    x = data(ins["Input"][0])  # reference feeds [T, N, D]
    w = data(ins["W"][0]).reshape(-1)
    hid = int(attrs.get("hidden_size", 100))
    layers = int(attrs.get("num_layers", 1))
    bidi = bool(attrs.get("is_bidirec", False))
    dropout_prob = float(attrs.get("dropout_prob", 0.0))
    ndir = 2 if bidi else 1
    t, n = x.shape[0], x.shape[1]

    init_h = data(ins["InitH"][0]) if ins.get("InitH") and ins["InitH"][0] is not None else None
    init_c = data(ins["InitC"][0]) if ins.get("InitC") and ins["InitC"][0] is not None else None

    def take(off, shape):
        size = int(np.prod(shape))
        return w[off : off + size].reshape(shape), off + size

    def run_dir(seq, wx, wh, b, h0, c0, reverse):
        if reverse:
            seq = seq[::-1]

        def step(carry, x_t):
            h_prev, c_prev = carry
            gates = x_t @ wx + h_prev @ wh + b
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h

        (hT, cT), hs = jax.lax.scan(step, (h0, c0), seq)
        if reverse:
            hs = hs[::-1]
        return hs, hT, cT

    off = 0
    inp = x
    last_h, last_c = [], []
    for layer in range(layers):
        d_in = inp.shape[-1]
        outs = []
        for direction in range(ndir):
            wx, off = take(off, (d_in, 4 * hid))
            wh, off = take(off, (hid, 4 * hid))
            b, off = take(off, (4 * hid,))
            li = layer * ndir + direction
            h0 = init_h[li] if init_h is not None else jnp.zeros((n, hid), x.dtype)
            c0 = init_c[li] if init_c is not None else jnp.zeros((n, hid), x.dtype)
            hs, hT, cT = run_dir(inp, wx, wh, b, h0, c0, reverse=(direction == 1))
            outs.append(hs)
            last_h.append(hT)
            last_c.append(cT)
        inp = jnp.concatenate(outs, axis=-1) if ndir == 2 else outs[0]
        if dropout_prob > 0.0 and layer < layers - 1 and not ctx.is_test:
            keep = 1.0 - dropout_prob
            mask = jax.random.bernoulli(ctx.rng(), keep, inp.shape)
            inp = jnp.where(mask, inp / keep, 0.0)
    return {
        "Out": [inp],
        "last_h": [jnp.stack(last_h)],
        "last_c": [jnp.stack(last_c)],
    }


# ---------------------------------------------------------------------------
# fused sequence RNNs (reference: operators/fused/fusion_lstm_op.cc,
# fusion_gru_op.cc — MKLDNN-era fusions of fc + recurrence; here the input
# projection is one extra MXU matmul feeding the same scan, and XLA fuses
# whatever else it can)
# ---------------------------------------------------------------------------
def _fusion_lstm_infer(op, block):
    x = in_desc(op, block, "X")
    wh = in_desc(op, block, "WeightH")
    if x is None or wh is None:
        return
    h = wh.shape[0]
    set_output(block, op, "Hidden", [-1, h], x.dtype, lod_level=1)
    set_output(block, op, "Cell", [-1, h], x.dtype, lod_level=1)
    if op.output("XX") and op.output("XX")[0]:
        set_output(block, op, "XX", [-1, 4 * h], x.dtype, lod_level=1)


@register_op("fusion_lstm", infer_shape=_fusion_lstm_infer,
             diff_inputs=["X", "WeightX", "WeightH", "Bias", "H0", "C0"])
def _fusion_lstm(ctx, ins, attrs):
    """fc + LSTM in one op (reference: fused/fusion_lstm_op.cc): the gate
    projection x @ WeightX lands on the MXU as one batched matmul and the
    recurrence reuses the lstm scan."""
    x = ins["X"][0]
    d = data(x)
    l = lengths(x)
    if l is None:
        l = jnp.full((d.shape[0],), d.shape[1], dtype=jnp.int32)
    wx = data(ins["WeightX"][0])   # [M, 4H]
    xx = jnp.einsum("ntm,mh->nth", d, wx)
    ins2 = dict(ins)
    ins2["Input"] = [LoDValue(xx, l)]
    ins2["Weight"] = ins["WeightH"]
    hs, cs, gates, preact, l = _lstm_core(ctx, ins2, attrs)
    return {
        "Hidden": [LoDValue(hs, l)],
        "Cell": [LoDValue(cs, l)],
        "XX": [LoDValue(xx, l)],
    }


def _fusion_gru_infer(op, block):
    x = in_desc(op, block, "X")
    wh = in_desc(op, block, "WeightH")
    if x is None or wh is None:
        return
    h = wh.shape[0]
    set_output(block, op, "Hidden", [-1, h], x.dtype, lod_level=1)
    if op.output("XX") and op.output("XX")[0]:
        set_output(block, op, "XX", [-1, 3 * h], x.dtype, lod_level=1)


@register_op("fusion_gru", infer_shape=_fusion_gru_infer,
             diff_inputs=["X", "WeightX", "WeightH", "Bias", "H0"])
def _fusion_gru(ctx, ins, attrs):
    """fc + GRU in one op (reference: fused/fusion_gru_op.cc)."""
    x = ins["X"][0]
    d = data(x)
    l = lengths(x)
    if l is None:
        l = jnp.full((d.shape[0],), d.shape[1], dtype=jnp.int32)
    wx = data(ins["WeightX"][0])   # [M, 3H]
    xx = jnp.einsum("ntm,mh->nth", d, wx)
    ins2 = dict(ins)
    ins2["Input"] = [LoDValue(xx, l)]
    ins2["Weight"] = ins["WeightH"]
    outs = _gru(ctx, ins2, attrs)
    return {"Hidden": outs["Hidden"], "XX": [LoDValue(xx, l)]}


def _fused_emb_fc_lstm_infer(op, block):
    emb = in_desc(op, block, "Embeddings")
    ids = in_desc(op, block, "Ids")
    if emb is None or ids is None:
        return
    h = emb.shape[1] // 4
    set_output(block, op, "Hidden", [-1, h], emb.dtype, lod_level=1)
    set_output(block, op, "Cell", [-1, h], emb.dtype, lod_level=1)
    if op.output("XX") and op.output("XX")[0]:
        set_output(block, op, "XX", [-1, 4 * h], emb.dtype, lod_level=1)


@register_op("fused_embedding_fc_lstm", infer_shape=_fused_emb_fc_lstm_infer,
             diff_inputs=["Embeddings", "WeightH", "Bias", "H0", "C0"])
def _fused_embedding_fc_lstm(ctx, ins, attrs):
    """Embedding lookup + fc + LSTM in one op (reference:
    operators/fused/fused_embedding_fc_lstm_op.cc): Embeddings is the
    [vocab, 4H] table pre-multiplied with the gate projection, so the
    input half of the gates is a pure gather; the recurrence reuses the
    lstm scan (gate order [c-candidate, i, f, o], fusion_lstm_op.h)."""
    ids_v = ins["Ids"][0]
    ids = data(ids_v)
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]                       # [N, T]
    l = lengths(ids_v)
    if l is None:
        l = jnp.full((ids.shape[0],), ids.shape[1], dtype=jnp.int32)
    emb = data(ins["Embeddings"][0])            # [V, 4H]
    xx = emb[ids.astype(jnp.int32)]             # [N, T, 4H]
    ins2 = dict(ins)
    ins2["Input"] = [LoDValue(xx, l)]
    ins2["Weight"] = ins["WeightH"]
    hs, cs, gates, preact, l = _lstm_core(ctx, ins2, attrs)
    return {
        "Hidden": [LoDValue(hs, l)],
        "Cell": [LoDValue(cs, l)],
        "XX": [LoDValue(xx, l)],
    }


def _attention_lstm_infer(op, block):
    x = in_desc(op, block, "X")
    w = in_desc(op, block, "LSTMWeight")
    if x is None or w is None:
        return
    d = w.shape[1] // 4
    set_output(block, op, "Hidden", [-1, d], x.dtype, lod_level=1)
    set_output(block, op, "Cell", [-1, d], x.dtype, lod_level=1)
    for slot, width in (("AttentionedX", 1), ("AttentionFCOut", 1),
                        ("LSTMX", x.shape[-1]), ("LSTMOUT", 4 * d)):
        if op.output(slot) and op.output(slot)[0]:
            set_output(block, op, slot, [-1, width], x.dtype, lod_level=0)


@register_op("attention_lstm", infer_shape=_attention_lstm_infer,
             diff_inputs=["X", "AttentionWeight", "AttentionBias",
                          "AttentionScalar", "AttentionScalarBias",
                          "LSTMWeight", "LSTMBias", "H0", "C0"])
def _attention_lstm(ctx, ins, attrs):
    """Attention LSTM (reference: operators/attention_lstm_op.cc).  Per
    step: score every token with relu(x@w_x + c_prev@w_c [, *scalar +
    scalar_bias relu'd again]), softmax over the sequence, sum-pool the
    attended tokens into lstm_x, then one LSTM step whose 4D gate buffer
    is ordered [forget, input, output, candidate] (the reference doc's
    concat[forget, input, output, tilde]; note this differs from lstm_op's
    [c, i, f, o]).  LSTMWeight rows are [hidden (D), input (M)]."""
    x = ins["X"][0]
    d = data(x)                                  # [N, T, M]
    l = lengths(x)
    if l is None:
        l = jnp.full((d.shape[0],), d.shape[1], dtype=jnp.int32)
    n, t, m = d.shape
    aw = data(ins["AttentionWeight"][0]).reshape(-1)   # [(M+D)]
    ab = (data(ins["AttentionBias"][0]).reshape(())
          if ins.get("AttentionBias") and ins["AttentionBias"][0] is not None
          else None)
    a_scal = (data(ins["AttentionScalar"][0]).reshape(())
              if ins.get("AttentionScalar")
              and ins["AttentionScalar"][0] is not None else None)
    a_scal_b = (data(ins["AttentionScalarBias"][0]).reshape(())
                if ins.get("AttentionScalarBias")
                and ins["AttentionScalarBias"][0] is not None else None)
    lw = data(ins["LSTMWeight"][0])              # [(D+M), 4D]
    lb = data(ins["LSTMBias"][0]).reshape(-1)    # [4D]
    dim = lw.shape[1] // 4
    act_gate = _act(attrs.get("gate_activation", "sigmoid"))
    act_cell = _act(attrs.get("cell_activation", "tanh"))
    act_cand = _act(attrs.get("candidate_activation", "tanh"))

    # x(T x M) @ atten_w[:M] (+ bias, relu'd later with the cell part)
    atted_x = jnp.einsum("ntm,m->nt", d, aw[:m])
    if ab is not None:
        atted_x = atted_x + ab
    mask = jnp.arange(t)[None, :] < l[:, None]   # [N, T]
    h0 = (data(ins["H0"][0])
          if ins.get("H0") and ins["H0"][0] is not None
          else jnp.zeros((n, dim), d.dtype))
    c0 = data(ins["C0"][0])                      # required by the reference

    def step(carry, _x_t, step_mask):
        h_prev, c_prev = carry
        # 1. attention: score depends on the previous cell state
        pcb = c_prev @ aw[m:]                    # [N]
        score = jax.nn.relu(atted_x + pcb[:, None])
        if a_scal is not None:
            score = score * a_scal
            if a_scal_b is not None:
                score = score + a_scal_b
            score = jax.nn.relu(score)
        score = jnp.where(mask, score, -jnp.inf)
        alpha = jax.nn.softmax(score, axis=1)    # [N, T]
        # a zero-length row has an all -inf score -> softmax NaN; zero it
        # (the mf masking below cannot scrub it: NaN * 0 = NaN)
        alpha = jnp.where(mask.any(axis=1, keepdims=True), alpha, 0.0)
        lstm_x = jnp.einsum("nt,ntm->nm", alpha, d)
        # 2. LSTM step, [f, i, o, cand] gate order
        gates = lstm_x @ lw[dim:] + h_prev @ lw[:dim] + lb
        f = act_gate(gates[:, :dim])
        i = act_gate(gates[:, dim:2 * dim])
        o = act_gate(gates[:, 2 * dim:3 * dim])
        cand = act_cand(gates[:, 3 * dim:])
        c = f * c_prev + i * cand
        h = o * act_cell(c)
        mf = step_mask.astype(d.dtype)       # [N, 1]
        h_new = h * mf + h_prev * (1 - mf)
        c_new = c * mf + c_prev * (1 - mf)
        return (h_new, c_new), (h * mf, c * mf, alpha * mf, lstm_x * mf,
                                gates * mf)

    (_, _), (hs, cs, alphas, lstm_xs, lstm_outs) = _scan_time_major(
        step, (h0, c0), jnp.zeros((n, t, 0), d.dtype), mask
    )
    return {
        "Hidden": [LoDValue(hs, l)],
        "Cell": [LoDValue(cs, l)],
        "AttentionedX": [atted_x.reshape(n * t, 1)],
        "AttentionFCOut": [alphas[:, -1].reshape(-1, 1)],
        "LSTMX": [lstm_xs[:, -1]],
        "LSTMOUT": [lstm_outs[:, -1]],
    }
