"""Vision ops: RoI pooling/align, grid sampling, affine ops, YOLOv3 loss.

Reference kernels: operators/roi_pool_op.*, roi_align_op.*, psroi_pool_op.*,
grid_sampler_op.* (cuDNN spatial sampler), affine_grid_op.*,
affine_channel_op.*, yolov3_loss_op.h.

TPU-native notes: RoI ops vectorize over a padded per-image RoI tensor
(LoDValue [N, R, 4]) with vmap instead of the reference's per-RoI CUDA
threads; grid sampling is gather + bilinear weights, which XLA fuses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.lod import LoDValue
from ..core.proto import DataType
from ..core.registry import register_op
from .common import data, in_desc, lengths, set_output


def _rois_batched(rois_val, batch):
    """RoIs as [N, R, 4] + validity [N, R] from a LoDValue (or dense)."""
    d = data(rois_val)
    l = lengths(rois_val)
    if d.ndim == 2:
        d = jnp.broadcast_to(d[None], (batch,) + d.shape)
    if l is None:
        l = jnp.full((d.shape[0],), d.shape[1], dtype=jnp.int32)
    valid = jnp.arange(d.shape[1])[None, :] < l[:, None]
    return d, valid, l


def _bilinear_sample(feat, ys, xs):
    """feat [C, H, W]; ys/xs arbitrary shape -> [C, *shape] bilinear values
    (zero padding outside)."""
    C, H, W = feat.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    out = 0.0
    for dy, wy in ((0, 1.0 - wy1), (1, wy1)):
        for dx, wx in ((0, 1.0 - wx1), (1, wx1)):
            yi = (y0 + dy).astype(jnp.int32)
            xi = (x0 + dx).astype(jnp.int32)
            ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yc = jnp.clip(yi, 0, H - 1)
            xc = jnp.clip(xi, 0, W - 1)
            vals = feat[:, yc, xc]  # [C, *shape]
            out = out + vals * (wy * wx * ok)[None]
    return out


def _roi_out_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    ph = op.attr("pooled_height", 1)
    pw = op.attr("pooled_width", 1)
    set_output(block, op, "Out", [-1, x.shape[1], ph, pw], x.dtype)


@register_op("roi_pool", infer_shape=_roi_out_infer, diff_inputs=["X"])
def _roi_pool(ctx, ins, attrs):
    """Max pooling inside each RoI bin (reference: roi_pool_op.h)."""
    x = data(ins["X"][0])  # [N, C, H, W]
    rois, valid, l = _rois_batched(ins["ROIs"][0], x.shape[0])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    spatial_scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape
    R = rois.shape[1]

    def one_roi(feat, roi):
        x1, y1, x2, y2 = [jnp.round(roi[i] * spatial_scale) for i in range(4)]
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # dense sample grid (4x4 per bin) + max — static-shape stand-in for
        # the reference's exact integer bin walk
        sy = y1 + (jnp.arange(ph * 4) + 0.5) * bin_h / 4.0
        sx = x1 + (jnp.arange(pw * 4) + 0.5) * bin_w / 4.0
        yi = jnp.clip(sy.astype(jnp.int32), 0, H - 1)
        xi = jnp.clip(sx.astype(jnp.int32), 0, W - 1)
        patch = feat[:, yi][:, :, xi]  # [C, ph*4, pw*4]
        patch = patch.reshape(C, ph, 4, pw, 4)
        return jnp.max(patch, axis=(2, 4))

    def per_image(feat, img_rois):
        return jax.vmap(lambda r: one_roi(feat, r))(img_rois)

    out = jax.vmap(per_image)(x, rois)  # [N, R, C, ph, pw]
    out = out * valid[..., None, None, None]
    return {"Out": [out.reshape(N * R, C, ph, pw)]}


@register_op("roi_align", infer_shape=_roi_out_infer, diff_inputs=["X"])
def _roi_align(ctx, ins, attrs):
    """Average of bilinear samples per bin (reference: roi_align_op.h)."""
    x = data(ins["X"][0])
    rois, valid, l = _rois_batched(ins["ROIs"][0], x.shape[0])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    spatial_scale = float(attrs.get("spatial_scale", 1.0))
    sampling_ratio = int(attrs.get("sampling_ratio", -1))
    s = sampling_ratio if sampling_ratio > 0 else 2
    N, C, H, W = x.shape
    R = rois.shape[1]

    def one_roi(feat, roi):
        x1, y1, x2, y2 = [roi[i] * spatial_scale for i in range(4)]
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        sy = y1 + (jnp.arange(ph * s) + 0.5) * bin_h / s
        sx = x1 + (jnp.arange(pw * s) + 0.5) * bin_w / s
        yg, xg = jnp.meshgrid(sy, sx, indexing="ij")
        vals = _bilinear_sample(feat, yg - 0.5, xg - 0.5)  # [C, ph*s, pw*s]
        vals = vals.reshape(C, ph, s, pw, s)
        return jnp.mean(vals, axis=(2, 4))

    def per_image(feat, img_rois):
        return jax.vmap(lambda r: one_roi(feat, r))(img_rois)

    out = jax.vmap(per_image)(x, rois)
    out = out * valid[..., None, None, None]
    return {"Out": [out.reshape(N * R, C, ph, pw)]}


def _grid_sampler_infer(op, block):
    x = in_desc(op, block, "X")
    g = in_desc(op, block, "Grid")
    if x is None or g is None:
        return
    set_output(block, op, "Output",
               [x.shape[0], x.shape[1], g.shape[1], g.shape[2]], x.dtype)


@register_op("grid_sampler", infer_shape=_grid_sampler_infer,
             diff_inputs=["X", "Grid"])
def _grid_sampler(ctx, ins, attrs):
    """Bilinear sampling on a normalized [-1, 1] grid
    (reference: grid_sampler_op.* via cuDNN spatial transformer)."""
    x = data(ins["X"][0])  # [N, C, H, W]
    grid = data(ins["Grid"][0])  # [N, Ho, Wo, 2] (x, y) in [-1, 1]
    N, C, H, W = x.shape
    xs = (grid[..., 0] + 1.0) * (W - 1) / 2.0
    ys = (grid[..., 1] + 1.0) * (H - 1) / 2.0
    out = jax.vmap(_bilinear_sample)(x, ys, xs)  # [N, C, Ho, Wo]
    return {"Output": [out]}


def _affine_grid_infer(op, block):
    t = in_desc(op, block, "Theta")
    if t is None:
        return
    shape = op.attr("output_shape", [])
    if shape:
        set_output(block, op, "Output", [shape[0], shape[2], shape[3], 2], t.dtype)
    else:
        set_output(block, op, "Output", [-1, -1, -1, 2], t.dtype)


@register_op("affine_grid", infer_shape=_affine_grid_infer, diff_inputs=["Theta"])
def _affine_grid(ctx, ins, attrs):
    """2x3 affine -> sampling grid (reference: affine_grid_op.*)."""
    theta = data(ins["Theta"][0])  # [N, 2, 3]
    out_shape = ins.get("OutputShape", [None])[0]
    if out_shape is not None:
        v = data(out_shape)
        if isinstance(v, jax.core.Tracer):
            raise ValueError(
                "affine_grid: OutputShape must be a compile-time constant "
                "under XLA (it determines the result shape); pass "
                "out_shape as a static list instead of a traced tensor"
            )
        shape = [int(s) for s in np.asarray(v)]
    else:
        shape = [int(v) for v in attrs["output_shape"]]
    N, C, H, W = shape
    ys = jnp.linspace(-1.0, 1.0, H)
    xs = jnp.linspace(-1.0, 1.0, W)
    xg, yg = jnp.meshgrid(xs, ys)  # [H, W]
    ones = jnp.ones_like(xg)
    base = jnp.stack([xg, yg, ones], axis=-1)  # [H, W, 3]
    out = jnp.einsum("hwk,njk->nhwj", base, theta)  # [N, H, W, 2]
    return {"Output": [out]}


@register_op("affine_channel", infer_shape=lambda op, block: set_output(
    block, op, "Out",
    list(in_desc(op, block, "X").shape) if in_desc(op, block, "X") else [],
    in_desc(op, block, "X").dtype if in_desc(op, block, "X") else DataType.FP32,
), diff_inputs=["X", "Scale", "Bias"])
def _affine_channel(ctx, ins, attrs):
    """Per-channel scale+bias (reference: affine_channel_op.cc)."""
    x = data(ins["X"][0])
    scale = data(ins["Scale"][0]).reshape(-1)
    bias = data(ins["Bias"][0]).reshape(-1)
    layout = attrs.get("data_layout", "NCHW")
    if layout == "NCHW":
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        shape = (1,) * (x.ndim - 1) + (-1,)
    return {"Out": [x * scale.reshape(shape) + bias.reshape(shape)]}


def _box_clip_infer(op, block):
    x = in_desc(op, block, "Input")
    if x is None:
        return
    set_output(block, op, "Output", list(x.shape), x.dtype, lod_level=x.lod_level)


@register_op("box_clip", infer_shape=_box_clip_infer, diff_inputs=["Input"])
def _box_clip(ctx, ins, attrs):
    """Clip boxes to image bounds (reference: detection/box_clip_op.h)."""
    x = ins["Input"][0]
    d = data(x)
    im = data(ins["ImInfo"][0])  # [N, 3] (h, w, scale)
    hmax = im[:, 0] - 1.0
    wmax = im[:, 1] - 1.0
    shape = (-1,) + (1,) * (d.ndim - 1)
    xs = jnp.clip(d[..., 0::2], 0.0, wmax.reshape(shape))
    ys = jnp.clip(d[..., 1::2], 0.0, hmax.reshape(shape))
    out = jnp.stack(
        [xs[..., 0], ys[..., 0], xs[..., 1], ys[..., 1]], axis=-1
    )
    if isinstance(x, LoDValue):
        out = LoDValue(out, x.lengths)
    return {"Output": [out]}


# ---------------------------------------------------------------------------
# yolov3_loss
# ---------------------------------------------------------------------------
def _yolo_infer(op, block):
    set_output(block, op, "Loss", [-1], DataType.FP32)


@register_op("yolov3_loss", infer_shape=_yolo_infer, diff_inputs=["X"])
def _yolov3_loss(ctx, ins, attrs):
    """YOLOv3 training loss (reference: yolov3_loss_op.h CalcYolov3Loss):
    coord (sigmoid xy + raw wh) + objectness + class BCE, with gt boxes
    assigned to the best-IoU anchor at their cell."""
    x = data(ins["X"][0])  # [N, A*(5+cls), H, W]
    gt_box = data(ins["GTBox"][0])  # [N, B, 4] (cx, cy, w, h) normalized
    gt_label = data(ins["GTLabel"][0]).astype(jnp.int32)  # [N, B]
    anchors = [float(a) for a in attrs["anchors"]]
    class_num = int(attrs["class_num"])
    ignore_thresh = float(attrs.get("ignore_thresh", 0.7))
    downsample = int(attrs.get("downsample_ratio", attrs.get("downsample", 32)))
    N, _, H, W = x.shape
    A = len(anchors) // 2
    anc = jnp.asarray(anchors, dtype=x.dtype).reshape(A, 2)  # (w, h) px
    input_size = downsample * H

    x = x.reshape(N, A, 5 + class_num, H, W)
    px = jax.nn.sigmoid(x[:, :, 0])  # [N, A, H, W]
    py = jax.nn.sigmoid(x[:, :, 1])
    pw = x[:, :, 2]
    ph = x[:, :, 3]
    pobj = x[:, :, 4]
    pcls = x[:, :, 5:]  # [N, A, cls, H, W]

    B = gt_box.shape[1]
    gt_valid = (gt_box[..., 2] > 0) & (gt_box[..., 3] > 0)  # [N, B]
    gx = gt_box[..., 0] * W  # in grid units
    gy = gt_box[..., 1] * H
    gw = gt_box[..., 2] * input_size  # px
    gh = gt_box[..., 3] * input_size
    gi = jnp.clip(gx.astype(jnp.int32), 0, W - 1)  # [N, B]
    gj = jnp.clip(gy.astype(jnp.int32), 0, H - 1)

    # best anchor per gt by wh-IoU
    inter = jnp.minimum(gw[..., None], anc[None, None, :, 0]) * jnp.minimum(
        gh[..., None], anc[None, None, :, 1]
    )
    union = gw[..., None] * gh[..., None] + (anc[:, 0] * anc[:, 1])[None, None] - inter
    an_iou = inter / jnp.maximum(union, 1e-10)
    best_a = jnp.argmax(an_iou, axis=-1)  # [N, B]

    # per-gt predicted values at (best_a, gj, gi)
    def gather(nawh):  # [N, A, H, W] -> [N, B]
        def per(nv, a, j, i):
            return nv[a, j, i]

        return jax.vmap(
            lambda nv, aa, jj, ii: jax.vmap(per, in_axes=(None, 0, 0, 0))(
                nv, aa, jj, ii
            )
        )(nawh, best_a, gj, gi)

    tx = gx - jnp.floor(gx)
    ty = gy - jnp.floor(gy)
    tw = jnp.log(jnp.maximum(gw / anc[best_a, 0], 1e-10))
    th = jnp.log(jnp.maximum(gh / anc[best_a, 1], 1e-10))
    scale = 2.0 - gt_box[..., 2] * gt_box[..., 3]  # small boxes weigh more

    vmask = gt_valid.astype(x.dtype)
    loss_xy = jnp.sum(
        (_bce(gather(px), tx) + _bce(gather(py), ty)) * scale * vmask,
        axis=1,
    )
    loss_wh = jnp.sum(
        ((gather(pw) - tw) ** 2 + (gather(ph) - th) ** 2) * 0.5 * scale * vmask,
        axis=1,
    )

    # objectness: positive at assigned cells; negatives are ignored when the
    # predicted box's best IoU against any gt exceeds ignore_thresh
    # (reference: yolov3_loss_op.h CalcObjnessLoss + the ignore mask sweep)
    obj_target = jnp.zeros((N, A, H, W), dtype=x.dtype)
    pos_idx = (jnp.arange(N)[:, None], best_a, gj, gi)
    obj_target = obj_target.at[pos_idx].max(vmask)

    # predicted boxes for every cell, normalized to [0, 1]
    grid_x = jnp.arange(W, dtype=x.dtype)[None, None, None, :]
    grid_y = jnp.arange(H, dtype=x.dtype)[None, None, :, None]
    pred_cx = (px + grid_x) / W
    pred_cy = (py + grid_y) / H
    pred_w = jnp.exp(pw) * anc[None, :, 0, None, None] / input_size
    pred_h = jnp.exp(ph) * anc[None, :, 1, None, None] / input_size
    # IoU of every predicted box vs every gt (center-size form)
    px1 = pred_cx - pred_w / 2.0
    py1 = pred_cy - pred_h / 2.0
    px2 = pred_cx + pred_w / 2.0
    py2 = pred_cy + pred_h / 2.0
    gx1 = (gt_box[..., 0] - gt_box[..., 2] / 2.0)[:, None, None, None, :]
    gy1 = (gt_box[..., 1] - gt_box[..., 3] / 2.0)[:, None, None, None, :]
    gx2 = (gt_box[..., 0] + gt_box[..., 2] / 2.0)[:, None, None, None, :]
    gy2 = (gt_box[..., 1] + gt_box[..., 3] / 2.0)[:, None, None, None, :]
    iw = jnp.maximum(
        jnp.minimum(px2[..., None], gx2) - jnp.maximum(px1[..., None], gx1), 0.0
    )
    ih = jnp.maximum(
        jnp.minimum(py2[..., None], gy2) - jnp.maximum(py1[..., None], gy1), 0.0
    )
    inter_p = iw * ih
    area_p = (pred_w * pred_h)[..., None]
    area_g = (gt_box[..., 2] * gt_box[..., 3])[:, None, None, None, :]
    iou_pg = inter_p / jnp.maximum(area_p + area_g - inter_p, 1e-10)
    iou_pg = jnp.where(gt_valid[:, None, None, None, :], iou_pg, 0.0)
    best_iou = jnp.max(iou_pg, axis=-1)  # [N, A, H, W]

    noobj_weight = ((1.0 - obj_target) * (best_iou <= ignore_thresh)).astype(
        x.dtype
    )
    loss_obj = jnp.sum(
        _bce(jax.nn.sigmoid(pobj), obj_target) * (obj_target + noobj_weight),
        axis=(1, 2, 3),
    )

    cls_onehot = jax.nn.one_hot(gt_label, class_num, dtype=x.dtype)  # [N,B,cls]
    pcls_at = jax.vmap(
        lambda nv, aa, jj, ii: jax.vmap(
            lambda a, j, i: nv[a, :, j, i], in_axes=(0, 0, 0)
        )(aa, jj, ii)
    )(pcls, best_a, gj, gi)  # [N, B, cls]
    loss_cls = jnp.sum(
        jnp.sum(_bce(jax.nn.sigmoid(pcls_at), cls_onehot), axis=-1) * vmask,
        axis=1,
    )
    return {"Loss": [loss_xy + loss_wh + loss_obj + loss_cls]}


def _bce(p, t):
    p = jnp.clip(p, 1e-7, 1.0 - 1e-7)
    return -(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p))


# ---------------------------------------------------------------------------
# psroi_pool
# ---------------------------------------------------------------------------
def _psroi_pool_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    ph = op.attr("pooled_height", 1)
    pw = op.attr("pooled_width", 1)
    oc = op.attr("output_channels", 1)
    set_output(block, op, "Out", [-1, oc, ph, pw], x.dtype)


@register_op("psroi_pool", infer_shape=_psroi_pool_infer, diff_inputs=["X"])
def _psroi_pool(ctx, ins, attrs):
    """Position-sensitive RoI average pooling (reference: psroi_pool_op.h):
    output channel c's bin (i, j) averages input channel
    (c*ph + i)*pw + j over the bin's region.  Bin bounds are data-dependent,
    so each bin is a masked mean over the full H x W map — O(HW) per bin but
    fully static and MXU/VPU-fusible."""
    x = data(ins["X"][0])  # [N, C_in, H, W], C_in = oc*ph*pw
    rois, valid, _ = _rois_batched(ins["ROIs"][0], x.shape[0])
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    oc = int(attrs.get("output_channels", 1))
    spatial_scale = float(attrs.get("spatial_scale", 1.0))
    N, C_in, H, W = x.shape
    R = rois.shape[1]
    hg = jnp.arange(H, dtype=x.dtype)
    wg = jnp.arange(W, dtype=x.dtype)

    def one_roi(feat, roi):
        # psroi_pool_op.h: rounded roi corners, +1 on the end corner
        x1 = jnp.round(roi[0]) * spatial_scale
        y1 = jnp.round(roi[1]) * spatial_scale
        x2 = (jnp.round(roi[2]) + 1.0) * spatial_scale
        y2 = (jnp.round(roi[3]) + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h = rh / ph
        bin_w = rw / pw
        i = jnp.arange(ph, dtype=x.dtype)
        j = jnp.arange(pw, dtype=x.dtype)
        hstart = jnp.clip(jnp.floor(i * bin_h + y1), 0, H)      # [ph]
        hend = jnp.clip(jnp.ceil((i + 1) * bin_h + y1), 0, H)
        wstart = jnp.clip(jnp.floor(j * bin_w + x1), 0, W)
        wend = jnp.clip(jnp.ceil((j + 1) * bin_w + x1), 0, W)
        hmask = (
            (hg[None, :] >= hstart[:, None]) & (hg[None, :] < hend[:, None])
        ).astype(x.dtype)  # [ph, H]
        wmask = (
            (wg[None, :] >= wstart[:, None]) & (wg[None, :] < wend[:, None])
        ).astype(x.dtype)  # [pw, W]
        # feat regrouped: [oc, ph, pw, H, W]
        fr = feat.reshape(oc, ph, pw, H, W)
        sums = jnp.einsum("cijhw,ih,jw->cij", fr, hmask, wmask)
        counts = (
            jnp.sum(hmask, axis=1)[:, None] * jnp.sum(wmask, axis=1)[None, :]
        )
        return jnp.where(counts[None] > 0, sums / jnp.maximum(counts, 1.0),
                         0.0)

    def per_image(feat, img_rois):
        return jax.vmap(lambda r: one_roi(feat, r))(img_rois)

    out = jax.vmap(per_image)(x, rois)  # [N, R, oc, ph, pw]
    out = out * valid[..., None, None, None]
    return {"Out": [out.reshape(N * R, oc, ph, pw)]}


# ---------------------------------------------------------------------------
# roi_perspective_transform
# ---------------------------------------------------------------------------
def _roi_perspective_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    th = op.attr("transformed_height", 1)
    tw = op.attr("transformed_width", 1)
    set_output(block, op, "Out", [-1, x.shape[1], th, tw], x.dtype)


def _in_quad(px, py, qx, qy):
    """Ray-crossing point-in-quadrilateral test, with the reference's
    on-edge tolerance (roi_perspective_transform_op.cc in_quad: a point
    within 1e-4 of any edge segment counts as inside)."""
    inside = jnp.zeros(jnp.shape(px), dtype=bool)
    on_edge = jnp.zeros(jnp.shape(px), dtype=bool)
    for i in range(4):
        xs, ys = qx[i], qy[i]
        xe, ye = qx[(i + 1) % 4], qy[(i + 1) % 4]
        # point-to-segment distance for the boundary tolerance
        dx, dy = xe - xs, ye - ys
        seg2 = dx * dx + dy * dy
        t = jnp.clip(
            ((px - xs) * dx + (py - ys) * dy) / jnp.maximum(seg2, 1e-12),
            0.0, 1.0,
        )
        dist2 = (px - (xs + t * dx)) ** 2 + (py - (ys + t * dy)) ** 2
        on_edge = on_edge | (dist2 < 1e-6)
        flat = jnp.abs(ys - ye) < 1e-4
        in_y = (py >= jnp.minimum(ys, ye) - 1e-4) & (
            py <= jnp.maximum(ys, ye) + 1e-4
        )
        ix = (py - ys) * (xe - xs) / jnp.where(flat, 1.0, ye - ys) + xs
        cross = (~flat) & in_y & (ix > px)
        inside = inside ^ cross
    return inside | on_edge


@register_op("roi_perspective_transform",
             infer_shape=_roi_perspective_infer, diff_inputs=["X"])
def _roi_perspective_transform(ctx, ins, attrs):
    """Perspective-warp quadrilateral RoIs to a rectangle (reference:
    detection/roi_perspective_transform_op.cc): per RoI of 8 coords
    (x0,y0..x3,y3), build the 3x3 homography from the output rect to the
    quad (get_transform_matrix), bilinear-sample inside the quad, zero
    outside."""
    x = data(ins["X"][0])  # [N, C, H, W]
    rois, valid, _ = _rois_batched(ins["ROIs"][0], x.shape[0])  # [N, R, 8]
    th = int(attrs.get("transformed_height", 1))
    tw = int(attrs.get("transformed_width", 1))
    spatial_scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape
    R = rois.shape[1]

    def one_roi(feat, roi):
        qx = [roi[2 * k] * spatial_scale for k in range(4)]
        qy = [roi[2 * k + 1] * spatial_scale for k in range(4)]
        x0, x1, x2, x3 = qx
        y0, y1, y2, y3 = qy
        len1 = jnp.sqrt((x0 - x1) ** 2 + (y0 - y1) ** 2)
        len2 = jnp.sqrt((x1 - x2) ** 2 + (y1 - y2) ** 2)
        len3 = jnp.sqrt((x2 - x3) ** 2 + (y2 - y3) ** 2)
        len4 = jnp.sqrt((x3 - x0) ** 2 + (y3 - y0) ** 2)
        est_h = (len2 + len4) / 2.0
        est_w = (len1 + len3) / 2.0
        # reference clamps: normalized sizes never below 2, so the (n-1)
        # divisors below are always >= 1 (roi_perspective_transform_op.cc
        # get_transform_matrix)
        nh = float(max(th, 2))
        nw = jnp.clip(
            jnp.round(est_w * (nh - 1) / jnp.maximum(est_h, 1e-6)) + 1.0,
            2.0, float(max(tw, 2)),
        )
        dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
        dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
        den = dx1 * dy2 - dx2 * dy1
        den = jnp.where(jnp.abs(den) < 1e-10, 1e-10, den)
        a31 = (dx3 * dy2 - dx2 * dy3) / den / jnp.maximum(nw - 1, 1e-6)
        a32 = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
        a21 = (y1 - y0 + a31 * (nw - 1) * y1) / jnp.maximum(nw - 1, 1e-6)
        a22 = (y3 - y0 + a32 * (nh - 1) * y3) / (nh - 1)
        a11 = (x1 - x0 + a31 * (nw - 1) * x1) / jnp.maximum(nw - 1, 1e-6)
        a12 = (x3 - x0 + a32 * (nh - 1) * x3) / (nh - 1)

        ow, oh = jnp.meshgrid(
            jnp.arange(tw, dtype=x.dtype), jnp.arange(th, dtype=x.dtype)
        )  # [th, tw]
        u = a11 * ow + a12 * oh + x0
        v = a21 * ow + a22 * oh + y0
        w_ = a31 * ow + a32 * oh + 1.0
        in_w = u / jnp.where(jnp.abs(w_) < 1e-10, 1e-10, w_)
        in_h = v / jnp.where(jnp.abs(w_) < 1e-10, 1e-10, w_)
        ok = (
            _in_quad(in_w, in_h, qx, qy)
            & (in_w >= -0.5) & (in_w <= W - 0.5)
            & (in_h >= -0.5) & (in_h <= H - 0.5)
        )
        vals = _bilinear_sample(
            feat, jnp.clip(in_h, 0, H - 1), jnp.clip(in_w, 0, W - 1)
        )  # [C, th, tw]
        return vals * ok[None]

    def per_image(feat, img_rois):
        return jax.vmap(lambda r: one_roi(feat, r))(img_rois)

    out = jax.vmap(per_image)(x, rois)  # [N, R, C, th, tw]
    out = out * valid[..., None, None, None]
    return {"Out": [out.reshape(N * R, C, th, tw)]}
