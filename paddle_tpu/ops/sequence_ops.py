"""Sequence (LoD) ops — segment-aware lowerings over padded LoDValues.

Reference kernels: paddle/fluid/operators/sequence_ops/ (26 ops) plus
lod_reset, im2sequence, row_conv — all of which shuffle ragged token-major
buffers imperatively (operators/math/sequence2batch.h, sequence_pooling.cc).
XLA wants static shapes, so here every sequence op works on the padded
LoDValue layout (data [N, T, ...], lengths [N]) with masking; XLA fuses the
masks into the surrounding compute, and there is no layout shuffle at all.

Desc-level shapes stay token-major fluid style ([-1, F], lod_level=1) so
programs print like the reference's.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import wide_int
from ..core.lod import LoDValue
from ..core.proto import DataType
from ..core.registry import register_op
from .common import ACTS, data, in_desc, lengths, same_shape, set_output, wrap_lod


def _as_lod(x):
    """(padded data [N, T, ...], lengths [N]) view of a runtime value.
    Dense inputs are treated as N length-T sequences."""
    d = data(x)
    l = lengths(x)
    if l is None:
        l = jnp.full((d.shape[0],), d.shape[1] if d.ndim > 1 else 1, dtype=jnp.int32)
    return d, l


from .common import feature_mask as _fmask  # noqa: E402
from .common import time_mask as _time_mask  # noqa: E402


# ---------------------------------------------------------------------------
# sequence_pool + first/last step
# ---------------------------------------------------------------------------
def _seq_pool_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", list(x.shape), x.dtype, lod_level=0)
    names = op.output("MaxIndex")
    if names and names[0]:
        set_output(block, op, "MaxIndex", list(x.shape), DataType.INT32, lod_level=0)


@register_op("sequence_pool", infer_shape=_seq_pool_infer, diff_inputs=["X"])
def _sequence_pool(ctx, ins, attrs):
    """Pool each sequence to one vector (reference:
    operators/sequence_ops/sequence_pool_op.cc, math/sequence_pooling.cc).
    pooltype in {AVERAGE, SUM, SQRT, MAX, LAST, FIRST}."""
    x = ins["X"][0]
    d, l = _as_lod(x)
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    m = _fmask(d, l)
    lf = l.astype(d.dtype).reshape((-1,) + (1,) * (d.ndim - 2))
    lf = jnp.maximum(lf, 1)
    max_index = None
    if ptype == "SUM":
        out = jnp.sum(d * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(d * m, axis=1) / lf
    elif ptype == "SQRT":
        out = jnp.sum(d * m, axis=1) / jnp.sqrt(lf)
    elif ptype == "MAX":
        neg = jnp.full_like(d, -jnp.inf) if jnp.issubdtype(d.dtype, jnp.floating) else jnp.full_like(d, jnp.iinfo(d.dtype).min)
        masked = jnp.where(m, d, neg)
        out = jnp.max(masked, axis=1)
        max_index = jnp.argmax(masked, axis=1).astype(jnp.int32)
        # all-pad rows pool to 0 like the reference's empty-seq behavior
        out = jnp.where(l.reshape(lf.shape) > 0, out, jnp.zeros_like(out))
    elif ptype == "LAST":
        idx = jnp.maximum(l - 1, 0)
        out = jnp.take_along_axis(
            d, idx.reshape((-1, 1) + (1,) * (d.ndim - 2)).astype(jnp.int32), axis=1
        )[:, 0]
    elif ptype == "FIRST":
        out = d[:, 0]
    else:
        raise NotImplementedError(f"sequence_pool pooltype {ptype}")
    outs = {"Out": [out]}
    if max_index is not None:
        outs["MaxIndex"] = [max_index]
    return outs


# ---------------------------------------------------------------------------
# sequence_softmax
# ---------------------------------------------------------------------------
@register_op("sequence_softmax", infer_shape=same_shape(), diff_inputs=["X"])
def _sequence_softmax(ctx, ins, attrs):
    """Softmax within each sequence over the time axis (reference:
    operators/sequence_ops/sequence_softmax_op.cc)."""
    x = ins["X"][0]
    d, l = _as_lod(x)
    m = _fmask(d, l)
    neg = jnp.where(m, d, -jnp.inf)
    # softmax over time (axis=1), invalid slots exactly 0
    mx = jnp.max(neg, axis=1, keepdims=True)
    mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
    e = jnp.exp(d - mx) * m.astype(d.dtype)
    s = jnp.sum(e, axis=1, keepdims=True)
    out = e / jnp.maximum(s, 1e-30)
    return {"Out": [wrap_lod(x, out)]}


# ---------------------------------------------------------------------------
# sequence_expand / expand_as
# ---------------------------------------------------------------------------
def _seq_expand_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", list(x.shape), x.dtype, lod_level=1)


@register_op("sequence_expand", infer_shape=_seq_expand_infer, diff_inputs=["X"])
def _sequence_expand(ctx, ins, attrs):
    """Expand X to Y's sequence structure (reference:
    operators/sequence_ops/sequence_expand_op.cc).  The padded lowering
    supports the dominant use (a dense row — or a length-1 sequence — per
    target sequence, broadcast over the target lengths); ragged
    sequence-count expansion has no static-shape equivalent."""
    x, y = ins["X"][0], ins["Y"][0]
    yd, yl = _as_lod(y)
    xd = data(x)
    if isinstance(x, LoDValue):
        if xd.shape[1] == 1:
            xd = xd[:, 0]
        else:
            raise NotImplementedError(
                "sequence_expand of multi-token sequences has data-dependent "
                "output sequence counts; restructure with sequence_expand_as"
            )
    # xd: [N, F...] -> [N, Ty, F...], masked by y lengths
    out = jnp.broadcast_to(
        xd[:, None], (xd.shape[0], yd.shape[1]) + xd.shape[1:]
    )
    out = out * _fmask(out, yl).astype(out.dtype)
    return {"Out": [LoDValue(out, yl)]}


@register_op("sequence_expand_as", infer_shape=_seq_expand_infer, diff_inputs=["X"])
def _sequence_expand_as(ctx, ins, attrs):
    """Each row of X becomes a sequence of Y's length (reference:
    operators/sequence_ops/sequence_expand_as_op.cc)."""
    x, y = ins["X"][0], ins["Y"][0]
    yd, yl = _as_lod(y)
    xd = data(x)
    if isinstance(x, LoDValue) and xd.shape[1] == 1:
        xd = xd[:, 0]
    out = jnp.broadcast_to(xd[:, None], (xd.shape[0], yd.shape[1]) + xd.shape[1:])
    out = out * _fmask(out, yl).astype(out.dtype)
    return {"Out": [LoDValue(out, yl)]}


# ---------------------------------------------------------------------------
# sequence_concat
# ---------------------------------------------------------------------------
def _seq_concat_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", list(x.shape), x.dtype, lod_level=1)


@register_op("sequence_concat", infer_shape=_seq_concat_infer, diff_inputs=["X"])
def _sequence_concat(ctx, ins, attrs):
    """Concatenate sequences time-wise per row (reference:
    operators/sequence_ops/sequence_concat_op.cc).  Each row's valid tokens
    are packed back-to-back with vmapped dynamic_update_slice."""
    vals = ins["X"]
    ds, ls = zip(*(_as_lod(v) for v in vals))
    n = ds[0].shape[0]
    t_total = sum(d.shape[1] for d in ds)
    feat = ds[0].shape[2:]
    out = jnp.zeros((n, t_total) + feat, dtype=ds[0].dtype)
    off = jnp.zeros((n,), dtype=jnp.int32)
    for d, l in zip(ds, ls):
        dm = d * _fmask(d, l).astype(d.dtype)
        pad_t = t_total - d.shape[1]
        dm_full = jnp.pad(dm, [(0, 0), (0, pad_t)] + [(0, 0)] * (dm.ndim - 2))
        # shift row i's valid tokens right by off[i], then add; valid tokens
        # never wrap because off[i] + l_i <= sum of time dims
        out = out + jax.vmap(lambda row, o: jnp.roll(row, o, axis=0))(dm_full, off)
        off = off + l.astype(jnp.int32)
    return {"Out": [LoDValue(out, off)]}


# ---------------------------------------------------------------------------
# sequence_pad / unpad / mask
# ---------------------------------------------------------------------------
def _seq_pad_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    plen = op.attr("padded_length", -1)
    set_output(block, op, "Out", [-1 if plen in (-1, None) else plen] + list(x.shape[1:]), x.dtype, lod_level=0)
    if op.output("Length") and op.output("Length")[0]:
        set_output(block, op, "Length", [-1], DataType.INT64, lod_level=0)


@register_op("sequence_pad", infer_shape=_seq_pad_infer, diff_inputs=["X"])
def _sequence_pad(ctx, ins, attrs):
    """LoDValue -> (dense padded, lengths) (reference:
    operators/sequence_ops/sequence_pad_op.cc).  The padded layout is already
    our native representation; this just fills the pad slots with PadValue."""
    x = ins["X"][0]
    d, l = _as_lod(x)
    pad_value = data(ins["PadValue"][0]) if ins.get("PadValue") else jnp.asarray(0.0, d.dtype)
    plen = attrs.get("padded_length", -1)
    if plen not in (-1, None) and plen > d.shape[1]:
        d = jnp.pad(d, [(0, 0), (0, plen - d.shape[1])] + [(0, 0)] * (d.ndim - 2))
    m = _fmask(d, l).astype(bool)
    out = jnp.where(m, d, jnp.broadcast_to(jnp.reshape(pad_value, (1,) * d.ndim if jnp.ndim(pad_value) == 0 else jnp.shape(pad_value)), d.shape))
    return {"Out": [out], "Length": [l.astype(wide_int())]}


def _seq_unpad_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", [-1] + list(x.shape[2:]), x.dtype, lod_level=1)


@register_op("sequence_unpad", infer_shape=_seq_unpad_infer, diff_inputs=["X"])
def _sequence_unpad(ctx, ins, attrs):
    """(dense padded, lengths) -> LoDValue (reference:
    operators/sequence_ops/sequence_unpad_op.cc)."""
    d = data(ins["X"][0])
    l = data(ins["Length"][0]).reshape(-1).astype(jnp.int32)
    d = d * _fmask(d, l).astype(d.dtype)
    return {"Out": [LoDValue(d, l)]}


def _seq_mask_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    maxlen = op.attr("maxlen", -1)
    set_output(
        block, op, "Y", list(x.shape) + [maxlen if maxlen > 0 else -1],
        DataType(op.attr("out_dtype", int(DataType.INT64))), lod_level=0,
    )


@register_op("sequence_mask", infer_shape=_seq_mask_infer, no_grad=True)
def _sequence_mask(ctx, ins, attrs):
    """lengths -> [*, maxlen] 0/1 mask (reference:
    operators/sequence_ops/sequence_mask_op.cc)."""
    from ..core.proto import dtype_to_runtime

    x = ins["X"][0]
    l = data(x)
    if isinstance(x, LoDValue):
        l = x.lengths
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen <= 0:
        if isinstance(x, LoDValue):
            maxlen = x.data.shape[1]  # the padded time dim is the natural bound
        else:
            raise NotImplementedError(
                "sequence_mask with maxlen=-1 on a dense lengths tensor needs "
                "a data-dependent shape; pass an explicit maxlen on TPU"
            )
    dtype = dtype_to_runtime(DataType(attrs.get("out_dtype", int(DataType.INT64))))
    mask = (jnp.arange(maxlen) < l[..., None]).astype(dtype)
    return {"Y": [mask]}


# ---------------------------------------------------------------------------
# sequence_reshape / reverse / slice / erase / enumerate / scatter
# ---------------------------------------------------------------------------
def _seq_reshape_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", [-1, op.attr("new_dim", x.shape[-1])], x.dtype, lod_level=1)


@register_op("sequence_reshape", infer_shape=_seq_reshape_infer, diff_inputs=["X"])
def _sequence_reshape(ctx, ins, attrs):
    """Re-chunk each sequence's flat features to width new_dim (reference:
    operators/sequence_ops/sequence_reshape_op.cc).  Row-major padded rows
    keep valid tokens contiguous, so a per-row reshape is exact when
    (T*F) % new_dim == 0."""
    x = ins["X"][0]
    d, l = _as_lod(x)
    new_dim = int(attrs["new_dim"])
    n, t = d.shape[0], d.shape[1]
    f = int(np.prod(d.shape[2:])) if d.ndim > 2 else 1
    total = t * f
    if total % new_dim != 0:
        raise ValueError(f"sequence_reshape: T*F={total} not divisible by new_dim={new_dim}")
    out = jnp.reshape(d, (n, total // new_dim, new_dim))
    new_l = (l * f) // new_dim
    return {"Out": [LoDValue(out, new_l)]}


@register_op("sequence_reverse", infer_shape=same_shape("X", "Y"), diff_inputs=["X"])
def _sequence_reverse(ctx, ins, attrs):
    """Reverse valid tokens per sequence (reference:
    operators/sequence_ops/sequence_reverse_op.h — output slot is Y)."""
    x = ins["X"][0]
    d, l = _as_lod(x)
    t = d.shape[1]
    ar = jnp.arange(t)[None, :]
    idx = jnp.where(ar < l[:, None], l[:, None] - 1 - ar, ar)
    out = jnp.take_along_axis(d, idx.reshape(idx.shape + (1,) * (d.ndim - 2)).astype(jnp.int32), axis=1)
    return {"Y": [wrap_lod(x, out)]}


def _seq_slice_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", list(x.shape), x.dtype, lod_level=1)


@register_op("sequence_slice", infer_shape=_seq_slice_infer, diff_inputs=["X"])
def _sequence_slice(ctx, ins, attrs):
    """Per-sequence (offset, length) window (reference:
    operators/sequence_ops/sequence_slice_op.h)."""
    x = ins["X"][0]
    d, l = _as_lod(x)
    off = data(ins["Offset"][0]).reshape(-1).astype(jnp.int32)
    length = data(ins["Length"][0]).reshape(-1).astype(jnp.int32)
    t = d.shape[1]
    ar = jnp.arange(t)[None, :]
    idx = jnp.clip(off[:, None] + ar, 0, t - 1)
    out = jnp.take_along_axis(d, idx.reshape(idx.shape + (1,) * (d.ndim - 2)), axis=1)
    out = out * _fmask(out, length).astype(out.dtype)
    return {"Out": [LoDValue(out, length)]}


def _seq_erase_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", list(x.shape), x.dtype, lod_level=1)


@register_op("sequence_erase", infer_shape=_seq_erase_infer, no_grad=True)
def _sequence_erase(ctx, ins, attrs):
    """Drop tokens matching the given values, compacting left (reference:
    operators/sequence_ops/sequence_erase_op.h)."""
    x = ins["X"][0]
    d, l = _as_lod(x)
    tokens = jnp.asarray(list(attrs.get("tokens", [])), dtype=d.dtype).reshape(-1)
    valid = _time_mask(d, l)
    keep = valid & ~jnp.isin(d if d.ndim == 2 else d[..., 0], tokens)
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    t = d.shape[1]

    def compact(row, keep_row, pos_row):
        tgt = jnp.where(keep_row, pos_row, t)  # dumped tokens go past the end
        out_row = jnp.zeros((t + 1,) + row.shape[1:], dtype=row.dtype)
        out_row = out_row.at[tgt].set(row * keep_row.reshape((-1,) + (1,) * (row.ndim - 1)).astype(row.dtype))
        return out_row[:t]

    out = jax.vmap(compact)(d, keep, pos)
    return {"Out": [LoDValue(out, jnp.sum(keep, axis=1).astype(jnp.int32))]}


def _seq_enum_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", [-1, op.attr("win_size", 2)], x.dtype, lod_level=1)


@register_op("sequence_enumerate", infer_shape=_seq_enum_infer, no_grad=True)
def _sequence_enumerate(ctx, ins, attrs):
    """Sliding windows of ids (reference:
    operators/sequence_ops/sequence_enumerate_op.h)."""
    x = ins["X"][0]
    d, l = _as_lod(x)
    if d.ndim == 3 and d.shape[-1] == 1:
        d = d[..., 0]
    win = int(attrs.get("win_size", 2))
    pad = attrs.get("pad_value", 0)
    t = d.shape[1]
    ar = jnp.arange(t)[:, None] + jnp.arange(win)[None, :]  # [T, win]
    padded = jnp.pad(d, [(0, 0), (0, win)], constant_values=pad)
    out = padded[:, ar]  # [N, T, win]
    in_range = (ar[None] < l[:, None, None])
    out = jnp.where(in_range, out, pad)
    return {"Out": [LoDValue(out, l)]}


def _seq_scatter_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", list(x.shape), x.dtype, lod_level=x.lod_level)


@register_op("sequence_scatter", infer_shape=_seq_scatter_infer, diff_inputs=["X", "Updates"])
def _sequence_scatter(ctx, ins, attrs):
    """Per-row scatter-add of Updates at Ids (reference:
    operators/sequence_ops/sequence_scatter_op.cc — X row i receives
    updates from sequence i)."""
    xd = data(ins["X"][0])
    ids, il = _as_lod(ins["Ids"][0])
    upd, _ = _as_lod(ins["Updates"][0])
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    m = _time_mask(ids, il).astype(upd.dtype)
    upd = upd * m.reshape(m.shape + (1,) * (upd.ndim - 2))

    def row_scatter(xrow, idrow, updrow):
        return xrow.at[idrow].add(updrow)

    out = jax.vmap(row_scatter)(xd, ids.astype(jnp.int32), upd)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# lod_reset
# ---------------------------------------------------------------------------
@register_op("lod_reset", infer_shape=same_shape(), diff_inputs=["X"])
def _lod_reset(ctx, ins, attrs):
    """Attach/replace sequence lengths (reference: operators/lod_reset_op.cc).

    The reference relabels a FLAT token buffer's offset table; in the
    padded world that is a re-chunk.  With a static `target_lod` attr the
    re-chunk is exact (gather below).  With a runtime `Y` the new lengths
    are traced, so the output's padded extent can't be derived — the Y
    path RELABELS the existing rows instead, which matches the reference
    only when X's rows are already laid out per Y's chunking (the dominant
    use: adopting a sibling tensor's structure onto aligned data).  For a
    genuine runtime re-chunk, go through sequence_unpad + sequence_pad."""
    x = ins["X"][0]
    d = data(x)
    y = ins.get("Y", [None])[0]
    if y is not None:
        if isinstance(y, LoDValue):
            return {"Out": [LoDValue(d, y.lengths)]}
        ly = data(y).reshape(-1)
        # offsets -> lengths
        l = (ly[1:] - ly[:-1]).astype(jnp.int32)
        return {"Out": [LoDValue(d, l)]}
    target = attrs.get("target_lod", [])
    if not target:
        return {"Out": [d]}
    t = np.asarray(target)
    # reference passes level-0 OFFSETS ([0, 2, 6]) — validate, don't guess
    if t[0] != 0 or np.any(np.diff(t) < 0):
        raise ValueError(
            f"lod_reset target_lod must be non-decreasing offsets starting "
            f"at 0 (reference lod_reset_op contract), got {t.tolist()}"
        )
    new_l = np.diff(t).astype(np.int32)
    if not isinstance(x, LoDValue):
        if d.ndim >= 2 and d.shape[0] == len(new_l):
            return {"Out": [LoDValue(d, jnp.asarray(new_l))]}
        raise ValueError(
            f"lod_reset: dense input with {d.shape[0]} rows cannot take "
            f"{len(new_l)} sequence lengths"
        )
    # padded -> padded re-chunk: the target offsets are static, so each
    # output (seq, pos) maps to one global token index; locate it in the
    # input's (traced) offsets with a searchsorted gather
    n_out = len(new_l)
    t_out = int(new_l.max()) if n_out else 0
    new_off = np.concatenate([[0], np.cumsum(new_l)])
    in_l = jnp.asarray(x.lengths).astype(jnp.int32)
    in_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(in_l)]
    )
    gidx = new_off[:-1, None] + np.arange(t_out)[None, :]  # [n_out, t_out]
    gidx_j = jnp.asarray(gidx, jnp.int32)
    seq = jnp.clip(
        jnp.searchsorted(in_off, gidx_j.reshape(-1), side="right") - 1,
        0, d.shape[0] - 1,
    )
    pos = jnp.clip(gidx_j.reshape(-1) - in_off[seq], 0, d.shape[1] - 1)
    rows = d[seq, pos].reshape((n_out, t_out) + d.shape[2:])
    valid = jnp.asarray(
        np.arange(t_out)[None, :] < new_l[:, None]
    )
    rows = rows * valid.reshape(
        valid.shape + (1,) * (rows.ndim - 2)
    ).astype(rows.dtype)
    # the reference enforces last offset == total tokens; input lengths are
    # traced here, so poison the output when they disagree instead of
    # silently presenting padding as data (NaN for floats; a check_nan_inf
    # run or the loss surfaces it immediately)
    total_ok = jnp.sum(in_l) == int(new_off[-1])
    if jnp.issubdtype(rows.dtype, jnp.floating):
        rows = jnp.where(total_ok, rows, jnp.nan)
    out_l = jnp.where(total_ok, jnp.asarray(new_l), -1)
    return {"Out": [LoDValue(rows, out_l)]}


# ---------------------------------------------------------------------------
# sequence_conv / row_conv / im2sequence
# ---------------------------------------------------------------------------
def _seq_conv_infer(op, block):
    x = in_desc(op, block, "Filter")
    xin = in_desc(op, block, "X")
    if x is None or xin is None:
        return
    set_output(block, op, "Out", [-1, x.shape[1]], xin.dtype, lod_level=1)


def _context_window(d, l, clen, cstart):
    """im2col over the time axis: gather the [cstart, cstart+clen) context
    window per step, zero outside the sequence (math/context_project.h)."""
    t = d.shape[1]
    m = _fmask(d, l).astype(d.dtype)
    dm = d * m
    cols = []
    for j in range(clen):
        shift = cstart + j
        rolled = jnp.roll(dm, -shift, axis=1)
        ar = jnp.arange(t) + shift
        # mask against each sequence's own length (l <= t, so this also
        # covers the padded-window bound)
        ok_seq = (ar[None, :] < l[:, None]) & (ar[None, :] >= 0)
        rolled = rolled * ok_seq[..., None].astype(d.dtype)
        cols.append(rolled)
    out = jnp.concatenate(cols, axis=-1)  # [N, T, clen*F]
    # zero the padded target rows too (roll wraps valid data into them)
    return out * _time_mask(d, l)[..., None].astype(d.dtype)


@register_op("sequence_conv", infer_shape=_seq_conv_infer, diff_inputs=["X", "Filter"])
def _sequence_conv(ctx, ins, attrs):
    """Context-window convolution over time (reference:
    operators/sequence_ops/sequence_conv_op.cc, math/context_project.h):
    im2col the [contextStart, contextStart+contextLength) window per step
    (zero outside the sequence) then one matmul with the filter."""
    x = ins["X"][0]
    d, l = _as_lod(x)
    filt = data(ins["Filter"][0])  # [context_length * F, out]
    clen = int(attrs.get("contextLength", 3))
    cstart = int(attrs.get("contextStart", -((clen - 1) // 2)))
    ctx_feat = _context_window(d, l, clen, cstart)
    # padded rows of ctx_feat are already zero, so the matmul output is too
    out = jnp.einsum("ntf,fo->nto", ctx_feat, filt)
    return {"Out": [LoDValue(out, l)]}


def _row_conv_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", list(x.shape), x.dtype, lod_level=1)


@register_op("row_conv", infer_shape=_row_conv_infer, diff_inputs=["X", "Filter"])
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution (reference: operators/row_conv_op.cc):
    out[t] = sum_j x[t+j] * w[j], j in [0, future_context]."""
    x = ins["X"][0]
    d, l = _as_lod(x)
    w = data(ins["Filter"][0])  # [future_context + 1, F]
    t = d.shape[1]
    m = _fmask(d, l).astype(d.dtype)
    dm = d * m
    out = jnp.zeros_like(d)
    for j in range(w.shape[0]):
        shifted = jnp.roll(dm, -j, axis=1)
        ok_seq = ((jnp.arange(t)[None, :] + j) < l[:, None])[..., None].astype(d.dtype)
        out = out + shifted * ok_seq * w[j][None, None, :]
    out = out * m
    return {"Out": [wrap_lod(x, out)]}


def _im2sequence_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    kh, kw = op.attr("kernels", [3, 3])
    set_output(block, op, "Out", [-1, x.shape[1] * kh * kw], x.dtype, lod_level=1)


@register_op("im2sequence", infer_shape=_im2sequence_infer, diff_inputs=["X"])
def _im2sequence(ctx, ins, attrs):
    """NCHW image -> sequence of flattened patches (reference:
    operators/im2sequence_op.cc)."""
    x = data(ins["X"][0])
    kh, kw = attrs.get("kernels", [3, 3])
    sh, sw = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0, 0, 0])  # up, left, down, right
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw),
        padding=[(pads[0], pads[2]), (pads[1], pads[3])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, OH, OW]
    n, ckk = patches.shape[0], patches.shape[1]
    out = jnp.transpose(patches.reshape(n, ckk, -1), (0, 2, 1))  # [N, OH*OW, C*kh*kw]
    lengths = jnp.full((n,), out.shape[1], dtype=jnp.int32)
    return {"Out": [LoDValue(out, lengths)]}


# ---------------------------------------------------------------------------
# fused sequence ops (reference: operators/fused/ — MKLDNN-era fusions; on
# TPU each is a handful of XLA-fusable primitives around one MXU matmul)
# ---------------------------------------------------------------------------
def _seqconv_eltadd_relu_infer(op, block):
    x = in_desc(op, block, "X")
    f = in_desc(op, block, "Filter")
    if x is None or f is None:
        return
    set_output(block, op, "Out", [-1, f.shape[1]], x.dtype, lod_level=1)
    if op.output("ColMat") and op.output("ColMat")[0]:
        set_output(block, op, "ColMat", [-1, f.shape[0]], x.dtype, lod_level=0)


@register_op("fusion_seqconv_eltadd_relu",
             infer_shape=_seqconv_eltadd_relu_infer,
             diff_inputs=["X", "Filter", "Bias"])
def _fusion_seqconv_eltadd_relu(ctx, ins, attrs):
    """relu(sequence_conv(X, Filter) + Bias) in one op (reference:
    operators/fused/fusion_seqconv_eltadd_relu_op.cc; contextStride must
    be 1).  ColMat is the im2col intermediate the reference exposes."""
    if int(attrs.get("contextStride", 1)) != 1:
        raise ValueError("fusion_seqconv_eltadd_relu supports contextStride=1 only")
    x = ins["X"][0]
    d, l = _as_lod(x)
    filt = data(ins["Filter"][0])          # [clen*F, out]
    bias = data(ins["Bias"][0]).reshape(-1)  # [out]
    clen = int(attrs.get("contextLength", 3))
    cstart = int(attrs.get("contextStart", 0))
    ctx_feat = _context_window(d, l, clen, cstart)
    out = jax.nn.relu(jnp.einsum("ntf,fo->nto", ctx_feat, filt) + bias)
    out = out * _time_mask(d, l)[..., None].astype(out.dtype)
    return {"Out": [LoDValue(out, l)], "ColMat": [LoDValue(ctx_feat, l)]}


def _seqexpand_concat_fc_infer(op, block):
    x = in_desc(op, block, "X")
    w = in_desc(op, block, "FCWeight")
    if x is None or w is None:
        return
    set_output(block, op, "Out", [-1, w.shape[1]], x.dtype, lod_level=1)
    if op.output("FCOut") and op.output("FCOut")[0]:
        set_output(block, op, "FCOut", [-1, w.shape[1]], x.dtype, lod_level=0)


@register_op("fusion_seqexpand_concat_fc",
             infer_shape=_seqexpand_concat_fc_infer,
             diff_inputs=["X", "FCWeight", "FCBias"])
def _fusion_seqexpand_concat_fc(ctx, ins, attrs):
    """sequence_expand(ref_level=0) + concat(axis=1) + fc in one op
    (reference: operators/fused/fusion_seqexpand_concat_fc_op.cc): X[0] is
    the LoD sequence [N, T, M0]; X[1:] are one row per sequence [N, Mi]
    broadcast over time.  out_t = act(x0_t @ W[:M0] + [x1_i, ...] @ W[M0:]
    + b); the per-sequence half (the reference's FCOut scratch) is computed
    once per sequence, not per token."""
    xs = ins["X"]
    x0 = xs[0]
    d, l = _as_lod(x0)
    w = data(ins["FCWeight"][0])           # [M0+M1+..., D]
    m0 = d.shape[-1]
    tok = jnp.einsum("ntm,md->ntd", d, w[:m0])
    rest = [data(v).reshape(d.shape[0], -1) for v in xs[1:]]
    fc_out = None
    if rest:
        cat = jnp.concatenate(rest, axis=-1)  # [N, M1+M2+...]
        fc_out = cat @ w[m0:]                 # [N, D]
        tok = tok + fc_out[:, None, :]
    if ins.get("FCBias") and ins["FCBias"][0] is not None:
        tok = tok + data(ins["FCBias"][0]).reshape(-1)
    act = ACTS[attrs.get("fc_activation", "identity") or "identity"]
    out = act(tok) * _time_mask(d, l)[..., None].astype(d.dtype)
    outs = {"Out": [LoDValue(out, l)]}
    if fc_out is not None:
        outs["FCOut"] = [fc_out]
    return outs
