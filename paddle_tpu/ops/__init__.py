"""Operator library: importing this package registers every op lowering.

The reference's equivalent is the static-registrar operator library
paddle/fluid/operators/ (353 registered ops); here each module is a set of
JAX lowering rules consumed by paddle_tpu.core.compiler.
"""

from . import (  # noqa: F401
    activation_ops,
    attention_ops,
    beam_search_ops,
    compare_ops,
    control_flow_ops,
    crf_ops,
    detection_ops,
    elementwise_ops,
    framework_ops,
    loss_ops,
    math_ops,
    metric_ops,
    misc_ops,
    nn_ops,
    optimizer_ops,
    proposal_ops,
    quant_ops,
    reduce_ops,
    rnn_ops,
    sequence_ops,
    tensor_ops,
    vision_ops,
)

from ..core.registry import OpRegistry


def registered_ops():
    return OpRegistry.registered_ops()
