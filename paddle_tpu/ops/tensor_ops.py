"""Tensor creation / manipulation ops.

Reference kernels: paddle/fluid/operators/{reshape,concat,split,gather,...}_op.*
plus fill/random initializer ops.  Random ops draw from the compiler-threaded
PRNG stream (LoweringContext.rng) instead of the reference's stateful
curand/std::mt19937 seeds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.lod import LoDValue
from ..core.proto import DataType, dtype_to_runtime
from ..core.registry import register_op
from ..core.selected_rows import SelectedRowsValue
from .common import (data, in_desc, lengths, lod_padded_axis, same_shape,
                     set_output, wrap_lod)


# -- fills -------------------------------------------------------------------
def _fill_constant_infer(op, block):
    set_output(
        block, op, "Out", list(op.attr("shape", [1])),
        DataType(op.attr("dtype", int(DataType.FP32))),
    )


@register_op("fill_constant", infer_shape=_fill_constant_infer, no_grad=True)
def _fill_constant(ctx, ins, attrs):
    dtype = dtype_to_runtime(DataType(attrs.get("dtype", int(DataType.FP32))))
    shape = [int(d) for d in attrs.get("shape", [1])]
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0), dtype=dtype)]}


def _fill_like_infer(op, block):
    x = in_desc(op, block, "X") or in_desc(op, block, "Input")
    if x is None:
        return
    set_output(block, op, "Out", x.shape, x.dtype)


@register_op("fill_zeros_like", infer_shape=_fill_like_infer, no_grad=True)
def _fill_zeros_like(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [wrap_lod(x, jnp.zeros_like(data(x)))]}


def _fill_bsl_infer(op, block):
    x = in_desc(op, block, "Input")
    shape = list(op.attr("shape", [1]))
    if x is not None:
        in_idx = op.attr("input_dim_idx", 0)
        out_idx = op.attr("output_dim_idx", 0)
        if in_idx < len(x.shape):
            shape[out_idx] = x.shape[in_idx]
    set_output(block, op, "Out", shape, DataType(op.attr("dtype", int(DataType.FP32))))


@register_op("fill_constant_batch_size_like", infer_shape=_fill_bsl_infer, no_grad=True)
def _fill_constant_batch_size_like(ctx, ins, attrs):
    """Fill with the batch dim copied from a runtime input
    (reference: operators/fill_constant_batch_size_like_op.cc)."""
    x = data(ins["Input"][0])
    shape = [int(d) for d in attrs.get("shape", [1])]
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    dtype = dtype_to_runtime(DataType(attrs.get("dtype", int(DataType.FP32))))
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0), dtype=dtype)]}


@register_op("assign", infer_shape=_fill_like_infer)
def _assign(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [x]}


def _assign_value_infer(op, block):
    set_output(
        block, op, "Out", list(op.attr("shape", [1])),
        DataType(op.attr("dtype", int(DataType.FP32))),
    )


@register_op("assign_value", infer_shape=_assign_value_infer, no_grad=True)
def _assign_value(ctx, ins, attrs):
    dtype = DataType(attrs.get("dtype", int(DataType.FP32)))
    vals = (
        attrs.get("fp32_values")
        or attrs.get("int32_values")
        or attrs.get("values")
        or []
    )
    arr = jnp.asarray(np.asarray(vals, dtype=dtype_to_runtime(dtype)).reshape(attrs["shape"]))
    return {"Out": [arr]}


# -- random ------------------------------------------------------------------
def _random_infer(op, block):
    set_output(
        block, op, "Out", list(op.attr("shape", [1])),
        DataType(op.attr("dtype", int(DataType.FP32))),
    )


@register_op("uniform_random", infer_shape=_random_infer, no_grad=True, random=True)
def _uniform_random(ctx, ins, attrs):
    dtype = dtype_to_runtime(DataType(attrs.get("dtype", int(DataType.FP32))))
    shape = [int(d) for d in attrs["shape"]]
    out = jax.random.uniform(
        ctx.rng(), shape, dtype=dtype,
        minval=attrs.get("min", -1.0), maxval=attrs.get("max", 1.0),
    )
    return {"Out": [out]}


@register_op("uniform_random_batch_size_like", infer_shape=_fill_bsl_infer, no_grad=True, random=True)
def _uniform_random_bsl(ctx, ins, attrs):
    x = data(ins["Input"][0])
    shape = [int(d) for d in attrs["shape"]]
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    dtype = dtype_to_runtime(DataType(attrs.get("dtype", int(DataType.FP32))))
    out = jax.random.uniform(
        ctx.rng(), shape, dtype=dtype,
        minval=attrs.get("min", -1.0), maxval=attrs.get("max", 1.0),
    )
    return {"Out": [out]}


@register_op("gaussian_random", infer_shape=_random_infer, no_grad=True, random=True)
def _gaussian_random(ctx, ins, attrs):
    dtype = dtype_to_runtime(DataType(attrs.get("dtype", int(DataType.FP32))))
    shape = [int(d) for d in attrs["shape"]]
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.normal(
        ctx.rng(), shape, dtype=dtype
    )
    return {"Out": [out]}


@register_op("truncated_gaussian_random", infer_shape=_random_infer, no_grad=True, random=True)
def _truncated_gaussian_random(ctx, ins, attrs):
    dtype = dtype_to_runtime(DataType(attrs.get("dtype", int(DataType.FP32))))
    shape = [int(d) for d in attrs["shape"]]
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.truncated_normal(
        ctx.rng(), -2.0, 2.0, shape, dtype=dtype
    )
    return {"Out": [out]}


@register_op("sampling_id", infer_shape=lambda op, block: set_output(block, op, "Out", [in_desc(op, block, "X").shape[0]], DataType.INT64), no_grad=True, random=True)
def _sampling_id(ctx, ins, attrs):
    x = data(ins["X"][0])
    return {"Out": [jax.random.categorical(ctx.rng(), jnp.log(x + 1e-20), axis=-1)]}


# -- shape manipulation ------------------------------------------------------
def _resolve_reshape(in_shape, target):
    """Fluid reshape semantics: 0 copies the input dim, one -1 infers."""
    out = []
    for i, d in enumerate(target):
        if d == 0:
            out.append(in_shape[i])
        else:
            out.append(int(d))
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in in_shape:
            total *= d
        out[out.index(-1)] = total // known
    return out


def _reshape_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    target = list(op.attr("shape", []))
    shape = list(x.shape)
    if all(d >= 0 for d in shape):
        shape = _resolve_reshape(shape, target)
    else:
        shape = [shape[i] if d == 0 else d for i, d in enumerate(target)]
    # row-preserving feature reshapes keep the sequence view (the only
    # LoD case the lowering supports)
    lod = x.lod_level if (target and target[0] in (-1, 0)) else 0
    set_output(block, op, "Out", shape, x.dtype, lod_level=lod)
    if op.output("XShape"):
        set_output(block, op, "XShape", [0] + list(x.shape), x.dtype)


def _reshape_lower(ctx, ins, attrs):
    xv = ins["X"][0]
    x = data(xv)
    target = list(attrs["shape"])
    if isinstance(xv, LoDValue):
        # the desc-level target addresses the unpadded [sum(T), F...]
        # layout; a padded flat reshape would interleave pad slots into
        # the output.  Row-preserving feature reshapes ([-1/0, F'...])
        # keep the sequence view; anything that re-chunks rows has no
        # padded equivalent.
        if xv.sub_lengths:
            raise NotImplementedError(
                "reshape on multi-level LoD inputs is not supported")
        feat = x.shape[2:]
        feat_total = int(np.prod(feat)) if feat else 1
        if target and target[0] in (-1, 0):
            new_feat = []
            for i, d in enumerate(target[1:], start=1):
                # 0 copies the input dim at the same desc position
                # (unpadded dim i = padded dim i + 1)
                new_feat.append(int(x.shape[i + 1]) if d == 0 else int(d))
            if -1 in new_feat:
                known = 1
                for d in new_feat:
                    if d != -1:
                        known *= d
                new_feat[new_feat.index(-1)] = feat_total // max(known, 1)
            if int(np.prod(new_feat or [1])) == feat_total:
                out = jnp.reshape(x, x.shape[:2] + tuple(new_feat))
                return {"Out": [wrap_lod(xv, out)]}
        raise NotImplementedError(
            f"reshape of a sequence to {target} re-chunks its rows; use "
            "sequence_reshape for row re-chunking or sequence_unpad first")
    shape = _resolve_reshape(x.shape, target)
    return {"Out": [jnp.reshape(x, shape)]}


register_op("reshape", infer_shape=_reshape_infer, diff_inputs=["X"])(_reshape_lower)
register_op("reshape2", infer_shape=_reshape_infer, diff_inputs=["X"])(_reshape_lower)


def _squeeze_axes(shape, axes):
    if axes:
        axes = [a + len(shape) if a < 0 else a for a in axes]
        return [d for i, d in enumerate(shape) if not (i in axes and d == 1)]
    return [d for d in shape if d != 1]


def _squeeze_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", _squeeze_axes(list(x.shape), op.attr("axes", [])), x.dtype)
    if op.output("XShape"):
        set_output(block, op, "XShape", [0] + list(x.shape), x.dtype)


def _squeeze_lower(ctx, ins, attrs):
    x = data(ins["X"][0])
    return {"Out": [jnp.reshape(x, _squeeze_axes(x.shape, attrs.get("axes", [])))]}


register_op("squeeze", infer_shape=_squeeze_infer, diff_inputs=["X"])(_squeeze_lower)
register_op("squeeze2", infer_shape=_squeeze_infer, diff_inputs=["X"])(_squeeze_lower)


def _unsqueeze_shape(shape, axes):
    out = list(shape)
    for a in sorted(axes):
        a = a + len(out) + 1 if a < 0 else a
        out.insert(a, 1)
    return out


def _unsqueeze_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", _unsqueeze_shape(x.shape, op.attr("axes", [])), x.dtype)
    if op.output("XShape"):
        set_output(block, op, "XShape", [0] + list(x.shape), x.dtype)


def _unsqueeze_lower(ctx, ins, attrs):
    x = data(ins["X"][0])
    return {"Out": [jnp.reshape(x, _unsqueeze_shape(x.shape, attrs.get("axes", [])))]}


register_op("unsqueeze", infer_shape=_unsqueeze_infer, diff_inputs=["X"])(_unsqueeze_lower)
register_op("unsqueeze2", infer_shape=_unsqueeze_infer, diff_inputs=["X"])(_unsqueeze_lower)


def _flatten_shape(shape, axis):
    lead = 1
    for d in shape[:axis]:
        lead *= d
    tail = 1
    for d in shape[axis:]:
        tail *= d
    return [lead, tail]


def _flatten_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    shape = list(x.shape)
    axis = op.attr("axis", 1)
    if all(d >= 0 for d in shape):
        out = _flatten_shape(shape, axis)
    else:
        out = [-1, -1]
        if axis == 1 and len(shape) >= 1 and shape[0] < 0:
            tail = 1
            ok = all(d >= 0 for d in shape[1:])
            for d in shape[1:]:
                tail *= d
            out = [-1, tail if ok else -1]
    set_output(block, op, "Out", out, x.dtype)
    if op.output("XShape"):
        set_output(block, op, "XShape", [0] + list(x.shape), x.dtype)


def _flatten_lower(ctx, ins, attrs):
    x = data(ins["X"][0])
    return {"Out": [jnp.reshape(x, _flatten_shape(x.shape, attrs.get("axis", 1)))]}


register_op("flatten", infer_shape=_flatten_infer, diff_inputs=["X"])(_flatten_lower)
register_op("flatten2", infer_shape=_flatten_infer, diff_inputs=["X"])(_flatten_lower)


def _transpose_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    axis = op.attr("axis", [])
    set_output(block, op, "Out", [x.shape[a] for a in axis], x.dtype)
    if op.output("XShape"):
        set_output(block, op, "XShape", [0] + list(x.shape), x.dtype)


def _transpose_lower(ctx, ins, attrs):
    x = data(ins["X"][0])
    return {"Out": [jnp.transpose(x, attrs["axis"])]}


register_op("transpose", infer_shape=_transpose_infer, diff_inputs=["X"])(_transpose_lower)
register_op("transpose2", infer_shape=_transpose_infer, diff_inputs=["X"])(_transpose_lower)


def _concat_infer(op, block):
    xs = [in_desc(op, block, "X", i) for i in range(len(op.input("X")))]
    xs = [x for x in xs if x is not None]
    if not xs:
        return
    axis = op.attr("axis", 0)
    rank = len(xs[0].shape)
    axis = axis + rank if axis < 0 else axis
    shape = list(xs[0].shape)
    tot = 0
    for x in xs:
        d = x.shape[axis]
        if d < 0:
            tot = -1
            break
        tot += d
    shape[axis] = tot
    # sequences stay sequences: feature-axis concat keeps the lod view,
    # and axis-0 row concat merges batches of sequences
    set_output(block, op, "Out", shape, xs[0].dtype,
               lod_level=xs[0].lod_level)


@register_op("concat", infer_shape=_concat_infer)
def _concat(ctx, ins, attrs):
    vals = [v for v in ins["X"] if v is not None]
    xs = [data(v) for v in vals]
    axis = attrs.get("axis", 0)
    lod_in = next((v for v in vals if isinstance(v, LoDValue)), None)
    if lod_in is not None:
        # the desc-level axis addresses the reference's unpadded
        # [sum(T), F...] layout; feature axes shift right past the time
        # dims on padded data (lod_padded_axis handles N-level nesting)
        level = 1 + len(lod_in.sub_lengths)
        p_axis = lod_padded_axis(axis, level, xs[0].ndim)
        if p_axis == 0:
            # row concat: the reference appends the sequences of every
            # input into one batch (concatenated lod).  Pad to a common
            # time extent, stack along N, merge the lengths.
            if level != 1 or not all(
                isinstance(v, LoDValue) for v in vals
            ):
                raise NotImplementedError(
                    "concat(axis=0) on LoD inputs supports 1-level "
                    "sequences only")
            tmax = max(d.shape[1] for d in xs)
            padded = [
                jnp.pad(d, [(0, 0), (0, tmax - d.shape[1])]
                        + [(0, 0)] * (d.ndim - 2))
                for d in xs
            ]
            out = jnp.concatenate(padded, axis=0)
            lens = jnp.concatenate(
                [jnp.asarray(v.lengths).reshape(-1) for v in vals])
            return {"Out": [LoDValue(out, lens)]}
        out = jnp.concatenate(xs, axis=p_axis)
        return {"Out": [wrap_lod(lod_in, out)]}
    out = jnp.concatenate(xs, axis=axis)
    return {"Out": [out]}


def _split_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    axis = op.attr("axis", 0)
    rank = len(x.shape)
    axis = axis + rank if axis < 0 else axis
    num = op.attr("num", 0)
    sections = op.attr("sections", [])
    outs = op.output("Out")
    # feature-axis splits of a sequence stay sequences (see _concat_infer)
    lod = x.lod_level if axis >= 1 else 0
    for i in range(len(outs)):
        shape = list(x.shape)
        if sections:
            shape[axis] = sections[i]
        elif num:
            shape[axis] = x.shape[axis] // num if x.shape[axis] >= 0 else -1
        set_output(block, op, "Out", shape, x.dtype, idx=i, lod_level=lod)


@register_op("split", infer_shape=_split_infer)
def _split(ctx, ins, attrs):
    xv = ins["X"][0]
    x = data(xv)
    axis = attrs.get("axis", 0)
    lod = isinstance(xv, LoDValue)
    if lod:
        # same desc-axis -> padded-axis remap as _concat
        level = 1 + len(xv.sub_lengths)
        axis = lod_padded_axis(axis, level, x.ndim)
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, attrs.get("num", 1), axis=axis)
    if lod and axis >= 1:
        outs = [wrap_lod(xv, o) for o in outs]
    return {"Out": list(outs)}


def _stack_infer(op, block):
    xs = [in_desc(op, block, "X", i) for i in range(len(op.input("X")))]
    xs = [x for x in xs if x is not None]
    if not xs:
        return
    axis = op.attr("axis", 0)
    shape = list(xs[0].shape)
    axis = axis + len(shape) + 1 if axis < 0 else axis
    shape.insert(axis, len(xs))
    set_output(block, op, "Y", shape, xs[0].dtype)


@register_op("stack", infer_shape=_stack_infer)
def _stack(ctx, ins, attrs):
    xs = [data(v) for v in ins["X"] if v is not None]
    return {"Y": [jnp.stack(xs, axis=attrs.get("axis", 0))]}


def _unstack_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    axis = op.attr("axis", 0)
    rank = len(x.shape)
    axis = axis + rank if axis < 0 else axis
    shape = [d for i, d in enumerate(x.shape) if i != axis]
    for i in range(len(op.output("Y"))):
        set_output(block, op, "Y", shape, x.dtype, idx=i)


@register_op("unstack", infer_shape=_unstack_infer)
def _unstack(ctx, ins, attrs):
    x = data(ins["X"][0])
    axis = attrs.get("axis", 0)
    num = attrs.get("num", x.shape[axis])
    outs = [jnp.squeeze(s, axis=axis) for s in jnp.split(x, num, axis=axis)]
    return {"Y": outs}


def _slice_infer(op, block):
    x = in_desc(op, block, "Input")
    if x is None:
        return
    shape = list(x.shape)
    axes = op.attr("axes", [])
    starts = op.attr("starts", [])
    ends = op.attr("ends", [])
    for a, s, e in zip(axes, starts, ends):
        d = shape[a]
        if d < 0:
            continue
        s2 = max(0, s + d if s < 0 else s)
        e2 = min(d, e + d if e < 0 else e)
        shape[a] = max(0, e2 - s2)
    set_output(block, op, "Out", shape, x.dtype)


@register_op("slice", infer_shape=_slice_infer, diff_inputs=["Input"])
def _slice(ctx, ins, attrs):
    x = data(ins["Input"][0])
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        idx[a] = slice(s, e)
    return {"Out": [x[tuple(idx)]]}


def _gather_infer(op, block):
    x = in_desc(op, block, "X")
    index = in_desc(op, block, "Index")
    if x is None or index is None:
        return
    set_output(block, op, "Out", [index.shape[0]] + list(x.shape[1:]), x.dtype)


@register_op("gather", infer_shape=_gather_infer, diff_inputs=["X"])
def _gather(ctx, ins, attrs):
    x, idx = data(ins["X"][0]), data(ins["Index"][0])
    return {"Out": [jnp.take(x, idx.reshape(-1), axis=0)]}


@register_op("scatter", infer_shape=same_shape(), diff_inputs=["X", "Updates"])
def _scatter(ctx, ins, attrs):
    """Out = X with rows at Ids replaced (or accumulated) by Updates
    (reference: operators/scatter_op.cc)."""
    x = data(ins["X"][0])
    ids = data(ins["Ids"][0]).reshape(-1)
    upd = data(ins["Updates"][0])
    if attrs.get("overwrite", True):
        out = x.at[ids].set(upd)
    else:
        out = x.at[ids].add(upd)
    return {"Out": [out]}


def _pad_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    paddings = op.attr("paddings", [])
    shape = [
        d if d < 0 else d + paddings[2 * i] + paddings[2 * i + 1]
        for i, d in enumerate(x.shape)
    ]
    set_output(block, op, "Out", shape, x.dtype)


@register_op("pad", infer_shape=_pad_infer)
def _pad(ctx, ins, attrs):
    x = data(ins["X"][0])
    p = attrs["paddings"]
    widths = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, widths, constant_values=attrs.get("pad_value", 0.0))]}


def _pad2d_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    p = op.attr("paddings", [0, 0, 0, 0])
    shape = list(x.shape)
    if op.attr("data_format", "NCHW") == "NCHW":
        h_axis, w_axis = 2, 3
    else:
        h_axis, w_axis = 1, 2
    if shape[h_axis] >= 0:
        shape[h_axis] += p[0] + p[1]
    if shape[w_axis] >= 0:
        shape[w_axis] += p[2] + p[3]
    set_output(block, op, "Out", shape, x.dtype)


@register_op("pad2d", infer_shape=_pad2d_infer)
def _pad2d(ctx, ins, attrs):
    x = data(ins["X"][0])
    p = attrs.get("paddings", [0, 0, 0, 0])
    mode = attrs.get("mode", "constant")
    nchw = attrs.get("data_format", "NCHW") == "NCHW"
    widths = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])] if nchw else [
        (0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)
    ]
    jmode = {"constant": "constant", "reflect": "reflect", "edge": "edge"}[mode]
    kw = {"constant_values": attrs.get("pad_value", 0.0)} if mode == "constant" else {}
    return {"Out": [jnp.pad(x, widths, mode=jmode, **kw)]}


@register_op("pad_constant_like", infer_shape=same_shape("X", "Out"), diff_inputs=["Y"])
def _pad_constant_like(ctx, ins, attrs):
    x, y = data(ins["X"][0]), data(ins["Y"][0])
    widths = [(0, xd - yd) for xd, yd in zip(x.shape, y.shape)]
    return {"Out": [jnp.pad(y, widths, constant_values=attrs.get("pad_value", 0.0))]}


def _expand_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    times = op.attr("expand_times", [])
    shape = [d if d < 0 else d * t for d, t in zip(x.shape, times)]
    set_output(block, op, "Out", shape, x.dtype)


@register_op("expand", infer_shape=_expand_infer)
def _expand(ctx, ins, attrs):
    x = data(ins["X"][0])
    return {"Out": [jnp.tile(x, attrs["expand_times"])]}


@register_op("reverse", infer_shape=same_shape())
def _reverse(ctx, ins, attrs):
    x = data(ins["X"][0])
    axes = attrs.get("axis", [0])
    if isinstance(axes, int):
        axes = [axes]
    out = x
    for a in axes:
        out = jnp.flip(out, axis=a)
    return {"Out": [out]}


def _one_hot_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    depth = op.attr("depth", 1)
    shape = list(x.shape)
    if shape and shape[-1] == 1:
        shape = shape[:-1]
    set_output(block, op, "Out", shape + [depth], DataType.FP32)


@register_op("one_hot", infer_shape=_one_hot_infer, no_grad=True)
def _one_hot(ctx, ins, attrs):
    x = data(ins["X"][0])
    # squeeze the fluid [N, 1] id column — decided by the DESC rank, not the
    # runtime shape (a [N] input with N == 1 must not collapse to a scalar)
    desc_rank = None
    op = getattr(ctx, "cur_op", None) if ctx is not None else None
    if op is not None:
        names = op.input("X")
        v = ctx.block._find_var_recursive(names[0]) if names else None
        if v is not None and v.desc.shape:
            desc_rank = len(v.desc.shape)
    squeeze = (
        x.ndim == desc_rank if desc_rank is not None else x.ndim > 1
    ) and x.ndim and x.shape[-1] == 1 and (desc_rank or 2) > 1
    if squeeze:
        x = jnp.squeeze(x, axis=-1)
    return {"Out": [jax.nn.one_hot(x, attrs["depth"], dtype=jnp.float32)]}


@register_op("shape", infer_shape=lambda op, block: set_output(block, op, "Out", [len(in_desc(op, block, "Input").shape)], DataType.INT32), no_grad=True)
def _shape(ctx, ins, attrs):
    x = data(ins["Input"][0])
    return {"Out": [jnp.asarray(x.shape, dtype=jnp.int32)]}


def _lookup_infer(op, block):
    w = in_desc(op, block, "W")
    ids = in_desc(op, block, "Ids")
    if w is None or ids is None:
        return
    shape = list(ids.shape)
    if shape and shape[-1] == 1:
        shape = shape[:-1]
    set_output(block, op, "Out", shape + [w.shape[1]], w.dtype, lod_level=ids.lod_level)


@register_op("lookup_table", infer_shape=_lookup_infer, diff_inputs=["W"])
def _lookup_table(ctx, ins, attrs):
    """Embedding lookup (reference: operators/lookup_table_op.cc)."""
    w = data(ins["W"][0])
    ids = data(ins["Ids"][0])
    squeeze_last = ids.ndim >= 1 and ids.shape[-1] == 1
    if squeeze_last:
        ids = jnp.squeeze(ids, axis=-1)
    padding_idx = attrs.get("padding_idx", -1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return {"Out": [wrap_lod(ins["Ids"][0], out)]}


@register_op("lookup_table_grad", no_grad=True)
def _lookup_table_grad(ctx, ins, attrs):
    """Custom grad rule for lookup_table (replaces the vjp replay).

    The reference emits SelectedRows sparse grads
    (operators/lookup_table_op.cc:80 + framework/selected_rows.h:32) so a
    [V, D] table gradient is (ids, rows), not a dense table — essential at
    CTR vocab sizes.  With is_sparse=True this returns a SelectedRowsValue
    ([N] ids + [N, D] rows, V absent from every runtime buffer); sparse
    optimizer lowerings (ops/optimizer_ops.py) then update only the touched
    rows.  With is_sparse=False it scatter-adds into a dense table grad,
    identical to the vjp of jnp.take."""
    w_desc = ins["W"][0]
    og = data(ins["Out@GRAD"][0])
    ids = data(ins["Ids"][0])
    if ids.ndim >= 1 and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, axis=-1)
    height, dim = data(w_desc).shape
    ids_flat = jnp.reshape(ids, (-1,))
    if ids_flat.dtype.itemsize <= 4:
        ids_flat = ids_flat.astype(jnp.int32)
    # 64-bit ids (x64 mode) keep their width: the scatter target height
    # may exceed 2**31 for hashed/CTR id spaces
    rows = jnp.reshape(og, (-1, dim))
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        # grads at the padding id are dropped, as in the forward mask;
        # pointing them at the sentinel makes the scatter drop them
        ids_flat = jnp.where(ids_flat == padding_idx, height, ids_flat)
    srv = SelectedRowsValue(ids_flat, rows, height)
    if attrs.get("is_sparse", False):
        return {"W@GRAD": [srv]}
    return {"W@GRAD": [srv.to_dense()]}


@register_op("multiplex", infer_shape=lambda op, block: set_output(block, op, "Out", in_desc(op, block, "X").shape, in_desc(op, block, "X").dtype), diff_inputs=["X"])
def _multiplex(ctx, ins, attrs):
    ids = data(ins["Ids"][0]).reshape(-1)
    xs = jnp.stack([data(v) for v in ins["X"]], axis=0)
    rows = jnp.arange(xs.shape[1])
    return {"Out": [xs[ids[: xs.shape[1]], rows]]}


def _crop_infer(op, block):
    shape = list(op.attr("shape", []))
    x = in_desc(op, block, "X")
    if x is None:
        return
    set_output(block, op, "Out", shape or list(x.shape), x.dtype)


@register_op("crop", infer_shape=_crop_infer, diff_inputs=["X"])
def _crop(ctx, ins, attrs):
    x = data(ins["X"][0])
    offsets = attrs.get("offsets", [0] * x.ndim)
    shape = attrs.get("shape", list(x.shape))
    # -1 keeps the full extent from the offset (desc batch dims are -1)
    idx = tuple(
        slice(o, None) if s < 0 else slice(o, o + s)
        for o, s in zip(offsets, shape)
    )
    return {"Out": [x[idx]]}


def _space_to_depth_infer(op, block):
    x = in_desc(op, block, "X")
    if x is None:
        return
    b = op.attr("blocksize", 1)
    n, c, h, w = x.shape
    if c > 0 and c % (b * b):
        # reference InferShape enforce (space_to_depth_op.cc:41): the
        # reorg kernel scatters with depth-to-space indexing, so input
        # channels must be divisible by blocksize^2 even in the
        # space-to-depth direction
        raise ValueError(
            f"space_to_depth: input channels {c} must be divisible by "
            f"blocksize^2 ({b * b})")
    if (h > 0 and h % b) or (w > 0 and w % b):
        # companion enforces, space_to_depth_op.cc:44-49
        raise ValueError(
            f"space_to_depth: input H/W ({h}x{w}) must be divisible by "
            f"blocksize ({b})")
    set_output(block, op, "Out", [n, c * b * b, h // b if h > 0 else -1, w // b if w > 0 else -1], x.dtype)


@register_op("space_to_depth", infer_shape=_space_to_depth_infer)
def _space_to_depth(ctx, ins, attrs):
    """Darknet-reorg layout compatibility (reference:
    operators/space_to_depth_op.h:40-56): the kernel writes the input
    through DEPTH-TO-SPACE scatter indexing — channel k decomposes as
    (offset, c2) with h2 = j*bs + offset/bs, w2 = i*bs + offset%bs into a
    [N, C/bs^2, H*bs, W*bs] view — and the Out buffer is then READ with
    the declared [N, C*bs^2, H/bs, W/bs] shape.  YOLO-era models were
    trained against exactly this scramble, so it is the contract; a
    textbook block-to-channel space_to_depth does NOT match."""
    x = data(ins["X"][0])
    b = attrs["blocksize"]
    n, c, h, w = x.shape
    out_c = c // (b * b)
    y = jnp.reshape(x, (n, b, b, out_c, h, w))       # k = (oy, ox, c2)
    y = jnp.transpose(y, (0, 3, 4, 1, 5, 2))         # [n, c2, h, oy, w, ox]
    y = jnp.reshape(y, (n, out_c, h * b, w * b))     # depth-to-space image
    return {"Out": [jnp.reshape(y, (n, c * b * b, h // b, w // b))]}


def _range_static_len(op):
    a = op.attrs
    if all(f"const_{k}" in a for k in ("start", "end", "step")):
        import math

        return max(0, math.ceil((a["const_end"] - a["const_start"]) / a["const_step"]))
    return -1


def _range_infer(op, block):
    set_output(
        block, op, "Out", [_range_static_len(op)],
        DataType(op.attr("dtype", int(DataType.FP32))),
    )


@register_op("range", infer_shape=_range_infer, no_grad=True)
def _range(ctx, ins, attrs):
    def bound(slot):
        if f"const_{slot.lower()}" in attrs:
            return attrs[f"const_{slot.lower()}"]
        try:
            return float(np.asarray(data(ins[slot][0])).reshape(()))
        except Exception as e:
            raise NotImplementedError(
                "range requires compile-time-constant Start/End/Step: the "
                "output length sets a static XLA shape, so data-dependent "
                "bounds cannot be lowered"
            ) from e

    start, end, step = bound("Start"), bound("End"), bound("Step")
    dtype = dtype_to_runtime(DataType(attrs.get("dtype", int(DataType.FP32))))
    return {"Out": [jnp.arange(start, end, step, dtype=dtype)]}


@register_op("increment", infer_shape=same_shape())
def _increment(ctx, ins, attrs):
    x = data(ins["X"][0])
    # keep the input dtype: int64 counters must not promote to float
    step = np.asarray(attrs.get("step", 1.0)).astype(jnp.asarray(x).dtype if not hasattr(x, "dtype") else x.dtype)
    return {"Out": [x + step]}


@register_op("label_smooth", infer_shape=same_shape())
def _label_smooth(ctx, ins, attrs):
    x = data(ins["X"][0])
    eps = attrs.get("epsilon", 0.0)
    dist = ins.get("PriorDist", [None])[0]
    if dist is not None:
        out = (1.0 - eps) * x + eps * data(dist)
    else:
        out = (1.0 - eps) * x + eps / x.shape[-1]
    return {"Out": [out]}


@register_op("is_empty", infer_shape=lambda op, block: set_output(block, op, "Out", [1], DataType.BOOL), no_grad=True)
def _is_empty(ctx, ins, attrs):
    x = data(ins["X"][0])
    return {"Out": [jnp.asarray([x.size == 0])]}


@register_op("gaussian_random_batch_size_like", infer_shape=_fill_bsl_infer, no_grad=True, random=True)
def _gaussian_random_bsl(ctx, ins, attrs):
    x = data(ins["Input"][0])
    shape = [int(d) for d in attrs["shape"]]
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    dtype = dtype_to_runtime(DataType(attrs.get("dtype", int(DataType.FP32))))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.normal(
        ctx.rng(), shape, dtype=dtype
    )
    return {"Out": [out]}


def _bool_scalar_infer(op, block):
    set_output(block, op, "Out", [1], DataType.BOOL)


@register_op("isinf", infer_shape=_bool_scalar_infer, no_grad=True)
def _isinf(ctx, ins, attrs):
    return {"Out": [jnp.reshape(jnp.any(jnp.isinf(data(ins["X"][0]))), (1,))]}


@register_op("isnan", infer_shape=_bool_scalar_infer, no_grad=True)
def _isnan(ctx, ins, attrs):
    return {"Out": [jnp.reshape(jnp.any(jnp.isnan(data(ins["X"][0]))), (1,))]}


@register_op("isfinite", infer_shape=_bool_scalar_infer, no_grad=True)
def _isfinite(ctx, ins, attrs):
    return {"Out": [jnp.reshape(jnp.all(jnp.isfinite(data(ins["X"][0]))), (1,))]}
