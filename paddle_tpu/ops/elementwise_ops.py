"""Elementwise binary ops with Fluid broadcasting semantics.

Reference: paddle/fluid/operators/elementwise/ (REGISTER_ELEMWISE_OP macro
family) — Y broadcasts as a contiguous sub-shape of X anchored at `axis`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core import amp
from ..core.lod import LoDValue
from ..core.registry import register_op
from .common import broadcast_y, data, elemwise_shape, wrap_lod


def _make(name, fn):
    @register_op(name, infer_shape=elemwise_shape)
    def _lower(ctx, ins, attrs, _fn=fn):
        x, y = ins["X"][0], ins["Y"][0]
        axis = attrs.get("axis", -1)
        # a LoD X's padded value has an extra time dim vs its desc, so a
        # desc-relative axis shifts right by one
        if isinstance(x, LoDValue) and not isinstance(y, LoDValue) and axis >= 0:
            axis += 1
        yb = broadcast_y(data(x), data(y), axis)
        # amp keep_output: an fp32 bias/scale must not re-widen a bf16
        # activation chain through numpy promotion
        xd, yb = amp.match_kept(data(x), yb)
        return {"Out": [wrap_lod(x, _fn(xd, yb))]}

    return _lower


_make("elementwise_add", lambda x, y: x + y)
_make("elementwise_sub", lambda x, y: x - y)
_make("elementwise_mul", lambda x, y: x * y)
_make("elementwise_div", lambda x, y: x / y)
_make("elementwise_max", jnp.maximum)
_make("elementwise_min", jnp.minimum)
_make("elementwise_pow", jnp.power)
# C++ truncated semantics (sign of the dividend), matching the reference's
# % / fmod kernels — NOT python/numpy floored mod
_make("elementwise_mod", lambda x, y: jnp.fmod(x, y))
_make(
    "elementwise_floordiv",
    lambda x, y: jnp.trunc(jnp.true_divide(x, y)).astype(
        jnp.result_type(x, y)
    ),
)
