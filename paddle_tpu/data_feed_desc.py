"""DataFeedDesc (reference: python/paddle/fluid/data_feed_desc.py over
paddle/fluid/framework/data_feed.proto:26).

Parses the protobuf-text data-feed description used by AsyncExecutor's
MultiSlot format.  Only the fields the MultiSlot feed consumes are
understood (name, batch_size, multi_slot_desc.slots)."""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List

__all__ = ["DataFeedDesc", "SlotDesc"]


@dataclass
class SlotDesc:
    name: str
    type: str = "uint64"  # "uint64" (sparse ids) | "float"
    is_dense: bool = False
    is_used: bool = True


@dataclass
class DataFeedDesc:
    """Construct from protobuf-text (reference: data_feed_desc.py parses with
    google.protobuf.text_format)."""

    proto_desc: str = ""
    name: str = "MultiSlotDataFeed"
    batch_size: int = 1
    slots: List[SlotDesc] = field(default_factory=list)

    def __post_init__(self):
        if self.proto_desc:
            self._parse(self.proto_desc)

    def _parse(self, text: str) -> None:
        m = re.search(r'name:\s*"([^"]+)"', text)
        if m:
            self.name = m.group(1)
        m = re.search(r"batch_size:\s*(\d+)", text)
        if m:
            self.batch_size = int(m.group(1))
        self.slots = []
        for sm in re.finditer(r"slots?\s*\{([^}]*)\}", text):
            body = sm.group(1)
            nm = re.search(r'name:\s*"([^"]+)"', body)
            tp = re.search(r'type:\s*"([^"]+)"', body)
            dense = re.search(r"is_dense:\s*(true|false)", body)
            used = re.search(r"is_used:\s*(true|false)", body)
            self.slots.append(
                SlotDesc(
                    name=nm.group(1) if nm else "",
                    type=tp.group(1) if tp else "uint64",
                    is_dense=bool(dense and dense.group(1) == "true"),
                    is_used=not used or used.group(1) == "true",
                )
            )

    # reference API surface
    def set_batch_size(self, batch_size: int) -> None:
        self.batch_size = int(batch_size)

    def set_dense_slots(self, dense_slots_name) -> None:
        names = set(dense_slots_name)
        for s in self.slots:
            if s.name in names:
                s.is_dense = True

    def set_use_slots(self, use_slots_name) -> None:
        names = set(use_slots_name)
        for s in self.slots:
            s.is_used = s.name in names

    def desc(self) -> str:
        lines = [f'name: "{self.name}"', f"batch_size: {self.batch_size}",
                 "multi_slot_desc {"]
        for s in self.slots:
            lines.append(
                f'  slots {{ name: "{s.name}" type: "{s.type}" '
                f"is_dense: {str(s.is_dense).lower()} "
                f"is_used: {str(s.is_used).lower()} }}"
            )
        lines.append("}")
        return "\n".join(lines)
