"""LayerHelper: shared plumbing for layer functions
(reference: python/paddle/fluid/layer_helper.py) — creates parameters in the
startup+main programs, temp output vars, bias add and activation tails.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

from .core.framework import (
    Parameter,
    Variable,
    default_main_program,
    default_startup_program,
    unique_name,
)
from .core.proto import DataType
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr, WeightNormParamAttr

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs: Any):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    def append_op(self, *args, **kwargs):
        return self.block.append_op(*args, **kwargs)

    # -- inputs --------------------------------------------------------------
    def multiple_input(self, input_param_name: str = "input") -> List[Variable]:
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, Variable):
            return [inputs]
        return list(inputs)

    def input(self, input_param_name: str = "input") -> Variable:
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError(f"{self.layer_type} expects one input")
        return inputs[0]

    def input_dtype(self, input_param_name: str = "input"):
        dtype = None
        for v in self.multiple_input(input_param_name):
            if dtype is None:
                dtype = v.dtype
        return dtype

    # -- params --------------------------------------------------------------
    @property
    def param_attr(self) -> Optional[ParamAttr]:
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self) -> Optional[ParamAttr]:
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def get_parameter(self, name: str) -> Parameter:
        """Find an existing Parameter by name (reference:
        layer_helper.py get_parameter)."""
        param = self.main_program.global_block().vars.get(name)
        if not isinstance(param, Parameter):
            raise ValueError(f"no parameter named '{name}'")
        return param

    def create_parameter(
        self,
        attr: Optional[ParamAttr],
        shape,
        dtype,
        is_bias: bool = False,
        default_initializer=None,
    ) -> Optional[Parameter]:
        if attr is None:
            return None
        if attr is False:
            return None
        if not isinstance(attr, ParamAttr):
            attr = ParamAttr._to_attr(attr)
        if isinstance(attr, WeightNormParamAttr) and not is_bias:
            return self._create_weight_normalize(
                attr, shape, dtype, default_initializer
            )
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()
        name = attr.name or unique_name(f"{self.name}.w" if not is_bias else f"{self.name}.b")

        startup_block = self.startup_program.global_block()
        sv = startup_block.create_var(
            name=name, shape=list(shape), dtype=dtype, persistable=True
        )
        init(sv, startup_block)

        kwargs = attr._to_kwargs()
        kwargs["name"] = name
        param = self.main_program.global_block().create_parameter(
            shape=list(shape), dtype=dtype, **kwargs
        )
        if attr.sharding is not None:
            param.sharding = attr.sharding
        return param

    def _create_weight_normalize(self, attr, shape, dtype,
                                 default_initializer=None) -> Variable:
        """Weight normalization (reference: layer_helper.py
        _create_weight_normalize; Salimans & Kingma 2016): the trainable
        parameters are the direction v and per-`dim` magnitudes g; the
        layer consumes w = g * v / ||v||, recomputed each step in the
        main program.  g initializes to ||v_0|| in the startup program so
        training starts at the conventional parameterization."""
        dim = attr.dim
        if dim is not None:
            dim = int(dim) % len(shape)  # accept negative dims
        base = attr.name or unique_name(f"{self.name}.w")

        def derived_attr(suffix, initializer, sharding):
            # carry EVERY per-parameter setting of the user's attr (clip,
            # model-average, sharding included) onto v and g
            return ParamAttr(
                name=base + suffix, initializer=initializer,
                learning_rate=attr.learning_rate,
                regularizer=attr.regularizer, trainable=attr.trainable,
                gradient_clip=attr.gradient_clip,
                do_model_average=attr.do_model_average,
                sharding=sharding,
            )

        v = self.create_parameter(
            derived_attr(".w_v", attr.initializer, attr.sharding), shape,
            dtype, default_initializer=default_initializer,
        )
        k = 1 if dim is None else int(shape[dim])
        # g is rank-1 over the kept dim: its sharding is that dim's axis
        g_sharding = (
            [attr.sharding[dim]]
            if attr.sharding is not None and dim is not None else None
        )
        g = self.create_parameter(
            derived_attr(".w_g", None, g_sharding), [k], dtype,
            default_initializer=ConstantInitializer(1.0),
        )

        # startup: overwrite g's placeholder init with ||v_0||
        startup = self.startup_program.global_block()
        counter = [0]

        def sname(tag):
            counter[0] += 1
            return f"{base}.{tag}.init{counter[0]}"

        _norm_except_dim_ops(startup, v.name, g.name, shape, dim, dtype,
                             sname, keep_dim=False)

        # main program: w = v * g / ||v||
        main = self.main_program.global_block()
        w = main.create_var(name=base, shape=list(shape), dtype=dtype)
        _weight_norm_ops(main, v.name, g.name, w.name, shape, dim, dtype,
                         lambda tag: unique_name(f"{base}.{tag}"))
        return w

    # -- outputs -------------------------------------------------------------
    def create_variable_for_type_inference(self, dtype, stop_gradient: bool = False) -> Variable:
        return self.block.create_var(
            name=unique_name(f"{self.name}.tmp"),
            dtype=dtype,
            shape=[],
            stop_gradient=stop_gradient,
        )

    create_tmp_variable = create_variable_for_type_inference

    def create_global_variable(self, persistable: bool = False, **kwargs) -> Variable:
        return self.main_program.global_block().create_var(
            name=unique_name(f"{self.name}.global"),
            persistable=persistable,
            **kwargs,
        )

    def set_variable_initializer(self, var: Variable, initializer):
        startup_block = self.startup_program.global_block()
        sv = startup_block.create_var(
            name=var.name, shape=list(var.shape), dtype=var.dtype, persistable=True
        )
        initializer(sv, startup_block)

    # -- tails ---------------------------------------------------------------
    def append_bias_op(self, input_var: Variable, dim_start: int = 1, dim_end=None) -> Variable:
        size = list(input_var.shape)[dim_start:dim_end]
        bias_attr = self.bias_attr
        if bias_attr is None:
            return input_var
        b = self.create_parameter(bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [out]},
            attrs={"axis": dim_start},
        )
        return out

    def append_activation(self, input_var: Variable) -> Variable:
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = dict(act)
        act_type = act.pop("type")
        out = self.create_variable_for_type_inference(input_var.dtype)
        self.append_op(
            type=act_type, inputs={"X": [input_var]}, outputs={"Out": [out]}, attrs=act
        )
        return out


def _norm_except_dim_ops(block, v_name, out_name, shape, dim, dtype,
                         name_fn, keep_dim):
    """Append ||v|| over every axis except `dim` (the reference's
    __norm_except_dim: square -> reduce_sum -> sqrt) writing `out_name`."""
    rank = len(shape)
    axes = [i for i in range(rank) if dim is None or i != dim]
    if keep_dim:
        out_shape = [1] * rank
        if dim is not None:
            out_shape[dim] = int(shape[dim])
    else:
        out_shape = [1] if dim is None else [int(shape[dim])]
    sq = block.create_var(name=name_fn("weight_norm_sq"), shape=list(shape),
                          dtype=dtype)
    block.append_op(type="square", inputs={"X": [v_name]},
                    outputs={"Out": [sq]})
    ssum = block.create_var(name=name_fn("weight_norm_sum"),
                            shape=out_shape, dtype=dtype)
    block.append_op(type="reduce_sum", inputs={"X": [sq]},
                    outputs={"Out": [ssum]},
                    attrs={"dim": axes, "keep_dim": keep_dim,
                           "reduce_all": dim is None})
    block.append_op(type="sqrt", inputs={"X": [ssum]},
                    outputs={"Out": [out_name]})
    return out_shape


def _weight_norm_ops(block, v_name, g_name, out_name, shape, dim, dtype,
                     name_fn):
    """Append w = v * g / ||v||  ops to `block` (norm over every axis
    except `dim`, the reference's __norm_except_dim)."""
    norm = block.create_var(name=name_fn("weight_norm_norm"), dtype=dtype)
    bshape = _norm_except_dim_ops(block, v_name, norm.name, shape, dim,
                                  dtype, name_fn, keep_dim=True)

    def tmp(tag):
        return block.create_var(name=name_fn(tag), shape=list(bshape),
                                dtype=dtype)

    g2 = tmp("weight_norm_g_reshaped")
    block.append_op(type="reshape", inputs={"X": [g_name]},
                    outputs={"Out": [g2]}, attrs={"shape": bshape})
    scale = tmp("weight_norm_scale")
    block.append_op(type="elementwise_div", inputs={"X": [g2], "Y": [norm]},
                    outputs={"Out": [scale]}, attrs={"axis": -1})
    block.append_op(type="elementwise_mul",
                    inputs={"X": [v_name], "Y": [scale]},
                    outputs={"Out": [out_name]}, attrs={"axis": -1})
