"""Host-side metric accumulators (reference: python/paddle/fluid/metrics.py).

In-graph metric *ops* (accuracy, auc, mean_iou...) live in
paddle_tpu/ops/metric_ops.py; these classes accumulate fetched numpy values
across batches on the host, mirroring the reference class-for-class.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "MetricBase",
    "CompositeMetric",
    "Precision",
    "Recall",
    "Accuracy",
    "ChunkEvaluator",
    "EditDistance",
    "DetectionMAP",
    "Auc",
]


def _to_np(x):
    return np.asarray(x)


class MetricBase:
    """reference: metrics.py MetricBase."""

    def __init__(self, name=None):
        self._name = str(name) if name is not None else self.__class__.__name__

    def __str__(self):
        return self._name

    def reset(self):
        for attr, value in self.__dict__.items():
            if attr.startswith("_"):
                continue
            if isinstance(value, int):
                setattr(self, attr, 0)
            elif isinstance(value, float):
                setattr(self, attr, 0.0)
            elif isinstance(value, (np.ndarray,)):
                setattr(self, attr, np.zeros_like(value))
            elif isinstance(value, (list,)):
                setattr(self, attr, [])

    def get_config(self):
        return {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith("_")
        }

    def update(self, preds, labels):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    """Bundle several metrics updated with the same (preds, labels)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise ValueError("add_metric expects a MetricBase instance")
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]

    def reset(self):
        for m in self._metrics:
            m.reset()


class Precision(MetricBase):
    """Binary precision over 0/1 preds (reference: metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).astype(np.int64).reshape(-1)
        labels = _to_np(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(_to_np(preds)).astype(np.int64).reshape(-1)
        labels = _to_np(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else 0.0


class Accuracy(MetricBase):
    """Weighted running accuracy (update takes per-batch accuracy values,
    as fetched from the in-graph accuracy op)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        if weight < 0:
            raise ValueError("weight must be non-negative")
        self.value += float(np.ravel(_to_np(value))[0]) * float(weight)
        self.weight += float(weight)

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy has accumulated no batches")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """F1 over chunk counts fetched from the chunk_eval op
    (reference: metrics.py ChunkEvaluator)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.ravel(_to_np(num_infer_chunks))[0])
        self.num_label_chunks += int(np.ravel(_to_np(num_label_chunks))[0])
        self.num_correct_chunks += int(np.ravel(_to_np(num_correct_chunks))[0])

    def eval(self):
        precision = (
            float(self.num_correct_chunks) / self.num_infer_chunks
            if self.num_infer_chunks
            else 0.0
        )
        recall = (
            float(self.num_correct_chunks) / self.num_label_chunks
            if self.num_label_chunks
            else 0.0
        )
        f1 = (
            2 * precision * recall / (precision + recall)
            if self.num_correct_chunks
            else 0.0
        )
        return precision, recall, f1


class EditDistance(MetricBase):
    """Average edit distance + instance error rate
    (reference: metrics.py EditDistance)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = _to_np(distances).reshape(-1)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances != 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance has accumulated no sequences")
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class Auc(MetricBase):
    """Batch-accumulated ROC AUC via threshold buckets
    (reference: metrics.py Auc; matches the auc op's algorithm)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        bins = num_thresholds + 1
        # non-underscore so MetricBase.reset zeroes them
        self.stat_pos = np.zeros(bins, dtype=np.int64)
        self.stat_neg = np.zeros(bins, dtype=np.int64)

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).reshape(-1).astype(bool)
        pos_prob = preds[:, -1] if preds.ndim == 2 else preds.reshape(-1)
        idx = np.minimum(
            (pos_prob * self._num_thresholds).astype(np.int64),
            self._num_thresholds,
        )
        bins = self._num_thresholds + 1
        self.stat_pos += np.bincount(idx[labels], minlength=bins)
        self.stat_neg += np.bincount(idx[~labels], minlength=bins)

    @staticmethod
    def trapezoid_area(x1, x2, y1, y2):
        return abs(x1 - x2) * (y1 + y2) / 2.0

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        idx = self._num_thresholds
        while idx >= 0:
            tot_pos_prev = tot_pos
            tot_neg_prev = tot_neg
            tot_pos += self.stat_pos[idx]
            tot_neg += self.stat_neg[idx]
            auc += self.trapezoid_area(
                tot_neg, tot_neg_prev, tot_pos, tot_pos_prev
            )
            idx -= 1
        return (
            auc / tot_pos / tot_neg if tot_pos > 0.0 and tot_neg > 0.0 else 0.0
        )


class DetectionMAP(MetricBase):
    """Running mean of per-batch mAP values fetched from the detection_map op
    (reference: metrics.py DetectionMAP)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.has_state = None

    def get_map_var(self):
        return self.has_state

    def update(self, value, weight):
        if not hasattr(self, "value"):
            self.value = 0.0
            self.weight = 0.0
        self.value += float(np.ravel(_to_np(value))[0]) * float(weight)
        self.weight += float(weight)

    def eval(self):
        if getattr(self, "weight", 0.0) == 0.0:
            raise ValueError("DetectionMAP has accumulated no batches")
        return self.value / self.weight
