"""Optimizer classes (reference: python/paddle/fluid/optimizer.py —
Optimizer base :43, minimize :294 = append_backward + clip/regularization +
_create_optimization_pass appending one optimizer *op* per parameter).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .clip import append_gradient_clip_ops, error_clip_callback
from .core.backward import append_backward
from .core.framework import (
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    program_guard,
    unique_name,
)
from .core.proto import DataType
from .core.scope import global_scope
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops

__all__ = [
    "Optimizer",
    "SGD",
    "SGDOptimizer",
    "Momentum",
    "MomentumOptimizer",
    "LarsMomentum",
    "LarsMomentumOptimizer",
    "Adagrad",
    "AdagradOptimizer",
    "DecayedAdagrad",
    "DecayedAdagradOptimizer",
    "Adam",
    "AdamOptimizer",
    "Adamax",
    "AdamaxOptimizer",
    "Adadelta",
    "AdadeltaOptimizer",
    "RMSProp",
    "RMSPropOptimizer",
    "Ftrl",
    "FtrlOptimizer",
    "ModelAverage",
    "ProximalGDOptimizer",
    "ProximalAdagradOptimizer",
    "ProximalGD",
    "ProximalAdagrad",
    "GradientMergeOptimizer",
]


def _create_persistable_zeros(name, shape, dtype):
    """Persistable main-program var zero-initialized by the startup program
    (shared by ModelAverage / GradientMergeOptimizer accumulators)."""
    gblock = default_main_program().global_block()
    sblock = default_startup_program().global_block()
    v = gblock.create_var(name=name, shape=list(shape), dtype=dtype,
                          persistable=True, stop_gradient=True)
    sv = sblock.create_var(name=name, shape=list(shape), dtype=dtype,
                           persistable=True)
    ConstantInitializer(0.0)(sv, sblock)
    return v


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._learning_rate_map: Dict[int, Variable] = {}
        # accumulators[acc_name][param_name] -> Variable
        self._accumulators: Dict[str, Dict[str, Variable]] = defaultdict(dict)
        self.helper: Optional[LayerHelper] = None

    # -- learning rate -------------------------------------------------------
    def _create_global_learning_rate(self):
        program = default_main_program()
        lr = self._learning_rate_map.get(id(program))
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[id(program)] = self._learning_rate
            return
        name = unique_name("learning_rate")
        lr_var = program.global_block().create_var(
            name=name, shape=[1], dtype="float32", persistable=True, stop_gradient=True
        )
        startup = default_startup_program().global_block()
        sv = startup.create_var(name=name, shape=[1], dtype="float32", persistable=True)
        ConstantInitializer(float(self._learning_rate))(sv, startup)
        self._learning_rate_map[id(program)] = lr_var

    def _global_learning_rate(self, program: Optional[Program] = None) -> Variable:
        program = program or default_main_program()
        return self._learning_rate_map[id(program)]

    def _create_param_lr(self, param_and_grad) -> Variable:
        """Per-param LR scaling via ParamAttr learning_rate (reference:
        optimizer.py _create_param_lr)."""
        param = param_and_grad[0]
        base = self._global_learning_rate()
        param_lr = getattr(param, "optimize_attr", {}).get("learning_rate", 1.0)
        if param_lr == 1.0:
            return base
        from . import layers

        return layers.scale(base, scale=float(param_lr))

    # -- accumulators --------------------------------------------------------
    def _add_accumulator(self, name: str, param: Parameter, dtype=None,
                         fill_value: float = 0.0, shape=None) -> Variable:
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        program = default_main_program()
        var_name = unique_name(f"{param.name}_{name}")
        shape = list(shape if shape is not None else param.shape)
        var = program.global_block().create_var(
            name=var_name, shape=shape, dtype=dtype or param.dtype,
            persistable=True, stop_gradient=True,
        )
        startup = default_startup_program().global_block()
        sv = startup.create_var(name=var_name, shape=shape,
                                dtype=dtype or param.dtype, persistable=True)
        ConstantInitializer(fill_value)(sv, startup)
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name: str, param: Parameter) -> Variable:
        return self._accumulators[name][param.name]

    # -- hooks for subclasses ------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    # -- driver --------------------------------------------------------------
    def _create_optimization_pass(self, parameters_and_grads, loss, startup_program):
        program = loss.block.program
        # current block, not loss.block: a wrapping optimizer (GradientMerge)
        # places the apply ops inside a conditional sub-block
        block = program.current_block()
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(block, [p for p, g in parameters_and_grads if g is not None])
        optimize_ops = []
        for pg in parameters_and_grads:
            if pg[1] is None:
                continue
            optimize_ops.append(self._append_optimize_op(block, pg))
        self._finish_update(block, parameters_and_grads)
        return optimize_ops

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set)

    def apply_gradients(self, params_grads, loss=None, startup_program=None):
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads, self.regularization)
        return self._create_optimization_pass(params_grads, loss, startup_program)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """reference: optimizer.py:294 — backward + clip + regularization +
        optimizer ops."""
        with program_guard(loss.block.program, startup_program or default_startup_program()):
            params_grads = self.backward(loss, startup_program, parameter_list, no_grad_set)
            optimize_ops = self.apply_gradients(params_grads, loss, startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            type="sgd",
            inputs={"Param": [param], "Grad": [grad],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param]},
        )


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator(self._velocity_acc_str, param)
        return block.append_op(
            type="momentum",
            inputs={"Param": [param], "Grad": [grad], "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, lars_coeff=1e-3,
                 lars_weight_decay=5e-4, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator(self._velocity_acc_str, param)
        return block.append_op(
            type="lars_momentum",
            inputs={"Param": [param], "Grad": [grad], "Velocity": [velocity],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay},
        )


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        return block.append_op(
            type="adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon},
        )


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        return block.append_op(
            type="decayed_adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"
    _beta1_pow_acc_str = "beta1_pow_acc"
    _beta2_pow_acc_str = "beta2_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None, lazy_mode=False):
        super().__init__(learning_rate, regularization, name)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        # lazy_mode: sparse grads update only touched rows (TF LazyAdam
        # semantics); off by default for dense-equivalence
        self._lazy_mode = lazy_mode

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
            self._add_accumulator(self._beta1_pow_acc_str, p, shape=[1],
                                  fill_value=self._beta1)
            self._add_accumulator(self._beta2_pow_acc_str, p, shape=[1],
                                  fill_value=self._beta2)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator(self._moment1_acc_str, param)
        m2 = self._get_accumulator(self._moment2_acc_str, param)
        b1p = self._get_accumulator(self._beta1_pow_acc_str, param)
        b2p = self._get_accumulator(self._beta2_pow_acc_str, param)
        return block.append_op(
            type="adam",
            inputs={
                "Param": [param], "Grad": [grad],
                "Moment1": [m1], "Moment2": [m2],
                "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [param], "Moment1Out": [m1], "Moment2Out": [m2],
                "Beta1PowOut": [b1p], "Beta2PowOut": [b2p],
            },
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "lazy_mode": self._lazy_mode},
        )


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"
    _beta1_pow_acc_str = "beta1_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
            self._add_accumulator(self._beta1_pow_acc_str, p, shape=[1],
                                  fill_value=self._beta1)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        inf_norm = self._get_accumulator(self._inf_norm_acc_str, param)
        b1p = self._get_accumulator(self._beta1_pow_acc_str, param)
        op = block.append_op(
            type="adamax",
            inputs={
                "Param": [param], "Grad": [grad], "Moment": [moment],
                "InfNorm": [inf_norm], "Beta1Pow": [b1p],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "MomentOut": [moment],
                     "InfNormOut": [inf_norm]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )
        # advance beta1^t once per param (reference appends scale ops in
        # _finish_update; doing it inline keeps per-param state exact)
        block.append_op(
            type="scale", inputs={"X": [b1p]}, outputs={"Out": [b1p]},
            attrs={"scale": self._beta1},
        )
        return op


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        asg = self._get_accumulator(self._avg_squared_grad_acc_str, param)
        asu = self._get_accumulator(self._avg_squared_update_acc_str, param)
        return block.append_op(
            type="adadelta",
            inputs={"Param": [param], "Grad": [grad],
                    "AvgSquaredGrad": [asg], "AvgSquaredUpdate": [asu]},
            outputs={"ParamOut": [param], "AvgSquaredGradOut": [asg],
                     "AvgSquaredUpdateOut": [asu]},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"
    _mean_grad_acc_str = "mean_grad"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)
            self._add_accumulator(self._mean_grad_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        momentum = self._get_accumulator(self._momentum_acc_str, param)
        mean_square = self._get_accumulator(self._mean_square_acc_str, param)
        mean_grad = self._get_accumulator(self._mean_grad_acc_str, param)
        return block.append_op(
            type="rmsprop",
            inputs={"Param": [param], "Grad": [grad], "Moment": [momentum],
                    "MeanSquare": [mean_square], "MeanGrad": [mean_grad],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [momentum],
                     "MeanSquareOut": [mean_square], "MeanGradOut": [mean_grad]},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        sq = self._get_accumulator(self._squared_acc_str, param)
        lin = self._get_accumulator(self._linear_acc_str, param)
        return block.append_op(
            type="ftrl",
            inputs={"Param": [param], "Grad": [grad],
                    "SquaredAccumulator": [sq], "LinearAccumulator": [lin],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "SquaredAccumOut": [sq],
                     "LinearAccumOut": [lin]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class ModelAverage(Optimizer):
    """Parameter averaging for evaluation (reference: optimizer.py:1373 +
    operators/average_accumulates_op.cc).  Construct AFTER minimize(): one
    average_accumulates op per parameter maintains the reference's
    three-tier sliding window (sum_1 every step, drained into sum_2 every
    16384 updates for precision, both rotated into sum_3 when the window
    outgrows min(max_average_window, num_updates*average_window_rate)).
    apply() swaps parameters for (sum_1+sum_2+sum_3)/(num_accumulates +
    old_num_accumulates) inside a context manager; restore() puts the
    trained values back."""

    _ACC_SUMS = ("sum_1", "sum_2", "sum_3")
    _ACC_COUNTS = ("num_accumulates", "old_num_accumulates", "num_updates")

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, name)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        # param -> {acc role -> var name}; _param_sums keeps the historical
        # "one sum var per param" view (sum_1) for tools/tests
        self._param_accs: Dict[str, Dict[str, str]] = {}
        self._param_sums: Dict[str, str] = {}
        self._restore_vals: Dict[str, Any] = {}

        program = default_main_program()
        gblock = program.global_block()
        params = [
            v for v in gblock.vars.values() if isinstance(v, Parameter)
        ]

        for p in params:
            accs: Dict[str, str] = {}
            for role in self._ACC_SUMS:
                accs[role] = unique_name(f"{p.name}_avg_{role}")
                _create_persistable_zeros(accs[role], p.shape, p.dtype)
            for role in self._ACC_COUNTS:
                # int64: a fp32 counter saturates at 2^24 steps
                accs[role] = unique_name(f"{p.name}_avg_{role}")
                _create_persistable_zeros(accs[role], [1], "int64")
            gblock.append_op(
                type="average_accumulates",
                inputs={"param": [p.name],
                        **{f"in_{r}": [accs[r]]
                           for r in self._ACC_SUMS + self._ACC_COUNTS}},
                outputs={f"out_{r}": [accs[r]]
                         for r in self._ACC_SUMS + self._ACC_COUNTS},
                attrs={"average_window": float(self.average_window),
                       "min_average_window": int(self.min_average_window),
                       "max_average_window": int(self.max_average_window)},
            )
            self._param_accs[p.name] = accs
            self._param_sums[p.name] = accs["sum_1"]

    def _swap_in_averages(self, scope) -> None:
        import numpy as _np

        if self._restore_vals:
            raise RuntimeError(
                "ModelAverage.apply() re-entered without restore(); the "
                "trained parameters would be lost"
            )
        for p_name, accs in self._param_accs.items():
            vals = {r: scope.find_var(n) for r, n in accs.items()}
            cur = scope.find_var(p_name)
            if cur is None or any(v is None for v in vals.values()):
                continue
            total = sum(
                float(_np.ravel(_np.asarray(vals[r]))[0])
                for r in ("num_accumulates", "old_num_accumulates")
            )
            if total <= 0:
                continue
            # snapshot the param AND every accumulator: running the program
            # during apply() (evaluation) executes the accumulation ops
            # against the AVERAGED params, which must not pollute the
            # window after restore().  Host copies, not device handles —
            # the eval step DONATES the live state buffers.
            self._restore_vals[p_name] = _np.asarray(cur).copy()
            for r, n in accs.items():
                self._restore_vals[n] = _np.asarray(vals[r]).copy()
            avg = (
                _np.asarray(vals["sum_1"])
                + _np.asarray(vals["sum_2"])
                + _np.asarray(vals["sum_3"])
            ) / total
            scope.set_var(p_name, avg.astype(_np.asarray(cur).dtype))

    def apply(self, executor, need_restore=True):
        import contextlib

        scope = getattr(executor, "scope", None) or global_scope()

        @contextlib.contextmanager
        def _ctx():
            self._swap_in_averages(scope)
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)

        return _ctx()

    def restore(self, executor):
        scope = getattr(executor, "scope", None) or global_scope()
        for key, val in self._restore_vals.items():
            scope.set_var(key, val)
        self._restore_vals.clear()


SGD = SGDOptimizer
Momentum = MomentumOptimizer
LarsMomentum = LarsMomentumOptimizer
Adagrad = AdagradOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer


class ProximalGDOptimizer(Optimizer):
    """Proximal gradient descent with l1/l2 (reference: optimizer.py
    ProximalGDOptimizer over operators/optimizers/proximal_gd_op.cc)."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, regularization=None,
                 name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "proximal_gd"
        self._l1 = float(l1)
        self._l2 = float(l2)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            type="proximal_gd",
            inputs={"Param": [param], "Grad": [grad],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param]},
            attrs={"l1": self._l1, "l2": self._l2},
        )


class ProximalAdagradOptimizer(Optimizer):
    """Proximal adagrad (reference: optimizer.py ProximalAdagradOptimizer
    over operators/optimizers/proximal_adagrad_op.cc)."""

    _moment_acc_str = "moment"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, regularization=None,
                 name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "proximal_adagrad"
        self._l1 = float(l1)
        self._l2 = float(l2)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        return block.append_op(
            type="proximal_adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [moment],
                    "LearningRate": [self._create_param_lr(param_and_grad)]},
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"l1": self._l1, "l2": self._l2},
        )


class GradientMergeOptimizer:
    """Gradient accumulation over k steps (reference: the multi_batch_merge
    pass, reader/ctr use — VERDICT row 28).  Gradients accumulate into
    persistable buffers every step; every k-th step a conditional block
    applies the inner optimizer on the (optionally averaged) merged grad
    and zeroes the buffers.  The conditional lowers via if-conversion
    (ops/control_flow_ops.py conditional_block): inner updates compute every
    step and select by the apply mask, so optimizer moments advance only on
    apply steps — semantics identical to running the inner optimizer on the
    k-batch gradient."""

    def __init__(self, inner_optimizer, k_steps: int = 1, avg: bool = True):
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1")
        self.inner_optimizer = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from . import layers
        from .layers.control_flow import _conditional_block_ctx, equal

        if self.k_steps == 1:
            return self.inner_optimizer.minimize(
                loss, startup_program, parameter_list, no_grad_set)

        inner = self.inner_optimizer
        program = loss.block.program
        startup = startup_program or default_startup_program()
        with program_guard(program, startup):
            params_grads = inner.backward(
                loss, startup_program, parameter_list, no_grad_set)

            # int64 step counter: fp32 saturates at 2^24 steps
            step = _create_persistable_zeros(
                unique_name("grad_merge_step"), [1], "int64")
            one = layers.fill_constant([1], "int64", 1)
            k = layers.fill_constant([1], "int64", self.k_steps)
            layers.sums([step, one], out=step)
            rem = layers.elementwise_mod(step, k)
            zero = layers.fill_constant([1], "int64", 0)
            cond = equal(rem, zero)

            merged = []
            for p, g in params_grads:
                if g is None:
                    continue
                acc = _create_persistable_zeros(
                    unique_name(p.name + "_grad_merge"), p.shape, p.dtype)
                layers.sums([acc, g], out=acc)
                merged.append((p, acc))

            import contextlib

            helper = LayerHelper("gradient_merge")
            apply_block = contextlib.contextmanager(_conditional_block_ctx)
            with apply_block(helper, cond):
                apply_pgs = []
                for p, acc in merged:
                    g = (
                        layers.scale(acc, scale=1.0 / self.k_steps)
                        if self.avg else acc
                    )
                    apply_pgs.append((p, g))
                optimize_ops = inner.apply_gradients(apply_pgs, loss)
                for _, acc in merged:
                    zeros = layers.fill_constant(
                        [d for d in acc.shape], acc.dtype, 0.0)
                    layers.assign(zeros, output=acc)
        return optimize_ops, params_grads


ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer
