"""fluid.Tensor / fluid.LoDTensor / fluid.LoDTensorArray construction
parity (reference: pybind exposes the C++ Tensor/LoDTensor classes with
set()/set_lod()/shape(); user code builds feeds with them).  These shims
hold host numpy data; the executor's feed path converts a LoDTensor with
a LoD into the padded LoDValue runtime form."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .core.lod import LoDValue, create_lod_tensor

__all__ = ["Tensor", "LoDTensor", "LoDTensorArray"]


class Tensor:
    """Host tensor (reference: framework/tensor.h via pybind Tensor)."""

    def __init__(self):
        self._array: Optional[np.ndarray] = None

    def set(self, array, place=None) -> None:
        self._array = np.asarray(array)

    def shape(self) -> List[int]:
        return list(np.shape(self._array))

    def __array__(self, dtype=None):
        a = np.asarray(self._array)
        return a.astype(dtype) if dtype is not None else a

    def _as_feed(self):
        if self._array is None:
            raise ValueError("Tensor.set() was never called")
        return self._array


class LoDTensor(Tensor):
    """Host LoD tensor (reference: framework/lod_tensor.h; lod() is
    offset-form, recursive_sequence_lengths() is length-form)."""

    def __init__(self):
        super().__init__()
        self._rsl: List[List[int]] = []

    # -- offset-form (reference lod()) ----------------------------------
    def set_lod(self, lod: Sequence[Sequence[int]]) -> None:
        self._rsl = [
            [level[i + 1] - level[i] for i in range(len(level) - 1)]
            for level in lod
        ]

    def lod(self) -> List[List[int]]:
        out = []
        for lens in self._rsl:
            level = [0]
            for l in lens:
                level.append(level[-1] + l)
            out.append(level)
        return out

    # -- length-form ----------------------------------------------------
    def set_recursive_sequence_lengths(self, rsl) -> None:
        self._rsl = [list(level) for level in rsl]

    def recursive_sequence_lengths(self) -> List[List[int]]:
        return [list(level) for level in self._rsl]

    def _as_feed(self):
        arr = super()._as_feed()
        if not self._rsl:
            return arr
        return create_lod_tensor(arr, self._rsl)


class LoDTensorArray(list):
    """Host tensor array (reference: LOD_TENSOR_ARRAY variables; a plain
    list of LoDTensor/arrays on this side)."""

    def append(self, value):  # keep LoDTensor/ndarray entries as-is
        super().append(value)
