"""Initializers — append init ops to the startup program
(reference: python/paddle/fluid/initializer.py: Constant/Uniform/Normal/
TruncatedNormal/Xavier/MSRA/Bilinear initializer ops).
"""

from __future__ import annotations

import math

import numpy as np

from .core.framework import Block, Variable
from .core.proto import DataType

__all__ = [
    "Initializer",
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "NumpyArrayInitializer",
    "ConstantInitializer",
    "UniformInitializer",
    "NormalInitializer",
    "XavierInitializer",
    "MSRAInitializer",
    "force_init_on_cpu",
]


def force_init_on_cpu() -> bool:
    return False


class Initializer:
    def __call__(self, var: Variable, block: Block):
        raise NotImplementedError

    @staticmethod
    def _fan_in_out(var: Variable):
        shape = list(var.shape)
        if len(shape) < 2:
            return (shape[0] if shape else 1, shape[0] if shape else 1)
        receptive = 1
        for d in shape[2:]:
            receptive *= d
        return shape[1] * receptive, shape[0] * receptive


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0, force_cpu: bool = False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": int(var.dtype), "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0, seed: int = 0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape), "dtype": int(var.dtype),
                "min": self.low, "max": self.high, "seed": self.seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0, seed: int = 0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape), "dtype": int(var.dtype),
                "mean": self.loc, "std": self.scale, "seed": self.seed,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc: float = 0.0, scale: float = 1.0, seed: int = 0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape), "dtype": int(var.dtype),
                "mean": self.loc, "std": self.scale, "seed": self.seed,
            },
        )


class XavierInitializer(Initializer):
    """Glorot init (reference: initializer.py XavierInitializer)."""

    def __init__(self, uniform: bool = True, fan_in=None, fan_out=None, seed: int = 0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fi
        fan_out = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He/Kaiming init (reference: initializer.py MSRAInitializer)."""

    def __init__(self, uniform: bool = True, fan_in=None, seed: int = 0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fan_in)
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        return NormalInitializer(0.0, math.sqrt(2.0 / fan_in), self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        attrs = {"shape": list(self.value.shape), "dtype": int(var.dtype)}
        if var.dtype in (DataType.INT32, DataType.INT64):
            attrs["int32_values"] = self.value.astype(np.int64).reshape(-1).tolist()
        else:
            attrs["fp32_values"] = self.value.astype(np.float64).reshape(-1).tolist()
        return block.append_op(type="assign_value", outputs={"Out": [var.name]}, attrs=attrs)


class BilinearInitializer(Initializer):
    """Bilinear upsample kernel init for conv_transpose."""

    def __call__(self, var, block):
        shape = list(var.shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D weight")
        weight = np.zeros(shape, dtype=np.float32)
        k = shape[3]
        f = int(np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape[2:])):
            x, y = i % k, i // k
            val = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight[:, :, y, x] = val
        return NumpyArrayInitializer(weight)(var, block)


# public aliases (reference exports short names)
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
