"""DataFeeder (reference: python/paddle/fluid/data_feeder.py:83).

Converts reader minibatches — lists of per-sample tuples — into the feed
dict the Executor consumes: dense numpy for lod_level-0 vars, padded
LoDValue for sequence vars.  feed_parallel splits a batch across the
data-parallel axis like the reference's multi-device feed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .core.framework import Variable, default_main_program
from .core.lod import create_lod_tensor
from .core.proto import dtype_to_numpy

__all__ = ["DataFeeder"]


def dense_batch(samples, shape, np_dtype):
    """Stack lod_level-0 samples into one array, honoring trailing static
    dims ([-1, ...] batch leading).  Shared with py_reader."""
    arr = np.asarray(list(samples), dtype=np_dtype)
    if shape and all(d > 0 for d in shape[1:]):
        try:
            arr = arr.reshape([-1] + [int(d) for d in shape[1:]])
        except ValueError:
            pass
    return arr


def lod_batch(samples, np_dtype):
    """Pad variable-length samples into a LoDValue.  Shared with py_reader."""
    return create_lod_tensor(
        [np.asarray(s, dtype=np_dtype) for s in samples]
    )


class _DenseConverter:
    def __init__(self, shape, dtype):
        self.shape = [d for d in shape]
        self.dtype = dtype
        self.data: List[Any] = []

    def feed(self, sample):
        self.data.append(sample)

    def done(self):
        return dense_batch(self.data, self.shape, self.dtype)


class _LoDConverter:
    def __init__(self, dtype):
        self.dtype = dtype
        self.seqs: List[np.ndarray] = []

    def feed(self, sample):
        self.seqs.append(np.asarray(sample, dtype=self.dtype))

    def done(self):
        return create_lod_tensor(self.seqs)


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None):
        program = program or default_main_program()
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        self.place = place
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            if not isinstance(v, Variable):
                raise TypeError("feed_list holds Variables or var names")
            self.feed_names.append(v.name)
            self.feed_lod_level.append(v.lod_level)
            self.feed_shapes.append(list(v.shape))
            self.feed_dtypes.append(dtype_to_numpy(v.dtype))

    def feed(self, iterable) -> Dict[str, Any]:
        """One minibatch (iterable of per-sample tuples) -> feed dict."""
        converters = []
        for lod_level, shape, dtype in zip(
            self.feed_lod_level, self.feed_shapes, self.feed_dtypes
        ):
            if lod_level == 0:
                converters.append(_DenseConverter(shape, dtype))
            else:
                converters.append(_LoDConverter(dtype))
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                f"sample has {len(each_sample)} slots, feeder expects "
                f"{len(converters)}"
            )
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        return {
            name: conv.done()
            for name, conv in zip(self.feed_names, converters)
        }

    def feed_parallel(self, iterable: Sequence, num_places: Optional[int] = None):
        """Split a batch into per-device feeds (reference:
        data_feeder.py feed_parallel).  With pjit-style SPMD the global batch
        is usually fed whole; this exists for API parity."""
        if num_places is None or num_places <= 1:
            return [self.feed(iterable)]
        samples = list(iterable)
        # spread the remainder across the first chunks so no sample drops
        outs = []
        base, extra = divmod(len(samples), num_places)
        start = 0
        for i in range(num_places):
            size = base + (1 if i < extra else 0)
            chunk = samples[start : start + size]
            start += size
            if chunk:
                outs.append(self.feed(chunk))
        return outs
