"""Process-role assignment for downpour clusters
(reference: python/paddle/fluid/distributed/ps_instance.py
PaddlePSInstance — splits MPI ranks into pserver and worker halves).

Same role math as the reference: with server_worker_mode=0 the first
half of ranks are servers; with mode=1 ranks alternate server/worker
within each node (proc_per_node processes per host).  The comm splitting
the reference does with MPI sub-communicators reduces here to index
arithmetic — barriers/gather in-process are no-ops for size-1 and raise
for real multi-process use (launch via jax.distributed instead).
"""

from __future__ import annotations

from .helper import MPIHelper

__all__ = ["PaddlePSInstance"]

IDLE = -1
SERVER = 0
WORKER = 1


class PaddlePSInstance:
    def __init__(self, server_worker_mode: int = 1, proc_per_node: int = 2):
        if server_worker_mode == 1 and proc_per_node % 2 != 0:
            # interleaved mode pairs a server with a worker on each node;
            # an odd count would assign more servers than get_server_num()
            # reports and collide shard indices
            raise ValueError(
                "server_worker_mode=1 needs an even proc_per_node, got "
                f"{proc_per_node}"
            )
        self.dh = MPIHelper()
        self._rankid = self.dh.get_rank()
        self._server_worker_mode = server_worker_mode
        self._proc_per_node = proc_per_node
        # MPIHelper.get_size() is the TOTAL process count (the PADDLE_TRAINERS
        # convention) — unlike the reference, which launches one MPI rank per
        # node and multiplies by proc_per_node
        self._procs = self.dh.get_size()
        self._nodes = max(1, self._procs // proc_per_node)

        self._worker_num = self._procs // 2
        self._server_num = self._procs // 2
        self._total_server_worker = self._worker_num + self._server_num
        self._node_type = IDLE
        self._set_nodetype()

    def _set_nodetype(self) -> None:
        if self._server_worker_mode == 0:
            # block split: servers first, then workers
            if self._rankid < self._server_num:
                self._node_type = SERVER
            elif self._rankid < self._total_server_worker:
                self._node_type = WORKER
        elif self._server_worker_mode == 1:
            # interleaved within each node: even local index = server
            if self._rankid < self._total_server_worker:
                local = self._rankid % self._proc_per_node
                self._node_type = SERVER if local % 2 == 0 else WORKER
        # else IDLE

    def get_node_cnt(self) -> int:
        return self._nodes

    def get_worker_num(self) -> int:
        return self._worker_num

    def get_server_num(self) -> int:
        return self._server_num

    def get_worker_index(self) -> int:
        if self._server_worker_mode == 0:
            return self._rankid - self._server_num
        # interleaved: workers are the odd local indices on each node
        node = self._rankid // self._proc_per_node
        local = self._rankid % self._proc_per_node
        return node * (self._proc_per_node // 2) + local // 2

    def get_server_index(self) -> int:
        if self._server_worker_mode == 0:
            return self._rankid
        node = self._rankid // self._proc_per_node
        local = self._rankid % self._proc_per_node
        return node * (self._proc_per_node // 2) + local // 2

    def is_worker(self) -> bool:
        return self._node_type == WORKER

    def is_server(self) -> bool:
        return self._node_type == SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self.get_worker_index() == 0

    def set_ip(self, ip: str) -> None:
        self._ip = ip

    def gather_ips(self):
        if self.dh.get_size() > 1:
            raise NotImplementedError(
                "multi-process downpour uses jax.distributed coordination; "
                "see paddle_tpu/parallel/env.py"
            )
        return [self.dh.get_ip()]

    def barrier_all(self) -> None:
        if self.dh.get_size() > 1:
            raise NotImplementedError(
                "multi-process downpour uses jax.distributed coordination"
            )

    def finalize(self) -> None:
        pass
