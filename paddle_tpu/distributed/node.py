"""Server/worker desc builders for downpour training
(reference: python/paddle/fluid/distributed/node.py).

The reference fills pslib protobuf messages (ServerParameter /
DownpourTrainerParameter).  Here the descs are plain nested dicts with the
same field names, so they serialize to JSON, diff cleanly in tests, and
feed the in-process PS core (ps_core.PSCore.from_server_desc) directly.
"""

from __future__ import annotations

import json
from typing import List

__all__ = ["Server", "Worker", "DownpourServer", "DownpourWorker"]

PS_SPARSE_TABLE = 0
PS_DENSE_TABLE = 1


class Server:
    """Base server desc builder."""


class Worker:
    """Base worker desc builder."""


class DownpourServer(Server):
    """Builds the server-side table desc
    (reference: node.py DownpourServer — service_param + per-table
    accessor configs).  The service knobs that named brpc classes in the
    reference name the in-process core here."""

    def __init__(self):
        self.server_ = {
            "downpour_server_param": {
                "service_param": {
                    "start_server_port": 0,
                    "server_class": "InProcessPsServer",
                    "client_class": "InProcessPsClient",
                    "service_class": "DownpourPsService",
                    "server_thread_num": 12,
                },
                "downpour_table_param": [],
            }
        }

    def _tables(self) -> List[dict]:
        return self.server_["downpour_server_param"]["downpour_table_param"]

    def add_sparse_table(self, table_id, learning_rate, slot_key_vars,
                         slot_value_var):
        """Sparse embedding table: rows created on first pull, updated by
        row-wise adagrad (reference accessor: DownpourFeatureValueAccessor
        with sparse_sgd_param)."""
        dim = None
        for v in slot_value_var:
            if getattr(v, "shape", None):
                dim = int(v.shape[-1])
                break
        self._tables().append({
            "table_id": int(table_id),
            "table_class": "DownpourSparseTable",
            "type": PS_SPARSE_TABLE,
            "accessor": {
                "accessor_class": "DownpourFeatureValueAccessor",
                "embedx_dim": dim if dim is not None else 8,
                "fea_dim": dim if dim is not None else 11,
                "sparse_sgd_param": {
                    "learning_rate": float(learning_rate),
                    "initial_g2sum": 3.0,
                    "initial_range": 1e-4,
                    "weight_bounds": [-10.0, 10.0],
                },
            },
        })

    def add_dense_table(self, table_id, learning_rate, param_var, grad_var):
        """Dense table: all non-embedding params flattened into one vector,
        updated by adam (reference accessor: DownpourDenseValueAccessor
        dense_sgd_param.adam)."""
        fea_dim = 0
        for p in param_var:
            n = 1
            for d in p.shape:
                n *= int(d)
            fea_dim += n
        self._tables().append({
            "table_id": int(table_id),
            "table_class": "DownpourDenseTable",
            "type": PS_DENSE_TABLE,
            "accessor": {
                "accessor_class": "DownpourDenseValueAccessor",
                "fea_dim": fea_dim,
                "dense_sgd_param": {
                    "name": "adam",
                    "adam": {
                        "learning_rate": float(learning_rate),
                        "avg_decay_rate": 0.999993,
                        "ada_decay_rate": 0.9999,
                        "ada_epsilon": 1e-8,
                        "mom_decay_rate": 0.99,
                    },
                },
            },
        })

    def get_desc(self) -> dict:
        return self.server_


class DownpourWorker(Worker):
    """Builds the trainer-side desc: which vars ride which table
    (reference: node.py DownpourWorker — slot_key/slot_value/slot_gradient
    for sparse, dense_variable_name for dense)."""

    def __init__(self, window: int):
        self.window = window
        self.worker_ = {"sparse_table": [], "dense_table": []}

    def add_sparse_table(self, table_id, learning_rate, slot_key_vars,
                         slot_value_vars):
        self.worker_["sparse_table"].append({
            "table_id": int(table_id),
            "slot_key": [v.name for v in slot_key_vars],
            "slot_value": [v.name for v in slot_value_vars],
            "slot_gradient": [v.name + "@GRAD" for v in slot_value_vars],
        })

    def add_dense_table(self, table_id, learning_rate, param_vars, grad_vars):
        # the caller excludes the distributed table by exact name
        # (downpour.py); every other param — including local embeddings —
        # must ride the dense table or nothing ever updates it
        self.worker_["dense_table"].append({
            "table_id": int(table_id),
            "dense_variable_name": [p.name for p in param_vars],
            "dense_gradient_variable_name": [g.name for g in grad_vars],
        })

    def get_desc(self) -> dict:
        return self.worker_


def desc_to_text(desc: dict) -> str:
    """Stable text form of a desc (stands in for protobuf text_format)."""
    return json.dumps(desc, indent=2, sort_keys=True)
