"""Cross-process transport for the Downpour parameter server.

The reference runs pservers as real processes behind a gRPC/BRPC var
transport (operators/distributed/grpc_client.h:175, grpc_server.cc;
trainer/pserver processes forked by
python/paddle/fluid/tests/unittests/test_dist_base.py:212).  Dense data
parallelism in this framework rides XLA collectives instead, so the only
cross-process PS traffic left is the async Downpour plane: sparse row
pull/push and windowed dense pull/push.  This module is that transport —
a length-prefixed binary protocol over TCP (JSON header + raw ndarray
payloads, no pickle), serving a `ps_core.PSCore` to `RemotePS` clients
that plug into `AsyncExecutor.init_worker(ps=...)` unchanged.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["PSServer", "RemotePS", "serve_ps"]

_MAGIC = b"PSR1"


def _send_msg(sock: socket.socket, header: dict,
              arrays: Optional[Dict[str, np.ndarray]] = None) -> None:
    arrays = arrays or {}
    meta = dict(header)
    meta["__arrays__"] = {
        k: {"dtype": str(a.dtype), "shape": list(a.shape)}
        for k, a in arrays.items()
    }
    hbytes = json.dumps(meta).encode()
    parts = [_MAGIC, struct.pack(">I", len(hbytes)), hbytes]
    for k in meta["__arrays__"]:
        buf = np.ascontiguousarray(arrays[k]).tobytes()
        parts.append(struct.pack(">Q", len(buf)))
        parts.append(buf)
    sock.sendall(b"".join(parts))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("PS peer closed mid-message")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket) -> Tuple[dict, Dict[str, np.ndarray]]:
    magic = _recv_exact(sock, 4)
    if magic != _MAGIC:
        raise ConnectionError(f"bad PS frame magic {magic!r}")
    (hlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    meta = json.loads(_recv_exact(sock, hlen).decode())
    arrays = {}
    for k, spec in meta.pop("__arrays__", {}).items():
        (blen,) = struct.unpack(">Q", _recv_exact(sock, 8))
        arrays[k] = np.frombuffer(
            _recv_exact(sock, blen), dtype=np.dtype(spec["dtype"])
        ).reshape(spec["shape"])
    return meta, arrays


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):  # one connection, many requests
        core = self.server.ps_core
        lock = self.server.ps_lock
        while True:
            try:
                req, arrays = _recv_msg(self.request)
            except (ConnectionError, OSError):
                return
            cmd = req.get("cmd")
            stop = False
            try:
                # table ops serialize on the lock; the socket write happens
                # OUTSIDE it so one client's slow drain doesn't stall the
                # others (per-table Hogwild batching stays client-side)
                with lock:
                    if cmd == "pull_sparse":
                        reply = ({"ok": True}, {
                            "rows": core.sparse(req["table"]).pull(
                                arrays["ids"])})
                    elif cmd == "push_sparse":
                        core.sparse(req["table"]).push(
                            arrays["ids"], arrays["grads"])
                        reply = ({"ok": True}, None)
                    elif cmd == "sparse_len":
                        reply = ({"ok": True,
                                  "len": len(core.sparse(req["table"]))},
                                 None)
                    elif cmd == "sparse_dim":
                        reply = ({"ok": True,
                                  "dim": int(core.sparse(req["table"]).dim)},
                                 None)
                    elif cmd == "pull_dense":
                        reply = ({"ok": True},
                                 {"flat": core.dense(req["table"]).pull()})
                    elif cmd == "push_dense":
                        core.dense(req["table"]).push(arrays["grad"])
                        reply = ({"ok": True}, None)
                    elif cmd == "init_dense":
                        t = core.dense(req["table"])
                        if not t.initialized:  # first worker wins
                            t.init(arrays["values"])
                        reply = ({"ok": True}, None)
                    elif cmd == "dense_initialized":
                        reply = ({"ok": True, "initialized": bool(
                            core.dense(req["table"]).initialized)}, None)
                    elif cmd == "save":
                        core.save(req["path"])
                        reply = ({"ok": True}, None)
                    elif cmd == "shutdown":
                        reply = ({"ok": True}, None)
                        stop = True
                    else:
                        reply = ({"ok": False,
                                  "error": f"unknown cmd {cmd!r}"}, None)
            except Exception as e:  # surface server-side errors to client
                reply = ({"ok": False, "error": str(e)}, None)
            _send_msg(self.request, reply[0], reply[1])
            if stop:
                def _stop(srv=self.server):
                    srv.shutdown()
                    srv.server_close()  # release the listening fd

                threading.Thread(target=_stop, daemon=True).start()
                return


class PSServer(socketserver.ThreadingTCPServer):
    """Serve a PSCore over TCP.  One thread per client connection; table
    mutations serialize on one lock (the Hogwild batching happens
    client-side, as in the reference's per-request server handlers)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, core, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _Handler)
        self.ps_core = core
        self.ps_lock = threading.Lock()

    @property
    def endpoint(self) -> str:
        h, p = self.server_address
        return f"{h}:{p}"


def serve_ps(core, host: str = "127.0.0.1", port: int = 0) -> PSServer:
    """Start serving `core` on a background thread; returns the server
    (use .endpoint for clients, .shutdown() to stop)."""
    srv = PSServer(core, host, port)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class _Conn:
    def __init__(self, endpoint: str, timeout: float = 600.0):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self._sock: Optional[socket.socket] = socket.create_connection(
            self._addr, timeout=timeout)
        self._lock = threading.Lock()

    def call(self, header: dict, arrays=None) -> Tuple[dict, dict]:
        with self._lock:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
            try:
                _send_msg(self._sock, header, arrays)
                resp, resp_arrays = _recv_msg(self._sock)
            except BaseException:
                # any failure between send and recv leaves the stream
                # desynced (the old reply could satisfy the NEXT call) —
                # drop the connection so the next call starts clean
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                raise
        if not resp.get("ok"):
            raise RuntimeError(f"PS server error: {resp.get('error')}")
        return resp, resp_arrays


class _RemoteSparse:
    def __init__(self, conn: _Conn, table_id: int):
        self._c = conn
        self._t = table_id
        self._dim: Optional[int] = None

    @property
    def dim(self) -> int:
        if self._dim is None:
            resp, _ = self._c.call({"cmd": "sparse_dim", "table": self._t})
            self._dim = int(resp["dim"])
        return self._dim

    def pull(self, ids) -> np.ndarray:
        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1))
        _, arrays = self._c.call(
            {"cmd": "pull_sparse", "table": self._t}, {"ids": ids})
        return arrays["rows"]

    def push(self, ids, grads) -> None:
        self._c.call(
            {"cmd": "push_sparse", "table": self._t},
            {"ids": np.ascontiguousarray(np.asarray(ids).reshape(-1)),
             "grads": np.ascontiguousarray(grads)})

    def __len__(self) -> int:
        resp, _ = self._c.call({"cmd": "sparse_len", "table": self._t})
        return int(resp["len"])


class _RemoteDense:
    def __init__(self, conn: _Conn, table_id: int):
        self._c = conn
        self._t = table_id

    def pull(self) -> np.ndarray:
        _, arrays = self._c.call({"cmd": "pull_dense", "table": self._t})
        return arrays["flat"]

    def push(self, grad) -> None:
        self._c.call({"cmd": "push_dense", "table": self._t},
                     {"grad": np.ascontiguousarray(grad)})

    def init(self, values) -> None:
        self._c.call({"cmd": "init_dense", "table": self._t},
                     {"values": np.ascontiguousarray(values)})

    @property
    def initialized(self) -> bool:
        resp, _ = self._c.call(
            {"cmd": "dense_initialized", "table": self._t})
        return bool(resp["initialized"])


class RemotePS:
    """Client-side PSCore facade: drop-in for
    AsyncExecutor.init_worker(ps=...) across process boundaries."""

    def __init__(self, endpoint: str):
        self._conn = _Conn(endpoint)
        self._sparse: Dict[int, _RemoteSparse] = {}
        self._dense: Dict[int, _RemoteDense] = {}

    def sparse(self, table_id: int) -> _RemoteSparse:
        if table_id not in self._sparse:
            self._sparse[table_id] = _RemoteSparse(self._conn, table_id)
        return self._sparse[table_id]

    def dense(self, table_id: int) -> _RemoteDense:
        if table_id not in self._dense:
            self._dense[table_id] = _RemoteDense(self._conn, table_id)
        return self._dense[table_id]

    def save(self, path: str) -> None:
        self._conn.call({"cmd": "save", "path": path})

    def shutdown_server(self) -> None:
        self._conn.call({"cmd": "shutdown"})
