"""DownpourSGD distributed optimizer
(reference: python/paddle/fluid/distributed/downpour.py:24 DownpourSGD —
Large Scale Distributed Deep Networks' Downpour SGD).

minimize() appends backward only (no local optimizer ops: updates happen
on the server), maps the program's distributed lookup table to sparse
table 0 and every other param to dense table 1, and returns
[ps_param, worker_skipped_ops] exactly like the reference — the skipped
ops are the distributed lookup_table ops (and their grad ops) that
workers must not run, because the embedding rows live on the server and
arrive via pull_sparse (see async_executor.AsyncExecutor.run with
init_worker applied).
"""

from __future__ import annotations

from typing import List, Optional

from ..core.backward import append_backward
from ..distribute_lookup_table import (
    find_distributed_lookup_table,
    find_distributed_lookup_table_inputs,
    find_distributed_lookup_table_outputs,
)
from .node import DownpourServer, DownpourWorker

__all__ = ["DownpourSGD"]

SPARSE_TABLE_ID = 0
DENSE_TABLE_ID = 1


class DownpourSGD:
    """Async downpour SGD: sparse adagrad on the embedding table, dense
    adam on the rest, applied server-side.

    Args:
        learning_rate: sparse-table learning rate.
        window: batches between dense pull/push round-trips
            (communication strategy; reference DownpourWorker.window).
    """

    def __init__(self, learning_rate: float = 0.001, window: int = 1):
        self.learning_rate_ = learning_rate
        self.window_ = window
        self.type = "downpour"

    def minimize(
        self,
        loss,
        startup_program=None,
        parameter_list: Optional[List[str]] = None,
        no_grad_set=None,
    ):
        """Append backward and build server/worker descs.

        Returns:
            [ps_param, worker_skipped_ops]: ps_param is a dict with
            "server_param"/"trainer_param" descs (the reference's
            PSParameter protobuf); worker_skipped_ops are op types the
            worker executor must skip (reference returns
            ["lookup_table", "lookup_table_grad"]).
        """
        params_grads = sorted(
            append_backward(loss, parameter_list, no_grad_set),
            key=lambda x: x[0].name,
        )
        program = loss.block.program
        table_name = find_distributed_lookup_table(program)
        if table_name is None:
            raise ValueError(
                "DownpourSGD needs a distributed embedding: mark one with "
                "fluid.layers.embedding(..., is_distributed=True)"
            )
        prefetch_slots = find_distributed_lookup_table_inputs(
            program, table_name
        )
        prefetch_slots_emb = find_distributed_lookup_table_outputs(
            program, table_name
        )

        server = DownpourServer()
        worker = DownpourWorker(self.window_)
        server.add_sparse_table(
            SPARSE_TABLE_ID, self.learning_rate_,
            prefetch_slots, prefetch_slots_emb,
        )
        server.add_dense_table(
            DENSE_TABLE_ID, self.learning_rate_,
            [p for p, _ in params_grads if p.name != table_name],
            [g for p, g in params_grads if p.name != table_name],
        )
        worker.add_sparse_table(
            SPARSE_TABLE_ID, self.learning_rate_,
            prefetch_slots, prefetch_slots_emb,
        )
        worker.add_dense_table(
            DENSE_TABLE_ID, self.learning_rate_,
            [p for p, _ in params_grads if p.name != table_name],
            [g for p, g in params_grads if p.name != table_name],
        )
        ps_param = {
            "server_param": server.get_desc(),
            "trainer_param": worker.get_desc(),
            "table_name": table_name,
            "window": self.window_,
        }
        worker_skipped_ops = ["lookup_table", "lookup_table_grad"]
        return [ps_param, worker_skipped_ops]
