"""In-process downpour parameter server
(reference role: PSLIB's DownpourBrpcPsServer — closed-source in the
reference; node.py only builds its config.  This module is the open,
executable stand-in: the accessor semantics the configs describe, applied
to host-resident numpy state behind per-table locks so Hogwild
AsyncExecutor workers can pull/push concurrently).

Sparse tables (DownpourFeatureValueAccessor): vocab rows materialize
lazily on first pull (uniform(-initial_range, initial_range), g2sum =
initial_g2sum) and update by row-wise adagrad with weight bounds — the
whole table never exists as one dense array, which is the point of the
reference's SelectedRows/pserver path (operators/lookup_table_op.cc:80).

Dense tables (DownpourDenseValueAccessor): the model's non-embedding
params flattened to one vector, updated by adam with the desc's decay
rates.  Workers push grads every batch and pull fresh params every
`window` batches (DownpourWorker.window).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["SparseTable", "DenseTable", "PSCore"]


class SparseTable:
    """Lazy row-materializing embedding table with adagrad updates."""

    def __init__(self, dim: int, learning_rate: float = 0.05,
                 initial_g2sum: float = 3.0, initial_range: float = 1e-4,
                 weight_bounds: Sequence[float] = (-10.0, 10.0),
                 seed: int = 0):
        self.dim = int(dim)
        self.lr = float(learning_rate)
        self.initial_g2sum = float(initial_g2sum)
        self.initial_range = float(initial_range)
        self.lo, self.hi = (float(weight_bounds[0]), float(weight_bounds[1]))
        self._rows: Dict[int, np.ndarray] = {}
        self._g2sum: Dict[int, np.ndarray] = {}
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._rows)

    @staticmethod
    def _canonical_ids(ids) -> np.ndarray:
        """Keys are the 64-bit pattern as a non-negative int: hashed uint64
        feature ids ride int64 arrays as a bit-pattern view (see
        async_executor MultiSlot parsing), so an id may arrive negative
        from one caller and >= 2**63 from another — canonicalizing keeps
        them one row and keeps state_dict()'s uint64 id vector exact."""
        ids = np.asarray(ids).reshape(-1)
        if ids.dtype == object:
            ids = np.array([int(i) & 0xFFFFFFFFFFFFFFFF for i in ids],
                           dtype=np.uint64)
        return ids.astype(np.uint64)  # int64 -> uint64 keeps the bit pattern

    def pull(self, ids: np.ndarray) -> np.ndarray:
        """[N] ids -> [N, dim] rows; unseen ids materialize."""
        ids = self._canonical_ids(ids)
        out = np.empty((ids.size, self.dim), dtype=np.float32)
        with self._lock:
            for i, fid in enumerate(ids):
                fid = int(fid)
                row = self._rows.get(fid)
                if row is None:
                    row = self._rng.uniform(
                        -self.initial_range, self.initial_range, self.dim
                    ).astype(np.float32)
                    self._rows[fid] = row
                    self._g2sum[fid] = np.full(
                        self.dim, self.initial_g2sum, np.float32
                    )
                out[i] = row
        return out

    def push(self, ids: np.ndarray, grads: np.ndarray) -> None:
        """Row-wise adagrad: g2sum += g*g; w -= lr * g / sqrt(g2sum);
        duplicate ids in one push accumulate first (segment-sum), matching
        the reference's sparse-kernel merge of repeated rows."""
        ids = self._canonical_ids(ids)
        grads = np.asarray(grads, dtype=np.float32).reshape(ids.size, self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        merged = np.zeros((uniq.size, self.dim), dtype=np.float32)
        np.add.at(merged, inv, grads)
        with self._lock:
            for fid, g in zip(uniq, merged):
                fid = int(fid)
                if fid not in self._rows:
                    # push for a never-pulled id: materialize (a worker may
                    # have pulled from another server replica; be lenient)
                    self._rows[fid] = self._rng.uniform(
                        -self.initial_range, self.initial_range, self.dim
                    ).astype(np.float32)
                    self._g2sum[fid] = np.full(
                        self.dim, self.initial_g2sum, np.float32
                    )
                g2 = self._g2sum[fid]
                g2 += g * g
                w = self._rows[fid]
                w -= self.lr * g / np.sqrt(g2 + 1e-12)
                np.clip(w, self.lo, self.hi, out=w)

    def rows(self) -> Dict[int, np.ndarray]:
        with self._lock:
            return {k: v.copy() for k, v in self._rows.items()}

    def state_dict(self) -> dict:
        with self._lock:
            ids = np.fromiter(self._rows, dtype=np.uint64,
                              count=len(self._rows))
            return {
                "ids": ids,
                "rows": np.stack([self._rows[int(i)] for i in ids])
                if ids.size else np.zeros((0, self.dim), np.float32),
                "g2sum": np.stack([self._g2sum[int(i)] for i in ids])
                if ids.size else np.zeros((0, self.dim), np.float32),
            }

    def load_state_dict(self, state: dict) -> None:
        ids = self._canonical_ids(state["ids"])
        with self._lock:
            self._rows = {
                int(i): np.array(r, np.float32)
                for i, r in zip(ids, state["rows"])
            }
            self._g2sum = {
                int(i): np.array(g, np.float32)
                for i, g in zip(ids, state["g2sum"])
            }


class DenseTable:
    """Flat parameter vector with adam updates."""

    def __init__(self, dim: int, learning_rate: float = 5e-6,
                 mom_decay_rate: float = 0.99, ada_decay_rate: float = 0.9999,
                 ada_epsilon: float = 1e-8):
        self.dim = int(dim)
        self.lr = float(learning_rate)
        self.beta1 = float(mom_decay_rate)
        self.beta2 = float(ada_decay_rate)
        self.eps = float(ada_epsilon)
        self.w = np.zeros(self.dim, np.float32)
        self.mom = np.zeros(self.dim, np.float32)
        self.ada = np.zeros(self.dim, np.float32)
        self._initialized = False
        self._lock = threading.Lock()

    def init(self, values: np.ndarray) -> None:
        """Seed the table from a worker's startup-initialized params
        (reference: AsyncExecutor.init_model pushes worker 0's params).
        Re-seeding also resets the adam state — stale momentum must not
        step freshly initialized weights."""
        with self._lock:
            self.w = np.asarray(values, np.float32).reshape(self.dim).copy()
            self.mom = np.zeros(self.dim, np.float32)
            self.ada = np.zeros(self.dim, np.float32)
            self._initialized = True

    @property
    def initialized(self) -> bool:
        return self._initialized

    def pull(self) -> np.ndarray:
        with self._lock:
            return self.w.copy()

    def push(self, grad: np.ndarray) -> None:
        g = np.asarray(grad, np.float32).reshape(self.dim)
        with self._lock:
            self.mom = self.beta1 * self.mom + (1.0 - self.beta1) * g
            self.ada = self.beta2 * self.ada + (1.0 - self.beta2) * g * g
            self.w -= self.lr * self.mom / (np.sqrt(self.ada) + self.eps)

    def state_dict(self) -> dict:
        with self._lock:
            return {"w": self.w.copy(), "mom": self.mom.copy(),
                    "ada": self.ada.copy()}

    def load_state_dict(self, state: dict) -> None:
        with self._lock:
            self.w = np.array(state["w"], np.float32)
            self.mom = np.array(state["mom"], np.float32)
            self.ada = np.array(state["ada"], np.float32)
            self._initialized = True


class PSCore:
    """The server: table_id -> table, built from a DownpourServer desc."""

    def __init__(self):
        self.tables: Dict[int, object] = {}

    @classmethod
    def from_server_desc(cls, server_desc: dict) -> "PSCore":
        core = cls()
        params = server_desc["downpour_server_param"]["downpour_table_param"]
        for t in params:
            acc = t["accessor"]
            if t["table_class"] == "DownpourSparseTable":
                sgd = acc["sparse_sgd_param"]
                core.tables[t["table_id"]] = SparseTable(
                    dim=acc["embedx_dim"],
                    learning_rate=sgd["learning_rate"],
                    initial_g2sum=sgd["initial_g2sum"],
                    initial_range=sgd["initial_range"],
                    weight_bounds=sgd["weight_bounds"],
                )
            else:
                adam = acc["dense_sgd_param"]["adam"]
                core.tables[t["table_id"]] = DenseTable(
                    dim=acc["fea_dim"],
                    learning_rate=adam["learning_rate"],
                    mom_decay_rate=adam["mom_decay_rate"],
                    ada_decay_rate=adam["ada_decay_rate"],
                    ada_epsilon=adam["ada_epsilon"],
                )
        return core

    def sparse(self, table_id: int) -> SparseTable:
        t = self.tables[table_id]
        assert isinstance(t, SparseTable), f"table {table_id} is not sparse"
        return t

    def dense(self, table_id: int) -> DenseTable:
        t = self.tables[table_id]
        assert isinstance(t, DenseTable), f"table {table_id} is not dense"
        return t

    def save(self, path: str) -> None:
        """Checkpoint all tables to one .npz (reference: pserver periodic
        checkpoint, go/pserver/service.go:346 / PSLIB save_model)."""
        blobs = {}
        for tid, t in self.tables.items():
            for k, v in t.state_dict().items():
                blobs[f"t{tid}.{k}"] = v
        np.savez(path, **blobs)

    def load(self, path: str) -> None:
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        for tid, t in self.tables.items():
            keys = [k for k in data.files if k.startswith(f"t{tid}.")]
            if keys:
                t.load_state_dict(
                    {k.split(".", 1)[1]: data[k] for k in keys}
                )
