"""Cluster helpers for downpour mode
(reference: python/paddle/fluid/distributed/helper.py — FileSystem desc
builder + MPIHelper over mpi4py).

MPI is not the TPU-pod launch model; rank/size resolve from the same
PADDLE_* / JAX env the rest of paddle_tpu.parallel uses, so PSInstance
role math works unchanged in tests (env-injected ranks) and under real
multi-process launches (jax.distributed).
"""

from __future__ import annotations

import os
import socket

__all__ = ["FileSystem", "MPIHelper"]


class FileSystem:
    """Filesystem desc for dataset/model storage (reference: helper.py
    FileSystem builds a pslib FsClientParameter).  hdfs/afs URIs are
    carried as config; local paths work directly."""

    def __init__(self, fs_type: str = "afs", uri: str = "afs://xx",
                 user: str = None, passwd: str = None, hadoop_bin: str = ""):
        if fs_type not in ("afs", "hdfs", "local"):
            raise ValueError(f"unknown fs_type {fs_type!r}")
        self.fs_client = {
            "fs_type": fs_type,
            "uri": uri,
            "user": user,
            "passwd": passwd,
            "hadoop_bin": hadoop_bin,
        }

    def get_desc(self) -> dict:
        return self.fs_client


class MPIHelper:
    """Rank/size/host discovery (reference: helper.py MPIHelper wraps
    MPI.COMM_WORLD).  Resolution order: PADDLE_TRAINER_ID/PADDLE_TRAINERS
    env (the fluid cluster convention, fluid_benchmark.py:63), then
    OMPI/PMI env if launched under mpirun, then single-process."""

    def __init__(self):
        env = os.environ
        if "PADDLE_TRAINER_ID" in env:
            self._rank = int(env["PADDLE_TRAINER_ID"])
            self._size = int(env.get("PADDLE_TRAINERS", "1"))
        elif "OMPI_COMM_WORLD_RANK" in env:
            self._rank = int(env["OMPI_COMM_WORLD_RANK"])
            self._size = int(env.get("OMPI_COMM_WORLD_SIZE", "1"))
        elif "PMI_RANK" in env:
            self._rank = int(env["PMI_RANK"])
            self._size = int(env.get("PMI_SIZE", "1"))
        else:
            self._rank = 0
            self._size = 1

    def get_rank(self) -> int:
        return self._rank

    def get_size(self) -> int:
        return self._size

    def get_ip(self) -> str:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"

    def get_hostname(self) -> str:
        return socket.gethostname()
