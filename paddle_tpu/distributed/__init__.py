"""Downpour-style async parameter-server client
(reference: python/paddle/fluid/distributed/ — downpour.py DownpourSGD,
node.py DownpourServer/DownpourWorker, ps_instance.py, helper.py).

The reference builds pslib protobuf descs and hands them to Baidu's
closed-source PSLIB brpc server.  The TPU-native rebuild keeps the same
client API and desc structure but backs it with an open, in-process PS
core (ps_core.py): sparse tables apply adagrad row updates under the
DownpourFeatureValueAccessor semantics, dense tables apply adam — so
`AsyncExecutor` Hogwild workers can actually train against it (see
async_executor.py init_server/init_worker), instead of the hooks being
dead ends.  Mesh-sharded synchronous embeddings remain the first-class
TPU path (paddle_tpu/parallel); downpour is the async-PS parity mode.
"""

from .downpour import DownpourSGD
from .node import DownpourServer, DownpourWorker, Server, Worker
from .ps_core import DenseTable, PSCore, SparseTable
from .ps_instance import PaddlePSInstance
from .helper import FileSystem, MPIHelper

__all__ = [
    "DownpourSGD",
    "DownpourServer",
    "DownpourWorker",
    "Server",
    "Worker",
    "PSCore",
    "SparseTable",
    "DenseTable",
    "PaddlePSInstance",
    "FileSystem",
    "MPIHelper",
]
