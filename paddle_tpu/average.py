"""WeightedAverage (reference: python/paddle/fluid/average.py)."""

from __future__ import annotations

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number_or_matrix(var):
    return isinstance(var, (int, float, np.ndarray)) or np.isscalar(var)


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            value = np.asarray(value)
        if not np.isscalar(weight):
            weight = float(np.ravel(np.asarray(weight))[0])
        if self.numerator is None or self.denominator is None:
            self.numerator = value * weight
            self.denominator = weight
        else:
            self.numerator += value * weight
            self.denominator += weight

    def eval(self):
        if self.numerator is None or self.denominator is None:
            raise ValueError("WeightedAverage has no accumulated values")
        return self.numerator / self.denominator
