"""AsyncExecutor: many-thread file-sharded training
(reference: python/paddle/fluid/async_executor.py over
paddle/fluid/framework/async_executor.cc + executor_thread_worker.cc +
MultiSlotDataFeed data_feed.cc).

The reference runs N C++ threads, each popping files from a shared list,
parsing the MultiSlot text format and running the program Hogwild-style
over a shared scope.  Here each worker thread owns an Executor over the
shared scope; XLA compute releases the GIL so workers overlap, and scope
write-back is last-writer-wins per variable — the same Hogwild semantics.
Sparse CTR-style slots feed as padded LoDValues.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional, Sequence

import numpy as np

from .core.executor import Executor
from .core.framework import Program, default_main_program
from .core.lod import create_lod_tensor
from .core.place import CPUPlace, Place
from .core.scope import Scope, global_scope
from .data_feed_desc import DataFeedDesc

__all__ = ["AsyncExecutor"]


def _rows_from_handle(lib, h, slots):
    """Unpack a parsed-chunk handle into per-line rows of numpy views."""
    import ctypes

    L = lib.ms_num_lines(h)
    n = len(slots)
    cols = []
    for i, s in enumerate(slots):
        if not s.is_used:
            cols.append(None)  # never copied out of the C++ handle
            continue
        lens = np.empty(L, dtype=np.int32)
        if L:
            lib.ms_slot_lens(
                h, i, lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int))
            )
        total = lib.ms_slot_total(h, i)
        if s.type.startswith("float"):
            vals = np.empty(total, dtype=np.float32)
            if total:
                lib.ms_slot_values_f(
                    h, i,
                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                )
        else:
            vals = np.empty(total, dtype=np.int64)
            if total:
                lib.ms_slot_values_i(
                    h, i,
                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
                )
        offs = np.zeros(L + 1, dtype=np.int64)
        np.cumsum(lens, out=offs[1:])
        cols.append((vals, offs))
    for r in range(L):
        yield [
            cols[i][0][cols[i][1][r]: cols[i][1][r + 1]]
            if cols[i] is not None else None
            for i in range(n)
        ]


_MS_CHUNK_BYTES = 8 << 20  # per-worker parse granularity (bounds memory)


def _parse_multislot_file(path: str, slots):
    """Stream a MultiSlot file as per-line rows.  Chunks of whole lines go
    through the native C++ parser (native/multislot.cc, the reference's
    MultiSlotDataFeed::ParseOneInstance role) so worker memory stays
    O(chunk), not O(file); falls back to the per-line Python parser when
    the native lib doesn't build."""
    import ctypes

    from . import native

    lib = native.load("multislot")
    if lib is None:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield _parse_multislot_line(line, slots)
        return

    lib.ms_parse_buffer.restype = ctypes.c_void_p
    lib.ms_parse_buffer.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.c_long,
    ]
    for fn, res, args in (
        ("ms_error", ctypes.c_long, [ctypes.c_void_p]),
        ("ms_num_lines", ctypes.c_long, [ctypes.c_void_p]),
        ("ms_slot_total", ctypes.c_long, [ctypes.c_void_p, ctypes.c_int]),
    ):
        getattr(lib, fn).restype = res
        getattr(lib, fn).argtypes = args
    lib.ms_free.argtypes = [ctypes.c_void_p]
    lib.ms_slot_lens.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
    ]
    lib.ms_slot_values_f.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
    ]
    lib.ms_slot_values_i.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_longlong),
    ]

    n = len(slots)
    types = (ctypes.c_int * n)(
        *[0 if s.type.startswith("float") else 1 for s in slots]
    )
    lineno = 0
    with open(path, "rb") as f:
        tail = b""
        while True:
            chunk = f.read(_MS_CHUNK_BYTES)
            data = tail + chunk
            if not data:
                break
            if chunk:
                # cut at the last newline; the remainder carries over
                cut = data.rfind(b"\n")
                if cut < 0:
                    tail = data
                    continue
                data, tail = data[: cut + 1], data[cut + 1:]
            else:
                tail = b""
            h = lib.ms_parse_buffer(data, len(data), n, types, lineno)
            if not h:
                raise IOError(f"MultiSlot parse failed for {path!r}")
            try:
                err = lib.ms_error(h)
                if err:
                    raise ValueError(
                        f"malformed MultiSlot line {err} in {path!r}"
                    )
                yield from _rows_from_handle(lib, h, slots)
            finally:
                lib.ms_free(h)
            lineno += data.count(b"\n")
            if not chunk:
                break


def _parse_multislot_line(line: str, slots):
    """One MultiSlot text line: for each slot, '<n> v1 ... vn'
    (reference: data_feed.cc MultiSlotDataFeed::ParseOneInstance).  ALL
    slots are parsed in file order — unused ones are skipped after reading,
    like the reference — and truncated lines are rejected."""
    toks = line.split()
    pos = 0
    out = []
    for s in slots:
        if pos >= len(toks):
            raise ValueError(f"truncated MultiSlot line at slot {s.name}")
        n = int(toks[pos])
        pos += 1
        if pos + n > len(toks):
            raise ValueError(
                f"slot {s.name} declares {n} values but the line has "
                f"{len(toks) - pos} left"
            )
        vals = toks[pos : pos + n]
        pos += n
        if not s.is_used:
            out.append(None)
        elif s.type.startswith("float"):
            out.append(np.asarray([float(v) for v in vals], dtype=np.float32))
        else:
            # uint64 sparse ids: keep the bit pattern in int64 like the
            # native parser (hashed features exceed 2^63)
            out.append(
                np.asarray([int(v) for v in vals], dtype=np.uint64)
                .view(np.int64)
            )
    return out


class AsyncExecutor:
    """reference: async_executor.py AsyncExecutor (RunFromFile surface)."""

    def __init__(self, place: Optional[Place] = None, run_mode: str = ""):
        self.place = place or CPUPlace()
        self.scope = global_scope()
        # downpour mode state (reference: async_executor.py pslib hooks)
        self._instance = None
        self._ps = None
        self._dist_desc = None
        self._worker_program = None
        self._emb_map = []
        self._dense_params: List[str] = []
        self._dense_grads: List[str] = []
        self._window = 1

    def run(
        self,
        program: Optional[Program],
        data_feed: DataFeedDesc,
        filelist: Sequence[str],
        thread_num: int,
        fetch: Sequence,
        mode: str = "",
        debug: bool = False,
    ) -> None:
        program = program or default_main_program()
        if thread_num <= 0:
            raise ValueError("thread_num must be positive")
        fetch_names = [
            v.name if hasattr(v, "name") else str(v) for v in (fetch or [])
        ]
        block0 = program.global_block()
        all_slots = list(data_feed.slots)
        used_idx = [i for i, s in enumerate(all_slots) if s.is_used]
        used = [all_slots[i] for i in used_idx]

        files: queue.Queue = queue.Queue()
        for f in filelist:
            files.put(f)
        errors: List[BaseException] = []

        def feed_from(slot_rows):
            feed = {}
            for i, s in zip(used_idx, used):
                col = [row[i] for row in slot_rows]
                v = block0.vars.get(s.name)
                lod = v.lod_level if v is not None else (0 if s.is_dense else 1)
                if lod > 0:
                    feed[s.name] = create_lod_tensor(
                        [c[:, None] if c.ndim == 1 else c for c in col]
                    )
                else:
                    feed[s.name] = np.stack(col)
            return feed

        downpour = self._dist_desc is not None
        if downpour and program is not self._downpour_main:
            raise ValueError(
                "downpour mode executes the worker program derived at "
                "init_worker time; pass the same main program (or call "
                "init_worker again with the new one)"
            )

        def run_batch(exe, feed, counter):
            if downpour:
                return self._downpour_step(exe, feed, fetch_names, counter)
            return exe.run(
                program=program, feed=feed, fetch_list=fetch_names
            )

        def worker():
            exe = Executor(self.place, donate_states=False)
            counter = [0]
            try:
                while True:
                    try:
                        path = files.get_nowait()
                    except queue.Empty:
                        return
                    batch = []
                    for row in _parse_multislot_file(path, all_slots):
                        batch.append(row)
                        if len(batch) == data_feed.batch_size:
                            vals = run_batch(exe, feed_from(batch), counter)
                            if debug and fetch_names:
                                print(
                                    f"[async_executor] {path}: "
                                    + ", ".join(
                                        f"{n}={np.ravel(np.asarray(v))[0]:.6f}"
                                        for n, v in zip(fetch_names, vals)
                                    )
                                )
                            batch = []
                    if batch:
                        run_batch(exe, feed_from(batch), counter)
            except BaseException as e:  # propagate to the caller
                errors.append(e)

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(thread_num)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    # ------------------------------------------------------------------
    # Downpour (async parameter server) mode.
    # reference: async_executor.py config_distributed_nodes/init_server/
    # init_worker/init_model/save_model over Baidu's closed PSLIB; here the
    # server is the open in-process PS core (distributed/ps_core.py), so
    # the hooks actually train instead of requiring external infra.
    # ------------------------------------------------------------------
    def get_instance(self):
        if self._instance is None:
            raise ValueError("call config_distributed_nodes first")
        return self._instance

    def config_distributed_nodes(self):
        from .distributed.ps_instance import PaddlePSInstance

        self._instance = PaddlePSInstance(server_worker_mode=1,
                                          proc_per_node=2)
        return self._instance

    def init_server(self, dist_desc):
        """Build the PS tables from the server desc
        (dist_desc = ps_param returned by DownpourSGD.minimize)."""
        from .distributed.ps_core import PSCore

        self._ps = PSCore.from_server_desc(dist_desc["server_param"])
        return self._ps

    def init_worker(self, dist_desc, startup_program=None, program=None,
                    ps=None):
        """Prepare the worker: strip the distributed lookup ops (and the
        table's init op) out of a cloned program, record the id->embedding
        plumbing and the dense param/grad lists.  `ps` lets a worker point
        at another process's PSCore; defaults to this executor's."""
        from .core.framework import default_startup_program

        if ps is not None:
            self._ps = ps
        if self._ps is None:
            raise ValueError("no PS core: call init_server or pass ps=")
        self._dist_desc = dist_desc
        self._window = int(dist_desc.get("window", 1))
        table_name = dist_desc["table_name"]

        main = program or default_main_program()
        self._downpour_main = main
        wp = main.clone()
        bdesc = wp.global_block().desc  # clone's authoritative op list
        emb_map = []
        for i in reversed(range(len(bdesc.ops))):
            op = bdesc.ops[i]
            if (op.type == "lookup_table"
                    and op.input("W")[0] == table_name):
                out = op.output("Out")[0]
                emb_map.append((
                    op.input("Ids")[0], out, out + "@GRAD",
                ))
                del bdesc.ops[i]
            elif (op.type == "lookup_table_grad"
                    and op.input("W")[0] == table_name):
                del bdesc.ops[i]
        wp.desc.bump()
        emb_map.reverse()
        if not emb_map:
            raise ValueError(
                f"no lookup_table op on distributed table '{table_name}'"
            )
        self._emb_map = emb_map
        self._worker_program = wp

        # the table itself must never materialize on workers: drop its
        # initializer from the startup program (reference worker skips
        # param init for distributed tables via fake_init)
        sp = startup_program or default_startup_program()
        sblock = sp.global_block()
        removed = []  # (index, Operator) to restore on stop()
        for i in reversed(range(len(sblock.ops))):
            if table_name in sblock.ops[i].output_arg_names:
                removed.append((i, sblock.ops[i]))
                sblock._remove_op(i)
        sp.desc.bump()
        # a repeated init_worker (e.g. to re-point ps=, change window, or
        # switch startup programs) finds nothing left to strip in an
        # already-stripped program; keep every program's saved ops so
        # stop() can restore them all
        if not hasattr(self, "_stripped_startups"):
            self._stripped_startups = {}
        key = id(sp)
        prev_sp, prev_ops = self._stripped_startups.get(key, (sp, []))
        self._stripped_startups[key] = (
            sp, prev_ops + list(reversed(removed))
        )

        trainer = dist_desc["trainer_param"]
        dense = trainer["dense_table"][0] if trainer["dense_table"] else None
        self._dense_params = list(dense["dense_variable_name"]) if dense else []
        self._dense_grads = (
            list(dense["dense_gradient_variable_name"]) if dense else []
        )

    def init_model(self):
        """Seed the dense table from this worker's startup-initialized
        params (reference: init_model — worker 0 pushes initial params)."""
        if not self._dense_params:
            return
        from .distributed.downpour import DENSE_TABLE_ID

        vals = []
        for name in self._dense_params:
            v = self.scope.find_var(name)
            if v is None:
                raise ValueError(f"param '{name}' not in scope; run the "
                                 "startup program first")
            vals.append(np.ravel(np.asarray(v)))
        self._ps.dense(DENSE_TABLE_ID).init(np.concatenate(vals))

    def save_model(self, save_path: str):
        """Checkpoint the PS tables (reference: save_model RPC)."""
        if self._ps is None:
            raise ValueError("no PS core to save")
        self._ps.save(save_path)

    def stop(self):
        """Leave downpour mode: put the table's init op back into the
        startup program (init_worker stripped it in place) and drop the
        worker plumbing, so later non-downpour runs see the original
        program semantics."""
        for sp, removed in getattr(self, "_stripped_startups", {}).values():
            sblock = sp.global_block()
            for i, op in removed:  # ascending order restores positions
                sblock.ops.insert(i, op)
                sblock.desc.ops.insert(i, op.desc)
            sp.desc.bump()
        self._stripped_startups = {}
        self._dist_desc = None
        self._worker_program = None
        self._emb_map = []
        self._dense_params = []
        self._dense_grads = []

    def _pull_dense_into_scope(self):
        from .distributed.downpour import DENSE_TABLE_ID

        table = self._ps.dense(DENSE_TABLE_ID)
        if not table.initialized:
            raise RuntimeError(
                "dense table is uninitialized: call init_model() after the "
                "startup program (or load a PS checkpoint) before run() — "
                "otherwise dense params never train (the worker program has "
                "no local optimizer ops)"
            )
        flat = table.pull()
        block = self._worker_program.global_block()
        off = 0
        for name in self._dense_params:
            shape = [int(d) for d in block.var(name).shape]
            n = int(np.prod(shape)) if shape else 1
            self.scope.set_var(
                name, flat[off:off + n].reshape(shape).astype(np.float32)
            )
            off += n

    def _downpour_step(self, exe, feed, fetch_names, counter):
        """One worker batch: pull sparse rows for every distributed lookup,
        feed the embeddings, run forward+backward, push sparse and dense
        grads; refresh dense params from the server every `window` batches
        (reference: executor_thread_worker.cc downpour pull/push cadence)."""
        from .core.lod import LoDValue
        from .distributed.downpour import DENSE_TABLE_ID, SPARSE_TABLE_ID

        sparse = self._ps.sparse(SPARSE_TABLE_ID)
        pushes = []  # (flat_ids, keep_mask) per lookup, for the push phase
        for ids_name, out_name, _ in self._emb_map:
            ids_val = feed[ids_name]
            if isinstance(ids_val, LoDValue):
                data = np.asarray(ids_val.data)
                lengths = np.asarray(ids_val.lengths)
            else:
                data = np.asarray(ids_val)
                lengths = None
            if data.ndim >= 1 and data.shape[-1] == 1:
                core_shape = data.shape[:-1]
            else:
                core_shape = data.shape
            flat = data.reshape(-1)
            if lengths is not None:
                # only pull real positions: pulling padded slots would
                # lazily materialize a phantom row for the pad id (0) that
                # the model never saw; padding stays zero, matching the
                # forward's padding mask, and push skips it too
                pos = np.arange(data.shape[1])
                mask = (pos[None, :] < lengths[:, None]).reshape(-1)
                rows = np.zeros((flat.size, sparse.dim), np.float32)
                rows[mask] = sparse.pull(flat[mask])
                out = rows.reshape(core_shape + (sparse.dim,))
                feed[out_name] = LoDValue(out, lengths)
                pushes.append((flat, mask))
            else:
                rows = sparse.pull(flat)
                feed[out_name] = rows.reshape(core_shape + (sparse.dim,))
                pushes.append((flat, None))

        if counter[0] % self._window == 0:
            self._pull_dense_into_scope()
        counter[0] += 1

        emb_grad_names = [g for _, _, g in self._emb_map]
        vals = exe.run(
            program=self._worker_program,
            feed=feed,
            fetch_list=list(fetch_names) + emb_grad_names + self._dense_grads,
        )
        n_f, n_e = len(fetch_names), len(emb_grad_names)
        emb_grads = vals[n_f:n_f + n_e]
        dense_grads = vals[n_f + n_e:]

        for (flat, mask), g in zip(pushes, emb_grads):
            gd = np.asarray(g.data if isinstance(g, LoDValue) else g)
            gflat = gd.reshape(-1, sparse.dim)
            if mask is not None:
                sparse.push(flat[mask], gflat[mask])
            else:
                sparse.push(flat, gflat)

        if dense_grads:
            self._ps.dense(DENSE_TABLE_ID).push(
                np.concatenate([np.ravel(np.asarray(g)) for g in dense_grads])
            )
        return vals[:n_f]
