"""AsyncExecutor: many-thread file-sharded training
(reference: python/paddle/fluid/async_executor.py over
paddle/fluid/framework/async_executor.cc + executor_thread_worker.cc +
MultiSlotDataFeed data_feed.cc).

The reference runs N C++ threads, each popping files from a shared list,
parsing the MultiSlot text format and running the program Hogwild-style
over a shared scope.  Here each worker thread owns an Executor over the
shared scope; XLA compute releases the GIL so workers overlap, and scope
write-back is last-writer-wins per variable — the same Hogwild semantics.
Sparse CTR-style slots feed as padded LoDValues.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional, Sequence

import numpy as np

from .core.executor import Executor
from .core.framework import Program, default_main_program
from .core.lod import create_lod_tensor
from .core.place import CPUPlace, Place
from .core.scope import Scope, global_scope
from .data_feed_desc import DataFeedDesc

__all__ = ["AsyncExecutor"]


def _rows_from_handle(lib, h, slots):
    """Unpack a parsed-chunk handle into per-line rows of numpy views."""
    import ctypes

    L = lib.ms_num_lines(h)
    n = len(slots)
    cols = []
    for i, s in enumerate(slots):
        if not s.is_used:
            cols.append(None)  # never copied out of the C++ handle
            continue
        lens = np.empty(L, dtype=np.int32)
        if L:
            lib.ms_slot_lens(
                h, i, lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int))
            )
        total = lib.ms_slot_total(h, i)
        if s.type.startswith("float"):
            vals = np.empty(total, dtype=np.float32)
            if total:
                lib.ms_slot_values_f(
                    h, i,
                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                )
        else:
            vals = np.empty(total, dtype=np.int64)
            if total:
                lib.ms_slot_values_i(
                    h, i,
                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
                )
        offs = np.zeros(L + 1, dtype=np.int64)
        np.cumsum(lens, out=offs[1:])
        cols.append((vals, offs))
    for r in range(L):
        yield [
            cols[i][0][cols[i][1][r]: cols[i][1][r + 1]]
            if cols[i] is not None else None
            for i in range(n)
        ]


_MS_CHUNK_BYTES = 8 << 20  # per-worker parse granularity (bounds memory)


def _parse_multislot_file(path: str, slots):
    """Stream a MultiSlot file as per-line rows.  Chunks of whole lines go
    through the native C++ parser (native/multislot.cc, the reference's
    MultiSlotDataFeed::ParseOneInstance role) so worker memory stays
    O(chunk), not O(file); falls back to the per-line Python parser when
    the native lib doesn't build."""
    import ctypes

    from . import native

    lib = native.load("multislot")
    if lib is None:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield _parse_multislot_line(line, slots)
        return

    lib.ms_parse_buffer.restype = ctypes.c_void_p
    lib.ms_parse_buffer.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.c_long,
    ]
    for fn, res, args in (
        ("ms_error", ctypes.c_long, [ctypes.c_void_p]),
        ("ms_num_lines", ctypes.c_long, [ctypes.c_void_p]),
        ("ms_slot_total", ctypes.c_long, [ctypes.c_void_p, ctypes.c_int]),
    ):
        getattr(lib, fn).restype = res
        getattr(lib, fn).argtypes = args
    lib.ms_free.argtypes = [ctypes.c_void_p]
    lib.ms_slot_lens.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
    ]
    lib.ms_slot_values_f.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
    ]
    lib.ms_slot_values_i.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_longlong),
    ]

    n = len(slots)
    types = (ctypes.c_int * n)(
        *[0 if s.type.startswith("float") else 1 for s in slots]
    )
    lineno = 0
    with open(path, "rb") as f:
        tail = b""
        while True:
            chunk = f.read(_MS_CHUNK_BYTES)
            data = tail + chunk
            if not data:
                break
            if chunk:
                # cut at the last newline; the remainder carries over
                cut = data.rfind(b"\n")
                if cut < 0:
                    tail = data
                    continue
                data, tail = data[: cut + 1], data[cut + 1:]
            else:
                tail = b""
            h = lib.ms_parse_buffer(data, len(data), n, types, lineno)
            if not h:
                raise IOError(f"MultiSlot parse failed for {path!r}")
            try:
                err = lib.ms_error(h)
                if err:
                    raise ValueError(
                        f"malformed MultiSlot line {err} in {path!r}"
                    )
                yield from _rows_from_handle(lib, h, slots)
            finally:
                lib.ms_free(h)
            lineno += data.count(b"\n")
            if not chunk:
                break


def _parse_multislot_line(line: str, slots):
    """One MultiSlot text line: for each slot, '<n> v1 ... vn'
    (reference: data_feed.cc MultiSlotDataFeed::ParseOneInstance).  ALL
    slots are parsed in file order — unused ones are skipped after reading,
    like the reference — and truncated lines are rejected."""
    toks = line.split()
    pos = 0
    out = []
    for s in slots:
        if pos >= len(toks):
            raise ValueError(f"truncated MultiSlot line at slot {s.name}")
        n = int(toks[pos])
        pos += 1
        if pos + n > len(toks):
            raise ValueError(
                f"slot {s.name} declares {n} values but the line has "
                f"{len(toks) - pos} left"
            )
        vals = toks[pos : pos + n]
        pos += n
        if not s.is_used:
            out.append(None)
        elif s.type.startswith("float"):
            out.append(np.asarray([float(v) for v in vals], dtype=np.float32))
        else:
            # uint64 sparse ids: keep the bit pattern in int64 like the
            # native parser (hashed features exceed 2^63)
            out.append(
                np.asarray([int(v) for v in vals], dtype=np.uint64)
                .view(np.int64)
            )
    return out


class AsyncExecutor:
    """reference: async_executor.py AsyncExecutor (RunFromFile surface)."""

    def __init__(self, place: Optional[Place] = None, run_mode: str = ""):
        self.place = place or CPUPlace()
        self.scope = global_scope()

    def run(
        self,
        program: Optional[Program],
        data_feed: DataFeedDesc,
        filelist: Sequence[str],
        thread_num: int,
        fetch: Sequence,
        mode: str = "",
        debug: bool = False,
    ) -> None:
        program = program or default_main_program()
        if thread_num <= 0:
            raise ValueError("thread_num must be positive")
        fetch_names = [
            v.name if hasattr(v, "name") else str(v) for v in (fetch or [])
        ]
        block0 = program.global_block()
        all_slots = list(data_feed.slots)
        used_idx = [i for i, s in enumerate(all_slots) if s.is_used]
        used = [all_slots[i] for i in used_idx]

        files: queue.Queue = queue.Queue()
        for f in filelist:
            files.put(f)
        errors: List[BaseException] = []

        def feed_from(slot_rows):
            feed = {}
            for i, s in zip(used_idx, used):
                col = [row[i] for row in slot_rows]
                v = block0.vars.get(s.name)
                lod = v.lod_level if v is not None else (0 if s.is_dense else 1)
                if lod > 0:
                    feed[s.name] = create_lod_tensor(
                        [c[:, None] if c.ndim == 1 else c for c in col]
                    )
                else:
                    feed[s.name] = np.stack(col)
            return feed

        def worker():
            exe = Executor(self.place, donate_states=False)
            try:
                while True:
                    try:
                        path = files.get_nowait()
                    except queue.Empty:
                        return
                    batch = []
                    for row in _parse_multislot_file(path, all_slots):
                        batch.append(row)
                        if len(batch) == data_feed.batch_size:
                            vals = exe.run(
                                program=program,
                                feed=feed_from(batch),
                                fetch_list=fetch_names,
                            )
                            if debug and fetch_names:
                                print(
                                    f"[async_executor] {path}: "
                                    + ", ".join(
                                        f"{n}={np.ravel(np.asarray(v))[0]:.6f}"
                                        for n, v in zip(fetch_names, vals)
                                    )
                                )
                            batch = []
                    if batch:
                        exe.run(program=program, feed=feed_from(batch),
                                fetch_list=fetch_names)
            except BaseException as e:  # propagate to the caller
                errors.append(e)

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(thread_num)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    # reference API parity (PSLIB distributed hooks are Baidu-internal)
    def config_distributed_nodes(self):
        raise NotImplementedError(
            "PSLIB downpour mode is replaced by mesh-sharded training; "
            "use ParallelExecutor with a sharded embedding table"
        )
