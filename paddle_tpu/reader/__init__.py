"""Reader creators & decorators (reference: python/paddle/reader/decorator.py
+ python/paddle/batch.py).

A *reader* is a zero-arg callable returning an iterable of samples; a
*reader creator* builds readers.  Decorators compose readers functionally —
ported semantics-for-semantics (this layer is pure host Python; device work
starts at DataFeeder/py_reader).
"""

from __future__ import annotations

import itertools
import queue
import random
import threading
from typing import Any, Callable, Iterable, List

__all__ = [
    "cache",
    "map_readers",
    "buffered",
    "compose",
    "chain",
    "shuffle",
    "firstn",
    "xmap_readers",
    "multiprocess_reader",
    "batch",
    "ComposeNotAligned",
]

from . import creator  # noqa: E402,F401


class ComposeNotAligned(ValueError):
    pass


def cache(reader: Callable) -> Callable:
    """Cache the first full pass in memory (reference: decorator.py cache)."""
    all_data = tuple(reader())

    def cached_reader():
        for item in all_data:
            yield item

    return cached_reader


def map_readers(func: Callable, *readers: Callable) -> Callable:
    """Yield func applied across outputs of several readers
    (reference: decorator.py:36 map_readers)."""

    def reader():
        rs = [r() for r in readers]
        for vals in map(func, *rs):
            yield vals

    return reader


def shuffle(reader: Callable, buf_size: int) -> Callable:
    """Buffered shuffle (reference: decorator.py shuffle)."""

    def data_reader():
        buf: List[Any] = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers: Callable) -> Callable:
    """Concatenate readers (reference: decorator.py chain)."""

    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


def compose(*readers: Callable, **kwargs) -> Callable:
    """Zip readers into joined samples (reference: decorator.py compose)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned"
                    )
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader: Callable, size: int) -> Callable:
    """Background-thread prefetch buffer (reference: decorator.py buffered)."""

    class EndSignal:
        pass

    end = EndSignal()

    def read_worker(r, q):
        try:
            for d in r:
                q.put(d)
            q.put(end)
        except BaseException as e:  # surface reader errors to the consumer
            q.put(e)

    def data_reader():
        r = reader()
        q: queue.Queue = queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q), daemon=True)
        t.start()
        e = q.get()
        while not isinstance(e, EndSignal):
            if isinstance(e, BaseException):
                raise e
            yield e
            e = q.get()

    return data_reader


def firstn(reader: Callable, n: int) -> Callable:
    """First n samples (reference: decorator.py firstn)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper: Callable, reader: Callable, process_num: int,
                 buffer_size: int, order: bool = False) -> Callable:
    """Parallel map over a reader with worker threads
    (reference: decorator.py xmap_readers)."""

    end = object()

    def data_reader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feeder():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as e:
                out_q.put(e)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def worker():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                try:
                    out_q.put((i, mapper(sample)))
                except BaseException as e:
                    out_q.put(e)
                    out_q.put(end)
                    return

        threading.Thread(target=feeder, daemon=True).start()
        workers = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(process_num)
        ]
        for w in workers:
            w.start()

        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                if isinstance(item, BaseException):
                    raise item
                i, mapped = item
                pending[i] = mapped
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                if isinstance(item, BaseException):
                    raise item
                yield item[1]

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Merge several readers concurrently.  The reference forks processes;
    here worker threads suffice (the GIL releases during numpy/jax work and
    TPU hosts are fed from a single process)."""

    end = object()

    def data_reader():
        q: queue.Queue = queue.Queue(queue_size)

        def worker(r):
            try:
                for sample in r():
                    q.put(sample)
            except BaseException as e:
                q.put(e)
            finally:
                q.put(end)

        for r in readers:
            threading.Thread(target=worker, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            sample = q.get()
            if sample is end:
                finished += 1
            elif isinstance(sample, BaseException):
                raise sample
            else:
                yield sample

    return data_reader


def batch(reader: Callable, batch_size: int, drop_last: bool = False) -> Callable:
    """Group samples into minibatches (reference: python/paddle/batch.py)."""

    def batch_reader():
        r = reader()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
