"""Reader creators (reference: python/paddle/reader/creator.py —
np_array, text_file, recordio)."""

from __future__ import annotations

import glob as _glob

__all__ = ["np_array", "text_file", "recordio"]


def np_array(x):
    """Yield elements of a numpy vector / rows of a matrix / sub-planes of
    a higher-rank array (reference: creator.py np_array)."""

    def reader():
        if x.ndim < 1:
            # (the reference falls through here and crashes iterating a
            # 0-d array; yield-and-stop is the documented behavior)
            yield x
            return
        for e in x:
            yield e

    return reader


def text_file(path):
    """Yield a text file line by line, trailing newline stripped
    (reference: creator.py text_file)."""

    def reader():
        with open(path, "r") as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def recordio(paths, buf_size=100):
    """Yield raw records from recordio files — a comma-separated string
    (glob patterns supported) or a list of paths (reference:
    creator.py recordio over the recordio package; here the native
    chunked reader in paddle_tpu.recordio)."""
    from . import buffered
    from ..recordio import RecordIOScanner

    def reader():
        if isinstance(paths, str):
            path_list = [
                p for pat in paths.split(",") for p in
                (sorted(_glob.glob(pat)) or [pat])
            ]
        else:
            path_list = list(paths)
        for fn in path_list:
            with RecordIOScanner(fn) as sc:
                for rec in sc:
                    yield rec

    return buffered(reader, buf_size)
