"""Op frequency statistics
(reference: python/paddle/fluid/contrib/op_frequence.py op_freq_statistic —
counts single ops and adjacent op pairs across a program, for deciding
which fusions matter).  On TPU, XLA does the fusing, but the census is
still the tool for spotting hot op sequences worth a Pallas kernel.
"""

from __future__ import annotations

from collections import OrderedDict

from ..core.framework import Program

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program: Program):
    """Return (single-op counts, adjacent-pair counts), both ordered by
    descending frequency (reference: op_frequence.py:20)."""
    if not isinstance(program, Program):
        raise TypeError(f"expected a Program, got {type(program)!r}")

    uni_op_freq: dict = OrderedDict()
    adj_2_op_freq: dict = OrderedDict()
    op_in_ops = {}  # output var -> op type producing it

    for block in program.blocks:
        for op in block.desc.ops:
            uni_op_freq[op.type] = uni_op_freq.get(op.type, 0) + 1
            # count producer->consumer adjacency through each input var
            for name in op.input_arg_names():
                prev = op_in_ops.get(name)
                if prev is not None:
                    key = f"{prev},{op.type}"
                    adj_2_op_freq[key] = adj_2_op_freq.get(key, 0) + 1
            for name in op.output_arg_names():
                op_in_ops[name] = op.type

    uni = OrderedDict(
        sorted(uni_op_freq.items(), key=lambda kv: kv[1], reverse=True)
    )
    adj = OrderedDict(
        sorted(adj_2_op_freq.items(), key=lambda kv: kv[1], reverse=True)
    )
    return uni, adj
