"""StateCell / TrainingDecoder / BeamSearchDecoder
(reference: python/paddle/fluid/contrib/decoder/beam_search_decoder.py —
the seq2seq decoder API the MT demos use: a StateCell describes one RNN
step as a state-update function; TrainingDecoder runs it under DynamicRNN
with teacher forcing; BeamSearchDecoder runs it under a While loop doing
beam search at inference).

TPU-native representation: the reference tracks beams through LoD lineage
(sequence_expand before the step, LoD backtrace in beam_search_decode).
Here beams are dense rows [beam_size, ...] with explicit parent pointers
(ops/beam_search_ops.py) — finished beams freeze in place, states are
re-ordered after selection by a gather on the parent index, and the loop
always runs to max_len (XLA-friendly static control flow; the decode trims
at end_id).  The user-facing API is unchanged.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

from ... import layers
from ...core.framework import Variable, default_main_program

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState:
    """Initial value of one decoder state
    (reference: beam_search_decoder.py:43): either an explicit `init`
    Variable (e.g. the encoder's last hidden) or a (shape, value) fill
    boot-strapped from `init_boot`'s batch."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the init batch size"
            )
        else:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype
            )
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class StateCell:
    """One decoder step as a pure state update
    (reference: beam_search_decoder.py:159).

    states: {name: InitState}; inputs: {name: Variable or None} (None =
    bound later, e.g. the step input under TrainingDecoder or the previous
    word's embedding under BeamSearchDecoder); out_state: which state is
    the step output.  The update itself is the @state_cell.state_updater
    function, which reads get_input/get_state and calls set_state.
    """

    def __init__(self, inputs: Dict[str, Optional[Variable]],
                 states: Dict[str, InitState], out_state: str,
                 name: Optional[str] = None):
        self._inputs = dict(inputs)
        self._states = dict(states)
        self._state_names = list(states)
        self._out_state_name = out_state
        self._cur_states: Dict[str, Variable] = {}
        self._cur_inputs: Dict[str, Variable] = {}
        self._state_updater = None
        self._decoder_obj = None
        self._states_ready = False

    # -- decoder attach/detach (reference: _enter_decoder/_leave_decoder)
    def _enter_decoder(self, decoder_obj):
        if self._decoder_obj is not None:
            raise ValueError("StateCell is already inside a decoder")
        self._decoder_obj = decoder_obj
        self._cur_states = {}
        self._states_ready = False

    def _leave_decoder(self, decoder_obj):
        if self._decoder_obj is not decoder_obj:
            raise ValueError("leaving a decoder this StateCell never entered")
        self._decoder_obj = None
        self._states_ready = False

    def _ensure_states(self):
        """Lazily materialize per-decoder state carriers on first access
        (reference: the lazy _switch_decoder), so TrainingDecoder memories
        are created after the user's step_input established the batch."""
        if self._states_ready:
            return
        d = self._decoder_obj
        if d is None:
            raise ValueError("StateCell must be used inside a decoder block")
        if d.type == _DecoderType.TRAINING:
            drnn = d.dynamic_rnn
            for name, init in self._states.items():
                self._cur_states[name] = drnn.memory(
                    init=init.value, need_reorder=init.need_reorder
                )
        else:  # BEAM_SEARCH: decoder owns array-backed carries
            for name, init in self._states.items():
                self._cur_states[name] = d._make_state_carry(name, init.value)
        self._states_ready = True

    # -- accessors (reference API) -------------------------------------
    def get_state(self, state_name: str) -> Variable:
        self._ensure_states()
        if state_name not in self._cur_states:
            raise ValueError(f"unknown state '{state_name}'")
        return self._cur_states[state_name]

    def get_input(self, input_name: str) -> Variable:
        if input_name not in self._cur_inputs:
            raise ValueError(f"input '{input_name}' not provided yet")
        return self._cur_inputs[input_name]

    def set_state(self, state_name: str, state_value: Variable) -> None:
        self._ensure_states()
        if state_name not in self._states:
            raise ValueError(f"unknown state '{state_name}'")
        self._cur_states[state_name] = state_value

    def state_updater(self, updater):
        """Decorator registering the step function
        (reference: beam_search_decoder.py:314)."""
        self._state_updater = updater

        def _decorator(cell):
            if cell is not self:
                raise TypeError("updater bound to a different StateCell")
            updater(cell)

        return _decorator

    def compute_state(self, inputs: Dict[str, Variable]) -> None:
        """Bind this step's inputs and run the updater
        (reference: beam_search_decoder.py:335)."""
        self._ensure_states()
        if self._state_updater is None:
            raise ValueError("no state_updater registered")
        self._cur_inputs = dict(self._inputs)
        for name, v in inputs.items():
            if name not in self._inputs:
                raise ValueError(f"unknown input '{name}'")
            self._cur_inputs[name] = v
        self._prev_states = {
            n: self._cur_states[n] for n in self._state_names
        }
        self._state_updater(self)

    def update_states(self) -> None:
        """Commit the step's states to the carrier
        (reference: beam_search_decoder.py:360).  Training: DynamicRNN
        update_memory; beam search: the decoder re-orders by beam parent
        and writes the carry itself after selection."""
        d = self._decoder_obj
        if d is None:
            raise ValueError("update_states outside a decoder block")
        if d.type == _DecoderType.TRAINING:
            for name in self._state_names:
                prev, cur = self._prev_states[name], self._cur_states[name]
                if prev is not cur:
                    d.dynamic_rnn.update_memory(prev, cur)

    def out_state(self) -> Variable:
        return self._cur_states[self._out_state_name]


class TrainingDecoder:
    """Teacher-forced decoding under DynamicRNN
    (reference: beam_search_decoder.py:384)."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell: StateCell, name: Optional[str] = None):
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._dynamic_rnn = layers.DynamicRNN()
        self._type = _DecoderType.TRAINING
        self._status = TrainingDecoder.BEFORE_DECODER

    @property
    def state_cell(self) -> StateCell:
        self._assert_in_decoder_block("state_cell")
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._dynamic_rnn

    @property
    def type(self):
        return self._type

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError("block() can only be invoked once")
        self._status = TrainingDecoder.IN_DECODER
        with self._dynamic_rnn.block():
            yield
        self._status = TrainingDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    def step_input(self, x):
        self._assert_in_decoder_block("step_input")
        return self._dynamic_rnn.step_input(x)

    def static_input(self, x):
        self._assert_in_decoder_block("static_input")
        return self._dynamic_rnn.static_input(x)

    def output(self, *outputs):
        self._assert_in_decoder_block("output")
        self._dynamic_rnn.output(*outputs)

    def __call__(self, *args, **kwargs):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError(
                "output is only visible after the decoder block closes"
            )
        return self._dynamic_rnn(*args, **kwargs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError(
                f"{method} must be invoked inside the decoder block"
            )


class BeamSearchDecoder:
    """Beam-search inference under a While loop
    (reference: beam_search_decoder.py:523).  decode() wires the default
    step — embed previous ids, run the StateCell, softmax over the target
    dict, beam-select — and __call__() returns the back-traced
    (translation_ids, translation_scores)."""

    BEFORE_BEAM_SEARCH_DECODER = 0
    IN_BEAM_SEARCH_DECODER = 1
    AFTER_BEAM_SEARCH_DECODER = 2

    def __init__(self, state_cell: StateCell, init_ids, init_scores,
                 target_dict_dim: int, word_dim: int,
                 input_var_dict: Optional[dict] = None, topk_size: int = 50,
                 sparse_emb: bool = True, max_len: int = 100,
                 beam_size: int = 1, end_id: int = 1,
                 name: Optional[str] = None):
        self._type = _DecoderType.BEAM_SEARCH
        self._counter = layers.fill_constant([1], "int64", 0)
        self._max_len = layers.fill_constant([1], "int64", max_len)
        self._cond = layers.less_than(self._counter, self._max_len)
        self._while_op = layers.While(self._cond)
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._status = BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER
        self._zero_idx = layers.fill_constant([1], "int64", 0)
        self._array_dict = {}     # read-var name -> carry var
        self._state_carries = {}  # state name -> carry var
        self._ids_array = None
        self._scores_array = None
        self._parents_array = None
        self._beam_size = beam_size
        self._end_id = end_id
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._topk_size = topk_size
        self._sparse_emb = sparse_emb
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})

    @property
    def type(self):
        return self._type

    @property
    def state_cell(self) -> StateCell:
        self._assert_in_decoder_block("state_cell")
        return self._state_cell

    @contextlib.contextmanager
    def _in_parent_block(self):
        """Append init ops to the block surrounding the While sub-block
        (reference: _parent_block + parent_block.append_op)."""
        program = default_main_program()
        sub_idx = program.current_block_idx
        parent_idx = program.current_block().parent_idx
        if parent_idx < 0:
            raise ValueError("decoder block has no parent")
        program.current_block_idx = parent_idx
        try:
            yield
        finally:
            program.current_block_idx = sub_idx

    @contextlib.contextmanager
    def block(self):
        """One beam step (reference: beam_search_decoder.py:617).  The
        counter advances and the loop condition refreshes when the block
        closes."""
        if self._status != BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER:
            raise ValueError("block() can only be invoked once")
        self._status = BeamSearchDecoder.IN_BEAM_SEARCH_DECODER
        with self._while_op.block():
            yield
            layers.increment(self._counter, value=1, in_place=True)
            layers.less_than(self._counter, self._max_len, cond=self._cond)
        self._status = BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER
        self._state_cell._leave_decoder(self)

    def early_stop(self):
        """Break the generation loop (reference: early_stop)."""
        self._assert_in_decoder_block("early_stop")
        false = layers.fill_constant([1], "bool", 0)
        layers.assign(false, self._cond)

    def _make_carry(self, init) -> Variable:
        """A loop-carried var initialized in the parent block."""
        with self._in_parent_block():
            return layers.assign(init)

    def _make_state_carry(self, name: str, init) -> Variable:
        carry = self._make_carry(init)
        self._state_carries[name] = carry
        return carry

    def read_array(self, init, is_ids: bool = False,
                   is_scores: bool = False) -> Variable:
        """Previous step's value of a loop-carried variable
        (reference: read_array — array semantics collapse to a dense
        carry here; ids/scores additionally record per-step selections
        for the final backtrace)."""
        self._assert_in_decoder_block("read_array")
        if is_ids and is_scores:
            raise ValueError("a variable cannot be both ids and scores")
        if not isinstance(init, Variable):
            raise TypeError("`init` must be a Variable")
        carry = self._make_carry(init)
        if is_ids:
            with self._in_parent_block():
                self._ids_array = layers.create_array(init.dtype)
                self._parents_array = layers.create_array("int64")
        elif is_scores:
            with self._in_parent_block():
                self._scores_array = layers.create_array(init.dtype)
        read_value = layers.assign(carry)
        self._array_dict[read_value.name] = carry
        return read_value

    def update_array(self, array, value):
        """Store this step's value into the carry read by read_array
        (reference: update_array)."""
        self._assert_in_decoder_block("update_array")
        carry = self._array_dict.get(array.name)
        if carry is None:
            raise ValueError("invoke read_array before update_array")
        layers.assign(value, carry)

    def decode(self):
        """The default beam step (reference: decode :653).  Override for
        custom decoding."""
        with self.block():
            prev_ids = self.read_array(init=self._init_ids, is_ids=True)
            prev_scores = self.read_array(
                init=self._init_scores, is_scores=True
            )
            prev_ids_embedding = layers.embedding(
                input=prev_ids,
                size=[self._target_dict_dim, self._word_dim],
                dtype="float32",
                is_sparse=self._sparse_emb,
            )

            feed_dict = {}
            update_dict = {}
            for name, init_var in self._input_var_dict.items():
                if name not in self._state_cell._inputs:
                    raise ValueError(
                        f"variable '{name}' not found in StateCell"
                    )
                read_var = self.read_array(init=init_var)
                update_dict[name] = read_var
                feed_dict[name] = read_var

            for input_name in self._state_cell._inputs:
                if input_name not in feed_dict:
                    feed_dict[input_name] = prev_ids_embedding

            self.state_cell.compute_state(inputs=feed_dict)
            current_state = self.state_cell.out_state()
            scores = layers.fc(
                current_state, size=self._target_dict_dim, act="softmax"
            )
            topk_scores, topk_indices = layers.topk(
                scores, k=min(self._topk_size, self._target_dict_dim)
            )
            accu_scores = layers.elementwise_add(
                layers.log(topk_scores),
                layers.reshape(prev_scores, [-1, 1]),
            )
            selected_ids, selected_scores = layers.beam_search(
                prev_ids, prev_scores, topk_indices, accu_scores,
                self._beam_size, end_id=self._end_id,
            )
            parent = selected_ids._parent_idx

            # record this step for the final backtrace, then re-order every
            # carried state by beam lineage (the dense equivalent of the
            # reference's sequence_expand-by-LoD)
            layers.array_write(selected_ids, self._counter,
                               array=self._ids_array)
            layers.array_write(selected_scores, self._counter,
                               array=self._scores_array)
            layers.array_write(parent, self._counter,
                               array=self._parents_array)

            self.state_cell.update_states()
            for name in self._state_cell._state_names:
                new_state = self._state_cell.get_state(name)
                layers.assign(
                    layers.gather(new_state, parent),
                    self._state_carries[name],
                )
            self.update_array(prev_ids, selected_ids)
            self.update_array(prev_scores, selected_scores)
            for name, read_var in update_dict.items():
                self.update_array(read_var, feed_dict[name])

    def __call__(self):
        """Back-trace the beams (reference: __call__ :802)."""
        if self._status != BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER:
            raise ValueError(
                "decode result is only visible outside the block"
            )
        return layers.beam_search_decode(
            self._ids_array, self._scores_array,
            beam_size=self._beam_size, end_id=self._end_id,
            parent_idx=self._parents_array,
        )

    def _assert_in_decoder_block(self, method):
        if self._status != BeamSearchDecoder.IN_BEAM_SEARCH_DECODER:
            raise ValueError(
                f"{method} must be invoked inside the decoder block"
            )
