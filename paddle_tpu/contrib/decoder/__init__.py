"""Beam-search decoder machinery
(reference: python/paddle/fluid/contrib/decoder/beam_search_decoder.py)."""

from .beam_search_decoder import (
    BeamSearchDecoder,
    InitState,
    StateCell,
    TrainingDecoder,
)

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]
