"""Contrib (reference: python/paddle/fluid/contrib/): quantize transpiler,
memory-usage estimate, beam-search decoder."""

from . import quantize  # noqa: F401
from .memory_usage_calc import memory_usage  # noqa: F401
