"""Contrib (reference: python/paddle/fluid/contrib/): quantize transpiler,
memory-usage estimate, op census, CTR reader, beam-search decoder,
high-level Trainer/Inferencer, HDFS + lookup-table utilities."""

from . import quantize  # noqa: F401
from .memory_usage_calc import memory_usage  # noqa: F401
from .op_frequence import op_freq_statistic  # noqa: F401
from . import reader  # noqa: F401
from . import utils  # noqa: F401
from . import decoder  # noqa: F401
from .decoder import BeamSearchDecoder, InitState, StateCell, TrainingDecoder  # noqa: F401
from .trainer import (  # noqa: F401
    BeginEpochEvent,
    BeginStepEvent,
    CheckpointConfig,
    EndEpochEvent,
    EndStepEvent,
    Trainer,
)
from .inferencer import Inferencer  # noqa: F401
