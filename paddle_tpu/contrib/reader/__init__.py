"""contrib readers (reference: python/paddle/fluid/contrib/reader/)."""

from .ctr_reader import ctr_reader

__all__ = ["ctr_reader"]
