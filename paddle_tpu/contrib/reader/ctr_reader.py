"""CTR file reader
(reference: python/paddle/fluid/contrib/reader/ctr_reader.py over the C++
create_ctr_reader op — thread_num workers stream svm-format CTR files
into a blocking queue that `read` ops pop).

TPU-native: the same multi-threaded file fan-out feeds the py_reader
queue machinery (layers/io_pyreader.py) — workers parse
`label slot:feasign ...` lines, batch them per slot, and the executor
pops ready feed dicts; start()/reset() follow the reference contract.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Sequence

import numpy as np

from ...core.framework import default_main_program
from ...core.lod import create_lod_tensor
from ...layers.io_pyreader import PyReader

__all__ = ["ctr_reader"]


def _parse_ctr_line(line: str, slots: Sequence[str]):
    """`label slot_name:feasign slot_name:feasign ...` -> (label, per-slot
    id lists); absent slots get [0] like the C++ reader's padding."""
    toks = line.split()
    label = int(toks[0])
    by_slot = {s: [] for s in slots}
    for t in toks[1:]:
        if ":" not in t:
            continue
        slot, feasign = t.rsplit(":", 1)
        if slot in by_slot:
            by_slot[slot].append(int(feasign))
    return label, [by_slot[s] or [0] for s in slots]


class _CTRReader(PyReader):
    """PyReader whose worker pool streams CTR files instead of a
    user generator."""

    def __init__(self, names, lod_levels, capacity, thread_num, batch_size,
                 file_list, slots):
        shapes = [[-1, 1]] * len(names)
        dtypes = ["int64"] * len(names)
        super().__init__(names, shapes, dtypes, lod_levels, capacity)
        self._thread_num = thread_num
        self._batch_size = batch_size
        self._file_list = list(file_list)
        self._slots = list(slots)

    def start(self):
        self._queue = queue.Queue(self._capacity)
        self._stop_event = threading.Event()
        files: queue.Queue = queue.Queue()
        for f in self._file_list:
            files.put(f)
        self._pending_lock = threading.Lock()

        def put_checked(q, stop, item) -> bool:
            """Bounded put that stays responsive to reset(): never block
            indefinitely on a queue nobody drains."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            q, stop = self._queue, self._stop_event
            try:
                while not stop.is_set():
                    try:
                        path = files.get_nowait()
                    except queue.Empty:
                        return
                    batch = []
                    with open(path) as f:
                        for line in f:
                            if stop.is_set():
                                return
                            line = line.strip()
                            if not line:
                                continue
                            batch.append(
                                _parse_ctr_line(line, self._slots)
                            )
                            if len(batch) == self._batch_size:
                                if not put_checked(
                                        q, stop, self._to_feed(batch)):
                                    return
                                batch = []
                    if batch:
                        if not put_checked(q, stop, self._to_feed(batch)):
                            return
            except BaseException as e:
                # surface IO/parse errors to the consumer instead of dying
                # silently into a clean-looking EOF (base PyReader._worker
                # does the same)
                if not stop.is_set():
                    q.put(e)
            finally:
                with self._pending_lock:
                    self._pending -= 1
                    if self._pending <= 0:
                        q.put(self._end)  # end-of-pass sentinel

        self._thread = None  # base-class slot unused; we own a pool
        self._pool = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self._thread_num)
        ]
        # each worker decrements pending exactly once on exit; the last one
        # out emits the end-of-pass sentinel
        self._pending = len(self._pool)
        for t in self._pool:
            t.start()

    def _to_feed(self, batch):
        label = np.array([[b[0]] for b in batch], dtype=np.int64)
        feed = {self._names[0]: label}
        for i, name in enumerate(self._names[1:]):
            rows = [np.asarray(b[1][i], dtype=np.int64)[:, None]
                    for b in batch]
            feed[name] = create_lod_tensor(rows)
        return feed

    def reset(self):
        stop = getattr(self, "_stop_event", None)
        if stop is not None:
            stop.set()
        q = self._queue
        self._queue = None
        if q is not None:
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
        for t in getattr(self, "_pool", []):
            t.join(timeout=5.0)
        self._pool = []


def ctr_reader(feed_data, capacity: int, thread_num: int, batch_size: int,
               file_list: Sequence[str], slots: Sequence[str], name=None):
    """Create a CTR reader feeding `feed_data` vars: feed_data[0] is the
    int64 label [N,1], the rest are lod-level-1 id vars, one per slot
    (reference: ctr_reader.py:47).  Returns the reader; call start() per
    pass, executor pops batches on feed=None runs."""
    if len(feed_data) != len(slots) + 1:
        raise ValueError(
            f"feed_data must be [label] + one var per slot: "
            f"{len(feed_data)} vars vs {len(slots)} slots"
        )
    names = [v.name for v in feed_data]
    lod_levels = [getattr(v, "lod_level", 0) for v in feed_data]
    reader = _CTRReader(names, lod_levels, capacity, thread_num, batch_size,
                        file_list, slots)
    program = default_main_program()
    program._py_readers = getattr(program, "_py_readers", [])
    program._py_readers.append(reader)
    return reader
