"""contrib utilities (reference: python/paddle/fluid/contrib/utils/)."""

from . import hdfs_utils  # noqa: F401
from . import lookup_table_utils  # noqa: F401
from .hdfs_utils import HDFSClient, multi_download, multi_upload
from .lookup_table_utils import (
    convert_dist_to_sparse_program,
    load_persistables_for_increment,
    load_persistables_for_inference,
)

__all__ = [
    "HDFSClient",
    "multi_download",
    "multi_upload",
    "convert_dist_to_sparse_program",
    "load_persistables_for_increment",
    "load_persistables_for_inference",
]
