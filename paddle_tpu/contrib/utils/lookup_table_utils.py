"""Distributed lookup-table program surgery + checkpoint loading
(reference: python/paddle/fluid/contrib/utils/lookup_table_utils.py —
convert_dist_to_sparse_program rewrites the pserver-prefetch lookup into
lookup_sparse_table for single-machine incremental training;
load_persistable_vars restores a trained model whose embedding lives in
per-pserver shard files).
"""

from __future__ import annotations

import os
from typing import Optional

from ... import io as fluid_io
from ...core.framework import Program

__all__ = [
    "convert_dist_to_sparse_program",
    "load_persistables_for_increment",
    "load_persistables_for_inference",
]

_LOOKUP = "lookup_table"


def convert_dist_to_sparse_program(program: Program) -> Program:
    """Clone the program with every distributed lookup_table rewritten to
    the auto-growth lookup_sparse_table op, so a model trained against a
    parameter server keeps training on one machine without materializing
    the dense vocab (reference: lookup_table_utils.py:83)."""
    out = program.clone()
    block = out.global_block().desc
    changed = False
    for op in block.ops:
        if op.type == _LOOKUP and op.attr("is_distributed", False):
            op.type = "lookup_sparse_table"
            op.attrs["is_distributed"] = False
            op.attrs.setdefault("auto_grown_table", True)
            changed = True
    if not changed:
        raise ValueError(
            "no distributed lookup_table op in the program; nothing to "
            "convert (mark the embedding with is_distributed=True)"
        )
    out.desc.bump()
    return out


def _load_table_shards(executor, dirname: str, table_name: str,
                       program: Program) -> None:
    """Concatenate per-pserver table shard files `<table>.block<N>` into
    the scope var (reference: _load_lookup_table_vars — each pserver saved
    its slice; reassembly is row-order concat)."""
    import numpy as np

    from ...core.scope import global_scope

    def block_no(fname: str) -> int:
        stem = fname[:-4] if fname.endswith(".npy") else fname
        return int(stem.rsplit("block", 1)[-1]) if "block" in stem else -1

    shards = sorted(
        (f for f in os.listdir(dirname)
         if f in (table_name, table_name + ".npy")
         or f.startswith(table_name + ".block")),
        key=block_no,
    )
    if not shards:
        raise FileNotFoundError(
            f"no shard files for table '{table_name}' under {dirname!r}"
        )
    parts = [np.load(os.path.join(dirname, f), allow_pickle=False)
             for f in shards]
    global_scope().set_var(table_name, np.concatenate(parts, axis=0))


def load_persistables_for_increment(dirname: str, executor, program: Program,
                                    lookup_table_var,
                                    lookup_table_var_path: Optional[str] = None):
    """Load a dist-trained checkpoint to continue training locally: dense
    persistables via the normal loader, the big table from its shard files
    (reference: lookup_table_utils.py load_persistables_for_increment)."""
    table_name = (lookup_table_var if isinstance(lookup_table_var, str)
                  else lookup_table_var.name)
    fluid_io.load_vars(
        executor, dirname, main_program=program,
        predicate=lambda v: fluid_io.is_persistable(v)
        and v.name != table_name,
    )
    _load_table_shards(executor, lookup_table_var_path or dirname,
                       table_name, program)


def load_persistables_for_inference(dirname: str, executor, program: Program,
                                    lookup_table_var_name: str):
    """Same reassembly for an inference program
    (reference: lookup_table_utils.py load_persistables_for_inference)."""
    load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var_name)
    return program
