"""HDFS helpers (reference: python/paddle/fluid/contrib/utils/hdfs_utils.py
— shells out to the hadoop binary for ls/put/get/mv/rm, plus a
multi-process downloader).

Same contract: every operation execs `<hadoop_bin> fs` with the configured
name-node; without a hadoop binary the client raises a clear error at
call time (construction stays cheap so configs can be built anywhere).
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Dict, List, Optional

__all__ = ["HDFSClient", "multi_download", "multi_upload"]


class HDFSClient:
    def __init__(self, hadoop_home: str, configs: Dict[str, str]):
        self.pre_commands: List[str] = []
        hadoop_bin = os.path.join(hadoop_home, "bin", "hadoop")
        self.pre_commands.append(hadoop_bin)
        dfs = "fs"
        self.pre_commands.append(dfs)
        for k, v in configs.items():
            self.pre_commands.extend(["-D", f"{k}={v}"])
        self._hadoop_bin = hadoop_bin

    def _run(self, args: List[str], retry_times: int = 5) -> (int, str):
        if not (os.path.exists(self._hadoop_bin)
                or shutil.which(self._hadoop_bin)):
            raise RuntimeError(
                f"hadoop binary not found at {self._hadoop_bin!r}; HDFS "
                "operations need a hadoop install (zero-egress environments "
                "should use local paths instead)"
            )
        cmd = self.pre_commands + args
        last = ""
        for _ in range(max(1, retry_times)):
            proc = subprocess.run(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
            last = proc.stdout
            if proc.returncode == 0:
                return 0, last
        return 1, last

    def is_exist(self, hdfs_path: str) -> bool:
        rc, _ = self._run(["-test", "-e", hdfs_path], retry_times=1)
        return rc == 0

    def is_dir(self, hdfs_path: str) -> bool:
        rc, _ = self._run(["-test", "-d", hdfs_path], retry_times=1)
        return rc == 0

    def delete(self, hdfs_path: str) -> bool:
        rc, _ = self._run(["-rm", "-r", "-skipTrash", hdfs_path])
        return rc == 0

    def rename(self, src: str, dst: str, overwrite: bool = False) -> bool:
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        rc, _ = self._run(["-mv", src, dst])
        return rc == 0

    def makedirs(self, hdfs_path: str) -> bool:
        rc, _ = self._run(["-mkdir", "-p", hdfs_path])
        return rc == 0

    def ls(self, hdfs_path: str) -> List[str]:
        rc, out = self._run(["-ls", hdfs_path])
        if rc != 0:
            return []
        files = []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                files.append(parts[-1])
        return files

    def lsr(self, hdfs_path: str) -> List[str]:
        """Recursive listing of FILES only (directory rows start with a
        'd' permission flag and would -get recursively if kept)."""
        rc, out = self._run(["-ls", "-R", hdfs_path])
        if rc != 0:
            return []
        files = []
        for ln in out.splitlines():
            parts = ln.split()
            if len(parts) >= 8 and not parts[0].startswith("d"):
                files.append(parts[-1])
        return files

    def upload(self, hdfs_path: str, local_path: str,
               overwrite: bool = False, retry_times: int = 5) -> bool:
        if overwrite and self.is_exist(hdfs_path):
            self.delete(hdfs_path)
        rc, _ = self._run(["-put", local_path, hdfs_path], retry_times)
        return rc == 0

    def download(self, hdfs_path: str, local_path: str,
                 overwrite: bool = False, unzip: bool = False) -> bool:
        if overwrite and os.path.exists(local_path):
            if os.path.isdir(local_path):
                shutil.rmtree(local_path)
            else:
                os.remove(local_path)
        rc, _ = self._run(["-get", hdfs_path, local_path])
        return rc == 0


def multi_download(client: HDFSClient, hdfs_path: str, local_path: str,
                   trainer_id: int, trainers: int,
                   multi_processes: int = 5) -> List[str]:
    """Download this trainer's shard of the files under hdfs_path
    (reference: hdfs_utils.py multi_download — file i goes to trainer
    i % trainers), using a small process pool."""
    from multiprocessing.pool import ThreadPool

    files = client.lsr(hdfs_path)
    mine = [f for i, f in enumerate(files) if i % trainers == trainer_id]
    os.makedirs(local_path, exist_ok=True)
    prefix = hdfs_path.rstrip("/") + "/"

    def fetch(f):
        # keep the sub-directory structure: same-named files in different
        # dirs must not collapse onto one basename
        rel = f[len(prefix):] if f.startswith(prefix) else os.path.basename(f)
        dst = os.path.join(local_path, rel)
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        client.download(f, dst)
        return dst

    with ThreadPool(max(1, multi_processes)) as pool:
        return list(pool.map(fetch, mine))


def multi_upload(client: HDFSClient, hdfs_path: str, local_path: str,
                 multi_processes: int = 5, overwrite: bool = False):
    """Upload every file under local_path with a small process pool."""
    from multiprocessing.pool import ThreadPool

    todo = []
    for root, _, names in os.walk(local_path):
        for n in names:
            todo.append(os.path.join(root, n))
    client.makedirs(hdfs_path)

    def put(f):
        rel = os.path.relpath(f, local_path)  # preserve sub-dirs (shards!)
        dst = os.path.join(hdfs_path, rel)
        d = os.path.dirname(dst)
        if d and d != hdfs_path:
            client.makedirs(d)
        client.upload(dst, f, overwrite=overwrite)

    with ThreadPool(max(1, multi_processes)) as pool:
        list(pool.map(put, todo))
