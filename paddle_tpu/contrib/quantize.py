"""QuantizeTranspiler (reference:
python/paddle/fluid/contrib/quantize/quantize_transpiler.py).

Rewrites a training program for quantization-aware training: inserts
fake_quantize ops on the inputs/weights of mul / conv2d / depthwise_conv2d
ops.  The straight-through estimator lives in the op lowerings
(paddle_tpu/ops/quant_ops.py), so the rewritten program trains directly.
"""

from __future__ import annotations

from typing import Optional

from ..core.framework import Program, default_main_program, unique_name
from ..core.proto import OpDesc

__all__ = ["QuantizeTranspiler"]

# reference: quantize_transpiler.py:32 _QUANTIZABLE_OP_TYPES (matmul is
# NOT quantized there either; every member has a freeze_program int8 form)
_QUANTIZABLE = {"mul", "conv2d", "depthwise_conv2d"}


class QuantizeTranspiler:
    def __init__(
        self,
        weight_bits: int = 8,
        activation_bits: int = 8,
        activation_quantize_type: str = "abs_max",
        weight_quantize_type: str = "abs_max",
        window_size: int = 10000,
    ):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        if activation_quantize_type not in ("abs_max", "range_abs_max"):
            raise ValueError(
                f"unknown activation_quantize_type {activation_quantize_type}"
            )
        self.activation_quantize_type = activation_quantize_type
        self.window_size = window_size

    def training_transpile(
        self,
        program: Optional[Program] = None,
        startup_program: Optional[Program] = None,
    ) -> None:
        """Insert fake-quant ops before every quantizable op's float inputs
        (reference: quantize_transpiler.py training_transpile)."""
        program = program or default_main_program()
        block = program.global_block()
        desc = block.desc
        quantized: dict = {}

        new_ops = []
        for op in desc.ops:
            if op.type in _QUANTIZABLE and not op.attr("__skip_quant__", False):
                for slot in ("X", "Y", "Input", "Filter"):
                    names = op.input(slot)
                    if not names:
                        continue
                    n = names[0]
                    if n.endswith("@GRAD"):
                        continue
                    if n not in quantized:
                        qname = unique_name(n + ".quantized")
                        v = block._find_var_recursive(n)
                        if v is None:
                            continue
                        block.create_var(
                            name=qname, shape=list(v.shape), dtype=v.dtype
                        )
                        is_weight = slot in ("Y", "Filter")
                        qtype = (
                            "fake_quantize_abs_max"
                            if is_weight
                            or self.activation_quantize_type == "abs_max"
                            else "fake_quantize_range_abs_max"
                        )
                        q = OpDesc(
                            type=qtype,
                            inputs={"X": [n]},
                            outputs={"Out": [qname]},
                        )
                        if qtype == "fake_quantize_range_abs_max":
                            # running-max state: a persistable scale var fed
                            # back through InScale each step (the reference's
                            # scale window, O(1)-state form)
                            sname = unique_name(n + ".scale")
                            block.create_var(
                                name=sname, shape=[1], dtype=v.dtype,
                                persistable=True,
                            )
                            self._init_scale_var(startup_program, sname)
                            q.inputs["InScale"] = [sname]
                            q.outputs["OutScale"] = [sname]
                            q.attrs["window_size"] = self.window_size
                        else:
                            sname = unique_name(n + ".scale")
                            block.create_var(name=sname, shape=[1], dtype=v.dtype)
                            q.outputs["OutScale"] = [sname]
                        q.attrs["bit_length"] = (
                            self.weight_bits if is_weight
                            else self.activation_bits
                        )
                        new_ops.append(q)
                        quantized[n] = qname
                    op.inputs[slot] = [quantized[n]] + list(names[1:])
            new_ops.append(op)
        desc.ops[:] = new_ops
        self._transpile_backward(desc, quantized)
        program.desc.bump()  # in-place rewrite: invalidate compiled caches

    @staticmethod
    def _transpile_backward(desc, quantized: dict) -> None:
        """Rename matching *_grad op inputs to the quantized var names
        (reference: quantize_transpiler.py _transpile_backward).

        Under this compiler the rename is belt-and-braces: grad ops replay
        the forward op's jax.vjp closure (core/compiler.py _lower_grad_op),
        which was traced AFTER the forward inputs were renamed, so gradients
        already differentiate through the quantized forward (straight-through
        on the fake_quantize boundary).  The rename keeps the program desc
        consistent with what actually executes, for tools that read it."""
        for op in desc.ops:
            if not op.type.endswith("_grad"):
                continue
            for slot in ("X", "Y", "Input", "Filter"):
                names = op.inputs.get(slot)
                if names and names[0] in quantized:
                    op.inputs[slot] = [quantized[names[0]]] + list(names[1:])

    @staticmethod
    def _init_scale_var(startup_program: Optional[Program], name: str) -> None:
        from ..core.framework import default_startup_program

        startup = startup_program or default_startup_program()
        sb = startup.global_block()
        sv = sb.create_var(name=name, shape=[1], dtype="float32",
                           persistable=True)
        sb.append_op(
            type="fill_constant", inputs={}, outputs={"Out": [sv]},
            attrs={"shape": [1], "dtype": 5, "value": 0.0,
                   "force_cpu": False},
        )

    def freeze_program(self, program: Optional[Program] = None, place=None,
                       scope=None) -> None:
        """reference: quantize_transpiler.py freeze_program — convert the
        QAT program to REAL int8 inference.  Weight tables are quantized
        offline into int8 scope vars; each quantized mul/conv2d becomes a
        mul_int8/conv2d_int8 op whose dot runs int8xint8 -> int32 on the
        MXU with one fp32 rescale; the fake_quantize ops disappear.
        Activation scales: range_abs_max ops donate their trained running
        scale (wired as XScale); abs_max activations quantize dynamically
        at runtime inside the int8 op.

        Call on an inference program (clone(for_test=True) of the
        QAT-transpiled program) with the trained scope."""
        import numpy as np

        from ..core.scope import global_scope

        program = program or default_main_program()
        scope = scope or global_scope()
        block = program.global_block()
        desc = block.desc
        bin_cnt = (1 << (self.weight_bits - 1)) - 1

        # map: quantized-output name -> its fake_quantize producer op
        producers = {}
        for op in desc.ops:
            if op.type.startswith("fake_quantize"):
                producers[op.output("Out")[0]] = op

        _INT8 = {"mul": ("mul_int8", "X", "Y"),
                 "conv2d": ("conv2d_int8", "Input", "Filter"),
                 "depthwise_conv2d": ("conv2d_int8", "Input", "Filter")}

        used_fq: set = set()
        for op in desc.ops:
            if op.type not in _INT8:
                continue
            new_type, x_slot, w_slot = _INT8[op.type]
            xq_names = op.inputs.get(x_slot)
            wq_names = op.inputs.get(w_slot)
            if not xq_names or not wq_names:
                continue
            xq, wq = xq_names[0], wq_names[0]
            if xq not in producers or wq not in producers:
                continue  # not a QAT-rewritten op
            x_fq, w_fq = producers[xq], producers[wq]

            # 1. weight: quantize the trained fp32 table offline
            w_name = w_fq.input("X")[0]
            w_val = scope.find_var(w_name)
            if w_val is None:
                raise RuntimeError(
                    f"freeze_program: weight '{w_name}' not in scope — run "
                    "the startup program / load the checkpoint first")
            w_np = np.asarray(w_val, dtype=np.float32)
            sw = float(np.max(np.abs(w_np))) or 1e-8
            w_i8 = np.clip(np.round(w_np / sw * bin_cnt), -bin_cnt,
                           bin_cnt).astype(np.int8)
            i8_name = w_name + ".int8"
            sw_name = w_name + ".wscale"
            block.create_var(name=i8_name, shape=list(w_np.shape),
                             dtype="int8", persistable=True)
            block.create_var(name=sw_name, shape=[1], dtype="float32",
                             persistable=True)
            scope.set_var(i8_name, w_i8)
            scope.set_var(sw_name, np.asarray([sw], np.float32))

            # 2. rewire: original float activation in, int8 weight in
            if op.type == "depthwise_conv2d":
                # the depthwise lowering injects groups = input channels
                # at run time (nn_ops.py); the generic conv2d_int8 lowering
                # reads the attr, so pin it from the input desc
                x_desc = block._find_var_recursive(x_fq.input("X")[0])
                if x_desc is not None:
                    op.attrs["groups"] = int(x_desc.shape[1])
            op.type = new_type
            op.inputs[x_slot] = [x_fq.input("X")[0]]
            op.inputs[w_slot] = [i8_name]
            op.inputs["WScale"] = [sw_name]
            if x_fq.type == "fake_quantize_range_abs_max":
                # trained running scale (persistable InScale state var)
                op.inputs["XScale"] = [x_fq.input("InScale")[0]]
            op.attrs["bit_length"] = self.activation_bits
            op.attrs["weight_bits"] = self.weight_bits
            used_fq.add(id(x_fq))
            used_fq.add(id(w_fq))

        if used_fq:
            # drop a fake_quantize op only when nothing still reads its
            # output (a shared .quantized var may feed an unfrozen consumer)
            still_read: set = set()
            for op in desc.ops:
                if id(op) in used_fq:
                    continue
                for names in op.inputs.values():
                    still_read.update(names)
            desc.ops[:] = [
                op for op in desc.ops
                if id(op) not in used_fq or op.output("Out")[0] in still_read
            ]
            program.desc.bump()
