"""QuantizeTranspiler (reference:
python/paddle/fluid/contrib/quantize/quantize_transpiler.py).

Rewrites a training program for quantization-aware training: inserts
fake_quantize ops on the inputs/weights of mul / conv2d / depthwise_conv2d
ops.  The straight-through estimator lives in the op lowerings
(paddle_tpu/ops/quant_ops.py), so the rewritten program trains directly.
"""

from __future__ import annotations

from typing import Optional

from ..core.framework import Program, default_main_program, unique_name
from ..core.proto import OpDesc

__all__ = ["QuantizeTranspiler"]

_QUANTIZABLE = {"mul", "matmul", "conv2d", "depthwise_conv2d"}


class QuantizeTranspiler:
    def __init__(
        self,
        weight_bits: int = 8,
        activation_bits: int = 8,
        activation_quantize_type: str = "abs_max",
        weight_quantize_type: str = "abs_max",
        window_size: int = 10000,
    ):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        if activation_quantize_type not in ("abs_max", "range_abs_max"):
            raise ValueError(
                f"unknown activation_quantize_type {activation_quantize_type}"
            )
        self.activation_quantize_type = activation_quantize_type
        self.window_size = window_size

    def training_transpile(
        self,
        program: Optional[Program] = None,
        startup_program: Optional[Program] = None,
    ) -> None:
        """Insert fake-quant ops before every quantizable op's float inputs
        (reference: quantize_transpiler.py training_transpile)."""
        program = program or default_main_program()
        block = program.global_block()
        desc = block.desc
        quantized: dict = {}

        new_ops = []
        for op in desc.ops:
            if op.type in _QUANTIZABLE and not op.attr("__skip_quant__", False):
                for slot in ("X", "Y", "Input", "Filter"):
                    names = op.input(slot)
                    if not names:
                        continue
                    n = names[0]
                    if n.endswith("@GRAD"):
                        continue
                    if n not in quantized:
                        qname = unique_name(n + ".quantized")
                        v = block._find_var_recursive(n)
                        if v is None:
                            continue
                        block.create_var(
                            name=qname, shape=list(v.shape), dtype=v.dtype
                        )
                        is_weight = slot in ("Y", "Filter")
                        qtype = (
                            "fake_quantize_abs_max"
                            if is_weight
                            or self.activation_quantize_type == "abs_max"
                            else "fake_quantize_range_abs_max"
                        )
                        q = OpDesc(
                            type=qtype,
                            inputs={"X": [n]},
                            outputs={"Out": [qname]},
                        )
                        if qtype == "fake_quantize_range_abs_max":
                            # running-max state: a persistable scale var fed
                            # back through InScale each step (the reference's
                            # scale window, O(1)-state form)
                            sname = unique_name(n + ".scale")
                            block.create_var(
                                name=sname, shape=[1], dtype=v.dtype,
                                persistable=True,
                            )
                            self._init_scale_var(startup_program, sname)
                            q.inputs["InScale"] = [sname]
                            q.outputs["OutScale"] = [sname]
                            q.attrs["window_size"] = self.window_size
                        else:
                            sname = unique_name(n + ".scale")
                            block.create_var(name=sname, shape=[1], dtype=v.dtype)
                            q.outputs["OutScale"] = [sname]
                        q.attrs["bit_length"] = (
                            self.weight_bits if is_weight
                            else self.activation_bits
                        )
                        new_ops.append(q)
                        quantized[n] = qname
                    op.inputs[slot] = [quantized[n]] + list(names[1:])
            new_ops.append(op)
        desc.ops[:] = new_ops
        self._transpile_backward(desc, quantized)
        program.desc.bump()  # in-place rewrite: invalidate compiled caches

    @staticmethod
    def _transpile_backward(desc, quantized: dict) -> None:
        """Rename matching *_grad op inputs to the quantized var names
        (reference: quantize_transpiler.py _transpile_backward).

        Under this compiler the rename is belt-and-braces: grad ops replay
        the forward op's jax.vjp closure (core/compiler.py _lower_grad_op),
        which was traced AFTER the forward inputs were renamed, so gradients
        already differentiate through the quantized forward (straight-through
        on the fake_quantize boundary).  The rename keeps the program desc
        consistent with what actually executes, for tools that read it."""
        for op in desc.ops:
            if not op.type.endswith("_grad"):
                continue
            for slot in ("X", "Y", "Input", "Filter"):
                names = op.inputs.get(slot)
                if names and names[0] in quantized:
                    op.inputs[slot] = [quantized[names[0]]] + list(names[1:])

    @staticmethod
    def _init_scale_var(startup_program: Optional[Program], name: str) -> None:
        from ..core.framework import default_startup_program

        startup = startup_program or default_startup_program()
        sb = startup.global_block()
        sv = sb.create_var(name=name, shape=[1], dtype="float32",
                           persistable=True)
        sb.append_op(
            type="fill_constant", inputs={}, outputs={"Out": [sv]},
            attrs={"shape": [1], "dtype": 5, "value": 0.0,
                   "force_cpu": False},
        )

    def freeze_program(self, program: Optional[Program] = None, place=None,
                       scope=None) -> None:
        """reference: quantize_transpiler.py freeze_program — converts fake
        quant to real int8 for deployment.  Under XLA the quantized graph
        already runs fused; freezing is a no-op retained for API parity."""
        return None
