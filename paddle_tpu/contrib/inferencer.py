"""High-level Inferencer API
(reference: python/paddle/fluid/contrib/inferencer.py — builds the
inference program from a callback, loads params, and runs feeds through a
private scope)."""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .. import io as fluid_io
from ..core.executor import Executor
from ..core.framework import Program, program_guard, unique_name_guard
from ..core.scope import Scope, scope_guard
from .trainer import check_and_get_place

__all__ = ["Inferencer"]


class Inferencer:
    """reference: inferencer.py:31.

    Args:
        infer_func: callback building the inference graph; returns the
            prediction Variable(s).
        param_path: directory save_params/save_persistables wrote.
        place: CPUPlace/TPUPlace; defaults to TPU when available.
        parallel: accepted for API parity; XLA owns intra-chip parallelism.

    infer() routes through a serving.Engine in pass-through mode (one
    request per dispatch, feed forwarded verbatim — LoD feeds included):
    every call shares the executor's compiled-program cache through ONE
    ExecutorBackend and gains the engine's deadline/metrics story for
    free (serving.RequestTimeoutError on expiry; queue-depth/latency
    instruments under FLAGS_observability).  The public signature is
    unchanged."""

    def __init__(self, infer_func: Callable, param_path: str, place=None,
                 parallel: bool = False):
        self.param_path = param_path
        self.scope = Scope()
        self.parallel = parallel
        self.place = check_and_get_place(place)

        self.startup_program = Program()
        self.inference_program = Program()
        with program_guard(self.inference_program, self.startup_program), \
                unique_name_guard():
            outs = infer_func()
            self.predict_vars = (list(outs) if isinstance(outs, (list, tuple))
                                 else [outs])
        self.inference_program = self.inference_program.clone(for_test=True)

        self.exe = Executor(self.place, donate_states=False)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            fluid_io.load_persistables(
                self.exe, param_path, main_program=self.inference_program)
        self._engine = None  # built lazily on the first infer()
        self._engine_lock = threading.Lock()

    def _get_engine(self):
        # double-checked under a lock: concurrent first infer() calls
        # must not each build (and half-leak) a dispatcher thread
        if self._engine is None:
            with self._engine_lock:
                if self._engine is None:
                    from ..serving import Engine, EngineConfig

                    # buckets=() selects pass-through mode: no
                    # concat/pad/split, so arbitrary feed shapes (and
                    # LoD values) ride untouched
                    self._engine = Engine.from_program(
                        self.exe, self.inference_program, self.predict_vars,
                        scope=self.scope, feed_names=None,
                        config=EngineConfig(buckets=()), name="inferencer")
        return self._engine

    def infer(self, inputs: dict, return_numpy: bool = True,
              timeout: Optional[float] = None):
        """inputs: {var name: numpy array} (reference: inferencer.py:80)."""
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}"
            )
        return self._get_engine().infer(
            inputs, timeout=timeout,
            call_kwargs={"return_numpy": return_numpy})

    def close(self) -> None:
        """Drain and stop the serving engine (idempotent)."""
        if self._engine is not None:
            self._engine.close()
            self._engine = None
