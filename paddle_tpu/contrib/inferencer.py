"""High-level Inferencer API
(reference: python/paddle/fluid/contrib/inferencer.py — builds the
inference program from a callback, loads params, and runs feeds through a
private scope)."""

from __future__ import annotations

from typing import Callable, Optional

from .. import io as fluid_io
from ..core.executor import Executor
from ..core.framework import Program, program_guard, unique_name_guard
from ..core.scope import Scope, scope_guard
from .trainer import check_and_get_place

__all__ = ["Inferencer"]


class Inferencer:
    """reference: inferencer.py:31.

    Args:
        infer_func: callback building the inference graph; returns the
            prediction Variable(s).
        param_path: directory save_params/save_persistables wrote.
        place: CPUPlace/TPUPlace; defaults to TPU when available.
        parallel: accepted for API parity; XLA owns intra-chip parallelism.
    """

    def __init__(self, infer_func: Callable, param_path: str, place=None,
                 parallel: bool = False):
        self.param_path = param_path
        self.scope = Scope()
        self.parallel = parallel
        self.place = check_and_get_place(place)

        self.startup_program = Program()
        self.inference_program = Program()
        with program_guard(self.inference_program, self.startup_program), \
                unique_name_guard():
            outs = infer_func()
            self.predict_vars = (list(outs) if isinstance(outs, (list, tuple))
                                 else [outs])
        self.inference_program = self.inference_program.clone(for_test=True)

        self.exe = Executor(self.place, donate_states=False)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            fluid_io.load_persistables(
                self.exe, param_path, main_program=self.inference_program)

    def infer(self, inputs: dict, return_numpy: bool = True):
        """inputs: {var name: numpy array} (reference: inferencer.py:80)."""
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}"
            )
        with scope_guard(self.scope):
            results = self.exe.run(
                program=self.inference_program, feed=inputs,
                fetch_list=self.predict_vars,
                return_numpy=return_numpy,
            )
        return results
