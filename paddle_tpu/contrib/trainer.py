"""High-level Trainer API
(reference: python/paddle/fluid/contrib/trainer.py — the event-driven
Trainer the book examples used: program built by callbacks, epoch/step
events, checkpointing via CheckpointConfig, test()/save_params()/
save_inference_model()).

TPU-native simplifications: the executor path is the block-compiling
Executor; distributed setup maps PADDLE_TRAINING_ROLE env to the
DistributeTranspiler exactly like the reference; checkpoints are
serial-numbered directories with a success marker and bounded retention.
"""

from __future__ import annotations

import os
import shutil
from typing import Callable, List, Optional, Sequence

from .. import io as fluid_io
from ..core.executor import Executor
from ..core.framework import Program, program_guard, unique_name_guard
from ..core.place import CPUPlace, TPUPlace
from ..core.scope import Scope, global_scope, scope_guard
from ..data_feeder import DataFeeder

__all__ = [
    "BeginEpochEvent", "EndEpochEvent", "BeginStepEvent", "EndStepEvent",
    "CheckpointConfig", "Trainer",
]


class BeginEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id: int):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id: int, step_id: int):
        self.epoch = epoch_id
        self.step = step_id
        # mirrors the reference flag: handlers set this to fetch metrics
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id: int, step_id: int, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """reference: trainer.py:100 — serial-numbered checkpoint dirs with
    bounded retention and an epoch/step save cadence."""

    def __init__(self, checkpoint_dir: Optional[str] = None,
                 max_num_checkpoints: int = 3,
                 epoch_interval: int = 1, step_interval: int = 10):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            os.getcwd(), "checkpoint")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, epoch_interval)
        self.step_interval = max(1, step_interval)
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial: Optional[int] = None


def check_and_get_place(place):
    """reference: trainer.py:143 — default to TPU when available."""
    if place is not None:
        return place
    try:
        return TPUPlace()
    except Exception:
        return CPUPlace()


class Trainer:
    """Event-driven training harness (reference: trainer.py:169).

    Args:
        train_func: callback building the program; returns [loss, ...]
            fetch vars (run under this trainer's program guard).
        optimizer_func: returns the Optimizer to apply.
        place, param_path (warm start), checkpoint_config, parallel.
    """

    def __init__(self, train_func: Callable, optimizer_func: Callable,
                 param_path: Optional[str] = None, place=None,
                 parallel: bool = False,
                 checkpoint_config: Optional[CheckpointConfig] = None):
        self.__stop = False
        self.parallel = parallel
        self.checkpoint_cfg = checkpoint_config
        self.place = check_and_get_place(place)
        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()

        # fresh name counters: the Inferencer rebuilds the graph under its
        # own guard, so auto-generated param names line up for checkpoints
        with program_guard(self.train_program, self.startup_program), \
                unique_name_guard():
            outs = train_func()
            if isinstance(outs, (list, tuple)):
                self.train_func_outputs = list(outs)
            else:
                self.train_func_outputs = [outs]
            self.loss = self.train_func_outputs[0]
            optimizer = optimizer_func()
            optimizer.minimize(self.loss)

        self.trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._dist_transpile_if_necessary()

        with self._prog_and_scope_guard():
            exe = Executor(self.place)
            exe.run(self.startup_program)
            if param_path:
                fluid_io.load_persistables(
                    exe, param_path, main_program=self.train_program)
            if self.checkpoint_cfg:
                self._load_checkpoint()

    # -- distributed setup (reference: _dist_transpile_if_necessary) ----
    def _dist_transpile_if_necessary(self):
        role = os.getenv("PADDLE_TRAINING_ROLE")
        if role is None:
            return
        from ..transpiler import DistributeTranspiler

        port = os.getenv("PADDLE_PSERVER_PORT", "6174")
        ips = os.getenv("PADDLE_PSERVER_IPS", "")
        eplist = [f"{ip.strip()}:{port}" for ip in ips.split(",") if ip]
        pserver_endpoints = ",".join(eplist)
        trainers = int(os.getenv("PADDLE_TRAINERS", "1"))
        current_endpoint = (
            os.getenv("PADDLE_CURRENT_IP", "") + ":" + port)
        t = DistributeTranspiler()
        with program_guard(self.train_program, self.startup_program):
            t.transpile(self.trainer_id, pservers=pserver_endpoints,
                        trainers=trainers)
        if role == "PSERVER":
            self.train_program = t.get_pserver_program(current_endpoint)
            self.startup_program = t.get_startup_program(
                current_endpoint, self.train_program)
        elif role == "TRAINER":
            self.train_program = t.get_trainer_program()
        else:
            raise ValueError(
                "PADDLE_TRAINING_ROLE must be PSERVER or TRAINER"
            )

    def _prog_and_scope_guard(self):
        import contextlib

        @contextlib.contextmanager
        def guard():
            with program_guard(self.train_program, self.startup_program):
                with scope_guard(self.scope):
                    yield

        return guard()

    def stop(self):
        """Handlers call this to end training early."""
        self.__stop = True

    # -- training / testing --------------------------------------------
    def train(self, num_epochs: int, event_handler: Callable,
              reader=None, feed_order: Optional[Sequence[str]] = None):
        """reference: trainer.py train — executor loop with events."""
        with self._prog_and_scope_guard():
            exe = Executor(self.place)
            feeder = self._feeder(feed_order)
            start_epoch = (self.checkpoint_cfg.epoch_id
                           if self.checkpoint_cfg else 0)
            for epoch_id in range(start_epoch, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if self.__stop:
                        return
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    fetch = (self.train_func_outputs
                             if begin.fetch_metrics else [])
                    metrics = exe.run(
                        program=self.train_program,
                        feed=feeder.feed(data), fetch_list=fetch,
                    )
                    event_handler(EndStepEvent(epoch_id, step_id, metrics))
                    if (self.checkpoint_cfg
                            and step_id % self.checkpoint_cfg.step_interval
                            == 0):
                        self._save_checkpoint(epoch_id, step_id)
                event_handler(EndEpochEvent(epoch_id))
                if (self.checkpoint_cfg
                        and epoch_id % self.checkpoint_cfg.epoch_interval
                        == 0):
                    self._save_checkpoint(epoch_id, 0)

    def test(self, reader, feed_order: Optional[Sequence[str]] = None
             ) -> List[float]:
        """Mean of the train_func outputs over the reader
        (reference: trainer.py _test_by_executor)."""
        import numpy as np

        with self._prog_and_scope_guard():
            exe = Executor(self.place, donate_states=False)
            feeder = self._feeder(feed_order)
            test_prog = self.train_program.clone(for_test=True)
            accumulated = [0.0] * len(self.train_func_outputs)
            count = 0
            for data in reader():
                outs = exe.run(program=test_prog, feed=feeder.feed(data),
                               fetch_list=self.train_func_outputs)
                for i, v in enumerate(outs):
                    accumulated[i] += float(np.ravel(np.asarray(v))[0])
                count += 1
            return [a / max(1, count) for a in accumulated]

    def _feeder(self, feed_order):
        if feed_order is None:
            raise ValueError("feed_order is required (list of data names)")
        feed_list = [
            self.train_program.global_block().var(n) for n in feed_order
        ]
        return DataFeeder(feed_list=feed_list, place=self.place)

    # -- persistence ----------------------------------------------------
    def save_params(self, param_path: str):
        with self._prog_and_scope_guard():
            exe = Executor(self.place)
            fluid_io.save_persistables(exe, param_path,
                                       main_program=self.train_program)

    def save_inference_model(self, param_path: str,
                             feeded_var_names: Sequence[str],
                             target_var_indexes: Sequence[int]):
        with self._prog_and_scope_guard():
            exe = Executor(self.place)
            fluid_io.save_inference_model(
                param_path, list(feeded_var_names),
                [self.train_func_outputs[i] for i in target_var_indexes],
                exe, main_program=self.train_program,
            )

    def _serial_dir(self, serial: int) -> str:
        return os.path.join(self.checkpoint_cfg.checkpoint_dir, str(serial))

    def _save_checkpoint(self, epoch_id: int, step_id: int):
        cfg = self.checkpoint_cfg
        os.makedirs(cfg.checkpoint_dir, exist_ok=True)
        serial = self._latest_serial() + 1
        d = self._serial_dir(serial)
        exe = Executor(self.place)
        fluid_io.save_persistables(exe, d, main_program=self.train_program)
        with open(os.path.join(d, "trainer_args.json"), "w") as f:
            import json

            json.dump({"epoch_id": epoch_id, "step_id": step_id}, f)
        with open(os.path.join(d, "_SUCCESS"), "w"):
            pass
        self._scroll_delete()

    def _latest_serial(self) -> int:
        cfg = self.checkpoint_cfg
        best = -1
        if os.path.isdir(cfg.checkpoint_dir):
            for name in os.listdir(cfg.checkpoint_dir):
                if name.isdigit() and os.path.exists(
                        os.path.join(cfg.checkpoint_dir, name, "_SUCCESS")):
                    best = max(best, int(name))
        return best

    def _scroll_delete(self):
        cfg = self.checkpoint_cfg
        serials = sorted(
            int(n) for n in os.listdir(cfg.checkpoint_dir) if n.isdigit()
        )
        for s in serials[:-cfg.max_num_checkpoints]:
            shutil.rmtree(self._serial_dir(s), ignore_errors=True)

    def _load_checkpoint(self):
        import json

        serial = self._latest_serial()
        if serial < 0:
            return
        d = self._serial_dir(serial)
        exe = Executor(self.place)
        fluid_io.load_persistables(exe, d, main_program=self.train_program)
        args_path = os.path.join(d, "trainer_args.json")
        if os.path.exists(args_path):
            with open(args_path) as f:
                args = json.load(f)
            self.checkpoint_cfg.epoch_id = int(args["epoch_id"])
            self.checkpoint_cfg.step_id = int(args["step_id"])


