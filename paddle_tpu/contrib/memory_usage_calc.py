"""Estimate training memory usage
(reference: python/paddle/fluid/contrib/memory_usage_calc.py)."""

from __future__ import annotations

from ..core.framework import Program, default_main_program
from ..core.proto import DataType

__all__ = ["memory_usage"]

_DTYPE_BYTES = {
    DataType.FP64: 8, DataType.FP32: 4, DataType.FP16: 2, DataType.BF16: 2,
    DataType.INT64: 8, DataType.INT32: 4, DataType.INT16: 2,
    DataType.BOOL: 1, DataType.UINT8: 1, DataType.INT8: 1,
}


def memory_usage(program: Program = None, batch_size: int = 1):
    """Rough lower bound: sum of var sizes with -1 dims filled by
    batch_size.  Returns (min_bytes, max_bytes) like the reference's
    heuristic band."""
    program = program or default_main_program()
    total = 0
    for block_idx in range(program.desc.num_blocks()):
        for vd in program.desc.block(block_idx).vars.values():
            numel = 1
            for d in vd.shape:
                numel *= batch_size if d < 0 else max(int(d), 1)
            total += numel * _DTYPE_BYTES.get(DataType(vd.dtype), 4)
    return total, int(total * 1.5)
