"""Program debugging/printing utilities
(reference: python/paddle/fluid/debugger.py — draw_block_graphviz,
repr_program in text form)."""

from __future__ import annotations

from .core.framework import Program

__all__ = ["pprint_program_codes", "pprint_block_codes", "draw_block_graphviz"]


def pprint_block_codes(block_desc, show_backward=False) -> str:
    """Text rendering of one block's ops and vars
    (reference: debugger.py pprint_block_codes)."""
    lines = [f"block {block_desc.idx} (parent {block_desc.parent_idx}):"]
    for name, vd in sorted(block_desc.vars.items()):
        if not show_backward and "@GRAD" in name:
            continue
        lines.append(
            f"  var {name}: shape={list(vd.shape)} dtype={vd.dtype!s} "
            f"persistable={vd.persistable}"
        )
    for op in block_desc.ops:
        if not show_backward and op.type.endswith("_grad"):
            continue
        ins = ", ".join(
            f"{k}={v}" for k, v in sorted(op.inputs.items()) if v
        )
        outs = ", ".join(
            f"{k}={v}" for k, v in sorted(op.outputs.items()) if v
        )
        lines.append(f"  {op.type}({ins}) -> {outs}")
    return "\n".join(lines)


def pprint_program_codes(program: Program, show_backward=False) -> str:
    return "\n".join(
        pprint_block_codes(program.desc.block(i), show_backward)
        for i in range(program.desc.num_blocks())
    )


def draw_block_graphviz(block, highlights=None, path="./temp.dot") -> str:
    """Emit a graphviz dot file of the op/var graph
    (reference: debugger.py draw_block_graphviz)."""
    highlights = set(highlights or [])
    lines = ["digraph G {", "  rankdir=LR;"]
    desc = getattr(block, "desc", block)
    for i, op in enumerate(desc.ops):
        color = ' style=filled fillcolor="lightblue"' if op.type in highlights else ""
        lines.append(f'  op{i} [label="{op.type}" shape=box{color}];')
        for n in op.input_arg_names():
            lines.append(f'  "{n}" -> op{i};')
        for n in op.output_arg_names():
            lines.append(f'  op{i} -> "{n}";')
    lines.append("}")
    dot = "\n".join(lines)
    with open(path, "w") as f:
        f.write(dot)
    return dot
