"""Image preprocessing utilities
(reference: python/paddle/dataset/image.py — cv2-backed load / resize /
crop / flip / transform helpers feeding the vision configs).

Pure-numpy implementations (bilinear resize, HWC<->CHW, crops, flips) so
no cv2 dependency; images are float32/uint8 HWC arrays.  cv2, when
installed, is used only for decoding compressed files in load_image.
"""

from __future__ import annotations

import os
import tarfile
from typing import Sequence, Tuple

import numpy as np

__all__ = [
    "load_image", "load_image_bytes", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
]


def _resize_bilinear(im: np.ndarray, h: int, w: int) -> np.ndarray:
    """[H,W,C] or [H,W] bilinear resize, numpy only."""
    in_h, in_w = im.shape[:2]
    if (in_h, in_w) == (h, w):
        return im
    ys = (np.arange(h) + 0.5) * in_h / h - 0.5
    xs = (np.arange(w) + 0.5) * in_w / w - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, in_h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, in_w - 1)
    y1 = np.clip(y0 + 1, 0, in_h - 1)
    x1 = np.clip(x0 + 1, 0, in_w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)
    wx = np.clip(xs - x0, 0.0, 1.0)
    im_f = im.astype(np.float32)
    top = (im_f[y0][:, x0] * (1 - wx)[None, :, None]
           + im_f[y0][:, x1] * wx[None, :, None]) \
        if im.ndim == 3 else (im_f[y0][:, x0] * (1 - wx)
                              + im_f[y0][:, x1] * wx)
    bot = (im_f[y1][:, x0] * (1 - wx)[None, :, None]
           + im_f[y1][:, x1] * wx[None, :, None]) \
        if im.ndim == 3 else (im_f[y1][:, x0] * (1 - wx)
                              + im_f[y1][:, x1] * wx)
    wy_b = wy[:, None, None] if im.ndim == 3 else wy[:, None]
    out = top * (1 - wy_b) + bot * wy_b
    if im.dtype == np.uint8:
        out = np.clip(np.rint(out), 0, 255).astype(np.uint8)
    return out


def load_image_bytes(data: bytes, is_color: bool = True) -> np.ndarray:
    """Decode an encoded image from bytes (needs cv2); .npy bytes decode
    without it."""
    if data[:6] == b"\x93NUMPY":
        import io

        im = np.load(io.BytesIO(data), allow_pickle=False)
    else:
        try:
            import cv2  # gated: not in the base environment
        except ImportError as e:
            raise ImportError(
                "decoding compressed images needs cv2; store .npy arrays "
                "or install opencv"
            ) from e
        flag = cv2.IMREAD_COLOR if is_color else cv2.IMREAD_GRAYSCALE
        im = cv2.imdecode(np.frombuffer(data, dtype="uint8"), flag)
    return _color_convert(im, is_color)


def _color_convert(im: np.ndarray, is_color: bool) -> np.ndarray:
    if is_color and im.ndim == 2:
        im = np.repeat(im[:, :, None], 3, axis=2)
    if not is_color and im.ndim == 3:
        im = im.mean(axis=2).astype(im.dtype)
    return im


def load_image(file: str, is_color: bool = True) -> np.ndarray:
    """Load an image file as HWC (color) or HW (gray).  .npy loads
    directly; compressed formats go through cv2 when available."""
    if file.endswith(".npy"):
        return _color_convert(np.load(file), is_color)
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Resize so the shorter edge equals `size`, keeping aspect ratio."""
    h, w = im.shape[:2]
    if h < w:
        return _resize_bilinear(im, size, max(1, int(round(w * size / h))))
    return _resize_bilinear(im, max(1, int(round(h * size / w))), size)


def to_chw(im: np.ndarray, order: Sequence[int] = (2, 0, 1)) -> np.ndarray:
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int, is_color: bool = True):
    h, w = im.shape[:2]
    h0, w0 = (h - size) // 2, (w - size) // 2
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True):
    h, w = im.shape[:2]
    h0 = np.random.randint(0, h - size + 1)
    w0 = np.random.randint(0, w - size + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im: np.ndarray, is_color: bool = True) -> np.ndarray:
    return im[:, ::-1, :] if (is_color and im.ndim == 3) else im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True,
                     mean=None) -> np.ndarray:
    """resize_short -> (random|center) crop -> (train) random flip ->
    CHW float32 -> mean subtract (reference: image.py simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True,
                       mean=None) -> np.ndarray:
    return simple_transform(
        load_image(filename, is_color), resize_size, crop_size, is_train,
        is_color, mean,
    )


def batch_images_from_tar(data_file: str, dataset_name: str,
                          img2label: dict, num_per_batch: int = 1024) -> str:
    """Pre-batch a tar of images into pickled (data, label) batches
    (reference: image.py batch_images_from_tar); returns the batch-list
    file path."""
    import pickle

    out_path = data_file + "_batch"
    meta_file = os.path.join(out_path, "batch_names.txt")
    if os.path.exists(meta_file):
        return meta_file
    os.makedirs(out_path, exist_ok=True)
    data, labels, names = [], [], []
    n = 0
    with tarfile.open(data_file) as tf:
        for member in tf.getmembers():
            if member.name not in img2label:
                continue
            f = tf.extractfile(member)
            data.append(f.read())
            labels.append(img2label[member.name])
            if len(data) == num_per_batch:
                name = os.path.join(out_path, f"batch_{n}")
                with open(name, "wb") as out:
                    pickle.dump({"data": data, "label": labels}, out,
                                protocol=2)
                names.append(name)
                data, labels = [], []
                n += 1
    if data:
        name = os.path.join(out_path, f"batch_{n}")
        with open(name, "wb") as out:
            pickle.dump({"data": data, "label": labels}, out, protocol=2)
        names.append(name)
    with open(meta_file, "w") as f:
        f.write("\n".join(names) + "\n")
    return meta_file
