"""PTB language-model n-grams (reference: python/paddle/dataset/imikolov.py).

Samples: n-gram tuples of word ids (default n=5 windows), or sequence pairs
in NGRAM/SEQ data types.
"""

from __future__ import annotations

from . import common

__all__ = ["train", "test", "build_dict"]

VOCAB = 2074  # reference PTB dict ~2073 + <unk>
TRAIN_SIZE = 4096
TEST_SIZE = 512


def build_dict(min_word_freq=50):
    d = {f"w{i}": i for i in range(VOCAB - 1)}
    d["<unk>"] = VOCAB - 1
    return d


def _synthetic(split, size, n):
    def reader():
        rng = common.synthetic_rng("imikolov", split)
        for _ in range(size):
            # markov-ish: neighboring ids correlate
            base = int(rng.randint(0, VOCAB - n))
            gram = [
                (base + int(rng.randint(0, 5))) % VOCAB for _ in range(n)
            ]
            yield tuple(gram)

    return reader


def train(word_idx=None, n=5, data_type=None):
    return _synthetic("train", TRAIN_SIZE, n)


def test(word_idx=None, n=5, data_type=None):
    return _synthetic("test", TEST_SIZE, n)
