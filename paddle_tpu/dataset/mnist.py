"""MNIST dataset (reference: python/paddle/dataset/mnist.py).

Samples: (image float32[784] scaled to [-1, 1], label int64 in [0, 10)).
Uses real IDX files from the cache dir when present; otherwise a
deterministic synthetic set with the same schema (see common.py).
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import common

__all__ = ["train", "test"]

TRAIN_SIZE = 8192  # synthetic split sizes (real: 60000/10000)
TEST_SIZE = 2048


def _real_files(split):
    prefix = "train" if split == "train" else "t10k"
    img = os.path.join(common.DATA_HOME, "mnist", f"{prefix}-images-idx3-ubyte.gz")
    lab = os.path.join(common.DATA_HOME, "mnist", f"{prefix}-labels-idx1-ubyte.gz")
    if os.path.exists(img) and os.path.exists(lab):
        return img, lab
    return None


def _reader_from_idx(img_path, lab_path):
    def reader():
        with gzip.open(img_path, "rb") as fi, gzip.open(lab_path, "rb") as fl:
            fi.read(4)
            n, rows, cols = struct.unpack(">III", fi.read(12))
            fl.read(8)
            for _ in range(n):
                img = np.frombuffer(fi.read(rows * cols), dtype=np.uint8)
                img = img.astype(np.float32) / 255.0 * 2.0 - 1.0
                lab = struct.unpack("B", fl.read(1))[0]
                yield img, int(lab)

    return reader


def _synthetic_reader(split, size):
    def reader():
        rng = common.synthetic_rng("mnist", split)
        for _ in range(size):
            label = int(rng.randint(0, 10))
            # class-dependent mean so models can actually learn
            img = rng.normal(
                loc=(label - 4.5) / 10.0, scale=0.5, size=(784,)
            ).astype(np.float32)
            yield np.clip(img, -1.0, 1.0), label

    return reader


def train():
    files = _real_files("train")
    if files:
        return _reader_from_idx(*files)
    return _synthetic_reader("train", TRAIN_SIZE)


def test():
    files = _real_files("test")
    if files:
        return _reader_from_idx(*files)
    return _synthetic_reader("test", TEST_SIZE)
