"""Pascal VOC2012 segmentation set
(reference: python/paddle/dataset/voc2012.py — train/test/val readers over
the VOCtrainval tarball, yielding (image, segmentation label) pairs).

Zero-egress: yields a deterministic synthetic corpus with the real schema —
RGB image float32 [3, H, W] and label int32 [H, W] with the 21 VOC classes
(0 = background, 255 = void border) — unless real data is present under
PADDLE_TPU_DATA_HOME (see dataset/common.py).
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

N_CLASSES = 21
VOID = 255
TRAIN_SIZE = 128
TEST_SIZE = 32
VAL_SIZE = 32


def _synthetic(split, size):
    def reader():
        rng = common.synthetic_rng("voc2012", split)
        for _ in range(size):
            h = int(rng.choice([96, 128, 160]))
            w = int(rng.choice([96, 128, 160]))
            label = np.zeros((h, w), dtype=np.int32)
            img = rng.rand(3, h, w).astype(np.float32) * 0.1
            # a few rectangular "objects", each a class with a void border
            for _ in range(int(rng.randint(1, 4))):
                cls = int(rng.randint(1, N_CLASSES))
                y0, x0 = int(rng.randint(h // 2)), int(rng.randint(w // 2))
                y1 = y0 + int(rng.randint(8, h - y0))
                x1 = x0 + int(rng.randint(8, w - x0))
                label[y0:y1, x0:x1] = cls
                if y1 - y0 > 4 and x1 - x0 > 4:
                    label[y0, x0:x1] = VOID
                    label[y1 - 1, x0:x1] = VOID
                img[:, y0:y1, x0:x1] += (
                    rng.rand(3, 1, 1).astype(np.float32) * 0.8
                )
            yield img, label

    return reader


def train():
    """reader: (image float32 [3,H,W], label int32 [H,W])."""
    return _synthetic("train", TRAIN_SIZE)


def test():
    return _synthetic("test", TEST_SIZE)


def val():
    return _synthetic("val", VAL_SIZE)
