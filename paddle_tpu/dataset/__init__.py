"""Built-in datasets (reference: python/paddle/dataset/).

All modules fall back to deterministic synthetic corpora with the real
schema when the cache has no real data — see common.py.  Inventory parity:
mnist, cifar, uci_housing, imdb, imikolov, wmt16 (+ movielens, conll05,
wmt14, flowers as synthetic schemas).
"""

from . import (  # noqa: F401
    cifar,
    common,
    conll05,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    uci_housing,
    wmt14,
    wmt16,
)

__all__ = [
    "mnist", "cifar", "uci_housing", "imdb", "imikolov", "wmt14", "wmt16",
    "movielens", "conll05", "flowers", "common",
]
