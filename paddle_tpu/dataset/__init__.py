"""Built-in datasets (reference: python/paddle/dataset/).

All modules fall back to deterministic synthetic corpora with the real
schema when the cache has no real data — see common.py.  Inventory parity
with the reference package: mnist, cifar, flowers, imdb, imikolov,
movielens, mq2007, sentiment, uci_housing, voc2012, wmt14, wmt16, conll05,
plus the image preprocessing helpers.
"""

from . import (  # noqa: F401
    cifar,
    common,
    conll05,
    flowers,
    image,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)

__all__ = [
    "mnist", "cifar", "uci_housing", "imdb", "imikolov", "wmt14", "wmt16",
    "movielens", "conll05", "flowers", "mq2007", "sentiment", "voc2012",
    "image", "common",
]
