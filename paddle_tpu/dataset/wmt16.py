"""WMT16 en-de translation pairs (reference: python/paddle/dataset/wmt16.py).

Samples: (src ids, trg ids with <s>, trg ids with <e>) — the transformer
training triple.  Ids 0/1/2 are <s>/<e>/<unk> as in the reference.
"""

from __future__ import annotations

from . import common

__all__ = ["train", "test", "validation", "get_dict"]

START_ID, END_ID, UNK_ID = 0, 1, 2
TRAIN_SIZE = 2048
TEST_SIZE = 256


def get_dict(lang, dict_size, reverse=False):
    d = {"<s>": 0, "<e>": 1, "<unk>": 2}
    for i in range(3, dict_size):
        d[f"{lang}{i}"] = i
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _synthetic(split, size, src_dict_size, trg_dict_size):
    def reader():
        rng = common.synthetic_rng("wmt16", split)
        for _ in range(size):
            n = int(rng.randint(4, 50))
            src = [int(x) for x in rng.randint(3, src_dict_size, size=n)]
            # target "translates" each source id deterministically
            trg = [3 + (i * 7 + 11) % (trg_dict_size - 3) for i in src]
            yield src, [START_ID] + trg, trg + [END_ID]

    return reader


def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    return _synthetic("train", TRAIN_SIZE, src_dict_size, trg_dict_size)


def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    return _synthetic("test", TEST_SIZE, src_dict_size, trg_dict_size)


def validation(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    return _synthetic("val", TEST_SIZE, src_dict_size, trg_dict_size)
