"""Dataset plumbing (reference: python/paddle/dataset/common.py).

The reference auto-downloads into ~/.cache/paddle/dataset.  This
environment has no network egress, so every dataset module here generates a
*deterministic synthetic* corpus with the real schema (shapes, dtypes, vocab
sizes, label ranges) unless real files are already present in the cache dir.
Set PADDLE_TPU_DATA_HOME to point at pre-downloaded real data.
"""

from __future__ import annotations

import hashlib
import os

import numpy as np

__all__ = ["DATA_HOME", "md5file", "data_path", "synthetic_rng"]

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.expanduser("~/.cache/paddle_tpu/dataset"),
)


def md5file(fname: str) -> str:
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def data_path(module_name: str, *parts: str) -> str:
    p = os.path.join(DATA_HOME, module_name, *parts)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    return p


def synthetic_rng(name: str, split: str) -> np.random.RandomState:
    """Deterministic per-(dataset, split) generator so train/test are stable
    across runs and processes."""
    seed = int(
        hashlib.md5(f"{name}:{split}".encode()).hexdigest()[:8], 16
    )
    return np.random.RandomState(seed)
