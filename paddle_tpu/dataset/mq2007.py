"""MQ2007 learning-to-rank set (LETOR 4.0)
(reference: python/paddle/dataset/mq2007.py — parses the LETOR text format
into per-query lists and yields them in pointwise / pairwise / listwise
form).

The parser and Query/QueryList structures mirror the reference contract;
zero-egress, the corpus itself is a deterministic synthetic LETOR file
written into the cache dir (so the real text parser is exercised), with
46 features and {0,1,2} relevance like the original.
"""

from __future__ import annotations

import os
from functools import total_ordering
from typing import List, Optional

import numpy as np

from . import common

__all__ = ["train", "test", "Query", "QueryList",
           "gen_point", "gen_pair", "gen_list", "gen_plain_txt"]

FEATURE_DIM = 46
N_QUERIES_TRAIN = 120
N_QUERIES_TEST = 40


@total_ordering
class Query:
    """One judged document: relevance, query id, 46 features
    (reference: mq2007.py Query — parses 'rel qid:N 1:f ... #docid = D')."""

    def __init__(self, query_id: int = -1, relevance_score: int = -1,
                 feature_vector: Optional[List[float]] = None,
                 description: str = ""):
        self.query_id = query_id
        self.relevance_score = relevance_score
        self.feature_vector = feature_vector or []
        self.description = description

    def __str__(self):
        feats = " ".join(
            f"{i + 1}:{f}" for i, f in enumerate(self.feature_vector)
        )
        return f"{self.relevance_score} qid:{self.query_id} {feats}"

    __repr__ = __str__

    def __eq__(self, other):
        return self.relevance_score == other.relevance_score

    def __lt__(self, other):
        return self.relevance_score < other.relevance_score

    @classmethod
    def _parse_one_line(cls, line: str, fill_missing: float = -1.0):
        comment = ""
        if "#" in line:
            line, comment = line.split("#", 1)
        toks = line.split()
        rel = int(toks[0])
        qid = int(toks[1].split(":")[1])
        feats = [fill_missing] * FEATURE_DIM
        for t in toks[2:]:
            idx, val = t.split(":")
            feats[int(idx) - 1] = float(val)
        return cls(qid, rel, feats, comment.strip())


class QueryList:
    """All judged documents of one query (reference: mq2007.py QueryList)."""

    def __init__(self, querylist: Optional[List[Query]] = None):
        self.querylist = querylist or []
        self.query_id = self.querylist[0].query_id if self.querylist else -1

    def __iter__(self):
        return iter(self.querylist)

    def __len__(self):
        return len(self.querylist)

    def __getitem__(self, i):
        return self.querylist[i]

    def _correct_ranking_(self):
        self.querylist.sort(key=lambda q: q.relevance_score, reverse=True)

    def _add_query(self, query: Query):
        if self.query_id == -1:
            self.query_id = query.query_id
        elif query.query_id != self.query_id:
            raise ValueError(
                f"query id mismatch: {query.query_id} vs {self.query_id}"
            )
        self.querylist.append(query)


# -- generators over one QueryList (reference API) ----------------------
def gen_plain_txt(querylist):
    """yield (query_id, relevance, feature_vector) per doc."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    for q in querylist:
        yield querylist.query_id, q.relevance_score, np.array(
            q.feature_vector)


def gen_point(querylist):
    """pointwise: yield (relevance, feature_vector) per doc."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    for q in querylist:
        yield q.relevance_score, np.array(q.feature_vector)


def gen_pair(querylist, partial_order="full"):
    """pairwise: yield (label=1, better_doc, worse_doc) for each ordered
    pair with distinct relevance (reference emits label 1 with the higher
    doc first)."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    qs = sorted(querylist, key=lambda q: q.relevance_score, reverse=True)
    for i, hi in enumerate(qs):
        for lo in qs[i + 1:]:
            if hi.relevance_score > lo.relevance_score:
                yield (np.array([1.0]), np.array(hi.feature_vector),
                       np.array(lo.feature_vector))
                if partial_order != "full":
                    break  # one pair per doc — but only once one exists


def gen_list(querylist):
    """listwise: yield (relevance array, feature matrix) per query."""
    if not isinstance(querylist, QueryList):
        querylist = QueryList(querylist)
    rels = np.array([q.relevance_score for q in querylist])
    feats = np.array([q.feature_vector for q in querylist])
    yield rels, feats


def query_filter(querylists):
    """Drop queries where every judgment is identical — no ranking signal
    (reference: mq2007.py query_filter)."""
    out = []
    for ql in querylists:
        rels = {q.relevance_score for q in ql}
        if len(rels) > 1:
            out.append(ql)
    return out


def load_from_text(filepath, shuffle=False, fill_missing=-1.0):
    """Parse a LETOR text file into QueryLists; shuffle=True randomizes
    the query order (reference: mq2007.py load_from_text)."""
    by_qid = {}
    order = []
    with open(filepath) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            q = Query._parse_one_line(line, fill_missing)
            if q.query_id not in by_qid:
                by_qid[q.query_id] = QueryList()
                order.append(q.query_id)
            by_qid[q.query_id]._add_query(q)
    out = [by_qid[qid] for qid in order]
    if shuffle:
        common.synthetic_rng("mq2007", "shuffle").shuffle(out)
    return out


def _synthesize(split: str, n_queries: int) -> str:
    """Write a deterministic LETOR-format file into the cache dir; the
    relevance is a noisy linear function of the features so rankers can
    learn."""
    path = common.data_path("mq2007", f"{split}.txt")
    if not os.path.exists(path):
        rng = common.synthetic_rng("mq2007", split)
        w = np.linspace(-1, 1, FEATURE_DIM)
        with open(path, "w") as f:
            for qid in range(1, n_queries + 1):
                n_docs = int(rng.randint(5, 20))
                for d in range(n_docs):
                    x = rng.rand(FEATURE_DIM)
                    score = float(x @ w) + rng.randn() * 0.1
                    rel = int(np.clip(np.floor((score + 1.5) / 1.0), 0, 2))
                    feats = " ".join(
                        f"{i + 1}:{x[i]:.6f}" for i in range(FEATURE_DIM)
                    )
                    f.write(
                        f"{rel} qid:{qid} {feats} #docid = "
                        f"GX-{qid:03d}-{d:02d}\n"
                    )
    return path


def __reader__(filepath, format="pairwise", shuffle=False, fill_missing=-1.0):
    querylists = query_filter(
        load_from_text(filepath, shuffle=shuffle, fill_missing=fill_missing)
    )
    for ql in querylists:
        if format == "plain_txt":
            yield from gen_plain_txt(ql)
        elif format == "pointwise":
            yield from gen_point(ql)
        elif format == "pairwise":
            yield from gen_pair(ql)
        elif format == "listwise":
            yield from gen_list(ql)
        else:
            raise ValueError(f"unknown format {format!r}")


def train(format="pairwise", shuffle=False, fill_missing=-1.0):
    path = _synthesize("train", N_QUERIES_TRAIN)
    return lambda: __reader__(path, format, shuffle, fill_missing)


def test(format="pairwise", shuffle=False, fill_missing=-1.0):
    path = _synthesize("test", N_QUERIES_TEST)
    return lambda: __reader__(path, format, shuffle, fill_missing)
