"""UCI housing regression set (reference: python/paddle/dataset/uci_housing.py).

Samples: (features float32[13] normalized, price float32[1]).
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "feature_names"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

TRAIN_SIZE = 404
TEST_SIZE = 102

# fixed ground-truth linear model for the synthetic corpus
_W = np.linspace(-1.0, 1.0, 13).astype(np.float32)


def _synthetic(split, size):
    def reader():
        rng = common.synthetic_rng("uci_housing", split)
        for _ in range(size):
            x = rng.randn(13).astype(np.float32)
            y = float(x @ _W + 22.5 + rng.randn() * 0.5)
            yield x, np.array([y], dtype=np.float32)

    return reader


def train():
    return _synthetic("train", TRAIN_SIZE)


def test():
    return _synthetic("test", TEST_SIZE)
