"""IMDB sentiment (reference: python/paddle/dataset/imdb.py).

Samples: (word-id sequence, label in {0, 1}).  word_dict maps token->id.
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "word_dict"]

VOCAB_SIZE = 5149  # reference IMDB vocab ends up ~5147 + <unk>
TRAIN_SIZE = 2048
TEST_SIZE = 512


def word_dict():
    """token -> id; synthetic tokens w0..wN like the reference's dict shape."""
    d = {f"w{i}": i for i in range(VOCAB_SIZE - 1)}
    d["<unk>"] = VOCAB_SIZE - 1
    return d


def _synthetic(split, size):
    def reader():
        rng = common.synthetic_rng("imdb", split)
        for _ in range(size):
            label = int(rng.randint(0, 2))
            n = int(rng.randint(8, 64))
            # positive reviews skew toward low word-ids
            if label:
                ids = rng.zipf(1.3, size=n) % (VOCAB_SIZE // 2)
            else:
                ids = VOCAB_SIZE // 2 + rng.zipf(1.3, size=n) % (VOCAB_SIZE // 2)
            yield [int(i) for i in ids], label

    return reader


def train(word_idx=None):
    return _synthetic("train", TRAIN_SIZE)


def test(word_idx=None):
    return _synthetic("test", TEST_SIZE)
