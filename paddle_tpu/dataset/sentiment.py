"""Movie-review sentiment corpus
(reference: python/paddle/dataset/sentiment.py over NLTK movie_reviews:
get_word_dict() builds a frequency-sorted vocab, train/test yield
(word-id list, 0/1 polarity)).

Zero-egress: a deterministic synthetic corpus with the real schema — a
frequency-ranked word dict and variable-length id sequences whose word
distribution differs by polarity (so models can actually learn).
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "get_word_dict"]

VOCAB_SIZE = 5147  # reference vocab is movie_reviews-derived; fixed here
NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000

_word_dict = None


def get_word_dict():
    """word -> id, ids assigned by descending corpus frequency
    (reference: sentiment.py get_word_dict)."""
    global _word_dict
    if _word_dict is None:
        _word_dict = {f"w{i:05d}": i for i in range(VOCAB_SIZE)}
    return _word_dict


def _synthetic(split, size):
    def reader():
        rng = common.synthetic_rng("sentiment", split)
        # Zipf-ish draw; polarity shifts the head of the distribution
        base = 1.0 / (np.arange(1, VOCAB_SIZE + 1) ** 1.1)
        for _ in range(size):
            label = int(rng.randint(2))
            p = base.copy()
            # positive docs over-sample one band of words, negative another
            band = slice(100, 400) if label else slice(400, 700)
            p[band] *= 8.0
            p /= p.sum()
            n = int(rng.randint(20, 200))
            words = rng.choice(VOCAB_SIZE, size=n, p=p).astype(np.int64)
            yield list(map(int, words)), label

    return reader


def train():
    """reader: (word-id list, label in {0,1})."""
    return _synthetic("train", NUM_TRAINING_INSTANCES)


def test():
    return _synthetic("test", NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES)
