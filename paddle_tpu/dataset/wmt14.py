"""WMT14 fr-en pairs (reference: python/paddle/dataset/wmt14.py).

Same triple schema as wmt16: (src ids, trg in, trg out)."""

from __future__ import annotations

from . import wmt16

__all__ = ["train", "test"]


def train(dict_size=30000):
    return wmt16.train(dict_size, dict_size)


def test(dict_size=30000):
    return wmt16.test(dict_size, dict_size)
