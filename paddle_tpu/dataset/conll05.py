"""CoNLL-2005 semantic role labeling (reference: python/paddle/dataset/conll05.py).

Samples: 8 aligned token-id sequences + BIO label-id sequence, the SRL
DB-LSTM training tuple (word, ctx_n2..ctx_p2, verb, mark, label).
"""

from __future__ import annotations

from . import common

__all__ = ["get_dict", "test", "train"]

WORD_VOCAB = 44068
LABEL_VOCAB = 3857
PRED_VOCAB = 3162
TRAIN_SIZE = 1024
TEST_SIZE = 256


def get_dict():
    word_dict = {f"w{i}": i for i in range(WORD_VOCAB)}
    verb_dict = {f"v{i}": i for i in range(PRED_VOCAB)}
    label_dict = {f"l{i}": i for i in range(LABEL_VOCAB)}
    return word_dict, verb_dict, label_dict


def _synthetic(split, size):
    def reader():
        rng = common.synthetic_rng("conll05", split)
        for _ in range(size):
            n = int(rng.randint(5, 40))
            word = [int(x) for x in rng.randint(0, WORD_VOCAB, size=n)]
            ctx = [
                [int(x) for x in rng.randint(0, WORD_VOCAB, size=n)]
                for _ in range(5)
            ]
            verb = [int(rng.randint(0, PRED_VOCAB))] * n
            mark = [int(x) for x in rng.randint(0, 2, size=n)]
            label = [int(x) for x in rng.randint(0, LABEL_VOCAB, size=n)]
            yield (word, ctx[0], ctx[1], ctx[2], ctx[3], ctx[4], verb, mark,
                   label)

    return reader


def train():
    return _synthetic("train", TRAIN_SIZE)


def test():
    return _synthetic("test", TEST_SIZE)
