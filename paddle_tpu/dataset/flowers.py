"""Oxford 102 flowers (reference: python/paddle/dataset/flowers.py).

Samples: (image float32[3*224*224], label int in [0, 102))."""

from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "valid"]

TRAIN_SIZE = 256
TEST_SIZE = 64


def _synthetic(split, size):
    def reader():
        rng = common.synthetic_rng("flowers", split)
        for _ in range(size):
            label = int(rng.randint(0, 102))
            img = rng.rand(3 * 224 * 224).astype(np.float32)
            yield img, label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return _synthetic("train", TRAIN_SIZE)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return _synthetic("test", TEST_SIZE)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _synthetic("valid", TEST_SIZE)
