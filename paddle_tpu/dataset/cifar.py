"""CIFAR-10/100 (reference: python/paddle/dataset/cifar.py).

Samples: (image float32[3072] in [0, 1], label int).  Real pickled batches
used when cached; synthetic otherwise.
"""

from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100"]

TRAIN_SIZE = 4096
TEST_SIZE = 1024


def _synthetic(split, size, num_classes):
    def reader():
        rng = common.synthetic_rng(f"cifar{num_classes}", split)
        for _ in range(size):
            label = int(rng.randint(0, num_classes))
            img = rng.rand(3072).astype(np.float32)
            # tint a class-dependent channel so learning is possible
            img[label % 3 :: 3] = np.clip(
                img[label % 3 :: 3] + (label % 7) / 10.0, 0, 1
            )
            yield img, label

    return reader


def train10():
    return _synthetic("train", TRAIN_SIZE, 10)


def test10():
    return _synthetic("test", TEST_SIZE, 10)


def train100():
    return _synthetic("train", TRAIN_SIZE, 100)


def test100():
    return _synthetic("test", TEST_SIZE, 100)
