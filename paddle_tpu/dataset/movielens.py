"""MovieLens-1M recommender data (reference: python/paddle/dataset/movielens.py).

Samples: (user_id, gender_id, age_id, job_id, movie_id, category_ids,
title_ids, rating float).
"""

from __future__ import annotations

from . import common

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table"]

_MAX_USER = 6040
_MAX_MOVIE = 3952
_MAX_JOB = 20
age_table = [1, 18, 25, 35, 45, 50, 56]

TRAIN_SIZE = 4096
TEST_SIZE = 512


def max_user_id():
    return _MAX_USER


def max_movie_id():
    return _MAX_MOVIE


def max_job_id():
    return _MAX_JOB


def _synthetic(split, size):
    def reader():
        rng = common.synthetic_rng("movielens", split)
        for _ in range(size):
            uid = int(rng.randint(1, _MAX_USER + 1))
            mid = int(rng.randint(1, _MAX_MOVIE + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(age_table)))
            job = int(rng.randint(0, _MAX_JOB + 1))
            cats = [int(x) for x in rng.randint(0, 18, size=rng.randint(1, 4))]
            title = [int(x) for x in rng.randint(0, 5000, size=rng.randint(1, 6))]
            rating = float((uid * 7 + mid * 13) % 5 + 1)
            yield uid, gender, age, job, mid, cats, title, rating

    return reader


def train():
    return _synthetic("train", TRAIN_SIZE)


def test():
    return _synthetic("test", TEST_SIZE)
