"""Checkpoint / model IO (reference: python/paddle/fluid/io.py).

The reference saves by appending `save`/`load` ops (operators/save_op.cc)
and running them through an executor; variables serialize as LoDTensor blobs
with a version header.  TPU-native equivalent: checkpointing is a *host*
concern — values are pulled from the Scope (device->host), written as numpy
blobs, and restored by name.  The public API mirrors io.py:89-704:
save/load_vars, save/load_params, save/load_persistables,
save/load_inference_model.

Layout on disk (dirname/):
    <var_name>            one numpy .npy blob per var (save_vars default)
    <filename>            single .npz when filename= given (save_combine)
    __model__             program desc JSON (save_inference_model)
    __lod__/<var_name>    sequence lengths sidecar for LoDValues
"""

from __future__ import annotations

import json
import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from .core.framework import Program, Variable, default_main_program
from .core.lod import LoDValue
from .core.proto import VarType
from .core.scope import global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables",
    "load_vars", "load_params", "load_persistables",
    "save_inference_model", "load_inference_model",
    "get_inference_program",
    "save_sharded", "load_sharded", "AsyncCheckpoint",
]


def is_persistable(var: Variable) -> bool:
    """reference: io.py is_persistable — skips reader/raw vars."""
    if var.desc.type in (VarType.RAW, VarType.READER, VarType.LOD_TENSOR_ARRAY):
        return False
    return bool(var.persistable)


def is_parameter(var: Variable) -> bool:
    from .core.framework import Parameter

    return isinstance(var, Parameter)


def _var_value(scope, name: str):
    v = scope.find_var(name)
    if v is None:
        raise ValueError(f"variable '{name}' has no value in scope")
    return v


def _to_host(value):
    if isinstance(value, LoDValue):
        return np.asarray(value.data), np.asarray(value.lengths)
    return np.asarray(value), None


def save_vars(
    executor,
    dirname: str,
    main_program: Optional[Program] = None,
    vars: Optional[Sequence] = None,
    predicate: Optional[Callable] = None,
    filename: Optional[str] = None,
) -> None:
    """Save selected vars from the executor's scope (reference: io.py:89)."""
    main_program = main_program or default_main_program()
    if vars is None:
        vars = [
            v
            for v in main_program.global_block().vars.values()
            if predicate is None or predicate(v)
        ]
    names = [v.name if isinstance(v, Variable) else str(v) for v in vars]

    os.makedirs(dirname, exist_ok=True)
    scope = getattr(executor, "scope", None) or global_scope()
    blobs = {}
    lods = {}
    for n in names:
        data, lengths = _to_host(_var_value(scope, n))
        blobs[n] = data
        if lengths is not None:
            lods[n] = lengths
    if filename is not None:
        np.savez(os.path.join(dirname, filename), **blobs)
    else:
        for n, data in blobs.items():
            np.save(os.path.join(dirname, n + ".npy"), data)
    if lods:
        lod_dir = os.path.join(dirname, "__lod__")
        os.makedirs(lod_dir, exist_ok=True)
        for n, lengths in lods.items():
            np.save(os.path.join(lod_dir, n + ".npy"), lengths)


def save_params(executor, dirname, main_program=None, filename=None):
    """reference: io.py save_params."""
    return save_vars(
        executor, dirname, main_program, predicate=is_parameter,
        filename=filename,
    )


def save_persistables(executor, dirname, main_program=None, filename=None):
    """reference: io.py:270 save_persistables."""
    return save_vars(
        executor, dirname, main_program, predicate=is_persistable,
        filename=filename,
    )


def load_vars(
    executor,
    dirname: str,
    main_program: Optional[Program] = None,
    vars: Optional[Sequence] = None,
    predicate: Optional[Callable] = None,
    filename: Optional[str] = None,
) -> None:
    """reference: io.py load_vars; values land directly in the scope."""
    main_program = main_program or default_main_program()
    if vars is None:
        vars = [
            v
            for v in main_program.global_block().vars.values()
            if predicate is None or predicate(v)
        ]
    names = [v.name if isinstance(v, Variable) else str(v) for v in vars]

    scope = getattr(executor, "scope", None) or global_scope()
    combined = None
    if filename is not None:
        path = os.path.join(dirname, filename)
        if not os.path.exists(path):
            path = path + ".npz"
        combined = np.load(path)
    lod_dir = os.path.join(dirname, "__lod__")
    for n in names:
        if combined is not None:
            if n not in combined:
                raise ValueError(f"variable '{n}' missing from {filename}")
            data = combined[n]
        else:
            path = os.path.join(dirname, n + ".npy")
            if not os.path.exists(path):
                raise ValueError(f"no saved file for variable '{n}' in {dirname}")
            data = np.load(path)
        lod_path = os.path.join(lod_dir, n + ".npy")
        if os.path.exists(lod_path):
            scope.set_var(n, LoDValue(data, np.load(lod_path)))
        else:
            scope.set_var(n, data)


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(
        executor, dirname, main_program, predicate=is_parameter,
        filename=filename,
    )


def load_persistables(executor, dirname, main_program=None, filename=None):
    """reference: io.py:490 load_persistables."""
    return load_vars(
        executor, dirname, main_program, predicate=is_persistable,
        filename=filename,
    )


# ---------------------------------------------------------------------------
# program pruning + inference model export
# ---------------------------------------------------------------------------
def _prune_for_targets(
    program: Program, feed_names: Sequence[str], target_names: Sequence[str]
) -> Program:
    """Backward-reachability prune of block 0, stopping at fed vars
    (reference: framework/prune.cc via Program._prune).  Sub-blocks
    referenced by kept ops survive whole."""
    pruned = program.clone()
    block = pruned.desc.block(0)
    feeds = set(feed_names)
    needed = set(target_names) - feeds
    kept = []
    for op in reversed(block.ops):
        outs = set(op.output_arg_names())
        if outs & needed:
            kept.append(op)
            for n in op.input_arg_names():
                if n not in feeds:
                    needed.add(n)
    kept.reverse()
    # drop feed/fetch ops from prior runs; the predictor re-injects its own
    block.ops[:] = [op for op in kept if op.type not in ("feed", "fetch")]
    return pruned


def _referenced_persistables(program: Program) -> List[str]:
    """Persistable vars block 0's ops actually touch (shared by
    save_inference_model / load_inference_model)."""
    block = program.desc.block(0)
    referenced = set()
    for op in block.ops:
        referenced.update(op.input_arg_names())
        referenced.update(op.output_arg_names())
    return [
        name
        for name, vd in block.vars.items()
        if vd.persistable and name in referenced
        and vd.type not in (VarType.RAW, VarType.READER, VarType.LOD_TENSOR_ARRAY)
    ]


def get_inference_program(target_vars, main_program=None) -> Program:
    """reference: io.py get_inference_program."""
    main_program = main_program or default_main_program()
    targets = [
        t.name if isinstance(t, Variable) else str(t) for t in target_vars
    ]
    pruned = _prune_for_targets(main_program, [], targets)
    return _for_test(pruned)


def _for_test(program: Program) -> Program:
    return program.clone(for_test=True)


def save_inference_model(
    dirname: str,
    feeded_var_names: Sequence[str],
    target_vars: Sequence,
    executor,
    main_program: Optional[Program] = None,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
    export_for_deployment: bool = True,
) -> None:
    """Prune to the inference graph + save params (reference: io.py:570)."""
    main_program = main_program or default_main_program()
    target_names = [
        t.name if isinstance(t, Variable) else str(t) for t in target_vars
    ]
    inference_program = _for_test(
        _prune_for_targets(main_program, feeded_var_names, target_names)
    )

    os.makedirs(dirname, exist_ok=True)
    model = {
        "program": inference_program.desc.to_dict(),
        "feed_names": list(feeded_var_names),
        "fetch_names": target_names,
    }
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "w") as f:
        json.dump(model, f)

    # save every persistable the pruned program still references
    save_vars(
        executor, dirname, main_program,
        vars=_referenced_persistables(inference_program),
        filename=params_filename,
    )


def load_inference_model(
    dirname: str,
    executor,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
):
    """reference: io.py:704 — returns (program, feed_names, fetch_targets)."""
    from .core.proto import ProgramDesc

    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path) as f:
        model = json.load(f)
    program = Program()
    program.desc = ProgramDesc.from_dict(model["program"])
    from .core.framework import Block

    program.blocks = [Block(program, i) for i in range(program.desc.num_blocks())]
    program.current_block_idx = 0

    load_vars(
        executor, dirname, program, vars=_referenced_persistables(program),
        filename=params_filename,
    )
    fetch_targets = [
        program.global_block().var(n) for n in model["fetch_names"]
    ]
    return program, model["feed_names"], fetch_targets


# ---------------------------------------------------------------------------
# sharded (per-process) checkpointing
# ---------------------------------------------------------------------------
# one writer thread per checkpoint dirname; a new async save joins the
# previous one before touching the directory
_inflight_saves: dict = {}
_save_atexit_registered = False


def _ensure_save_atexit():
    # one process-wide hook (not one per save): interpreter exit joins
    # every pending checkpoint write
    global _save_atexit_registered
    if _save_atexit_registered:
        return
    import atexit

    def _join_all():
        for t in list(_inflight_saves.values()):
            t.join()

    atexit.register(_join_all)
    _save_atexit_registered = True


class AsyncCheckpoint:
    """Handle for an in-flight save_sharded(asynchronous=True) write.  The
    device->host snapshot happened before the call returned; wait() joins
    the disk write and re-raises any IO error."""

    def __init__(self, thread, exc_box):
        self._thread = thread
        self._exc_box = exc_box

    def done(self) -> bool:
        return not self._thread.is_alive()

    def wait(self) -> None:
        self._thread.join()
        if self._exc_box:
            raise self._exc_box[0]


def save_sharded(
    dirname: str,
    main_program: Optional[Program] = None,
    scope=None,
    predicate: Optional[Callable] = None,
    asynchronous: bool = False,
):
    """Per-process sharded checkpoint (reference analogue: the per-pserver
    parameter slices of distribute_transpiler.py:990; modern shape:
    tensorstore-style per-host shard files).

    Each process writes ONLY the addressable shards of each persistable
    value into `<dirname>/shard_<process_index>.npz`, with per-shard global
    index slices recorded alongside, plus (process 0) a `meta.json` of
    global shapes/dtypes.  No host ever materializes a full pod-scale
    tensor.  Works identically for single-process runs (every shard is
    addressable).

    asynchronous=True snapshots device state to host synchronously, then
    writes the files on a background thread and returns an AsyncCheckpoint
    — training continues (and may donate/overwrite the live buffers)
    while the checkpoint persists.  Multi-process runs ignore the flag
    and write synchronously: the completion barrier is a collective,
    which must not run off the main thread."""
    import jax

    main_program = main_program or default_main_program()
    scope = scope or global_scope()
    if predicate is None:
        predicate = is_persistable
    names = [
        v.name for v in main_program.global_block().vars.values()
        if predicate(v)
    ]

    os.makedirs(dirname, exist_ok=True)
    pid = jax.process_index()

    # any earlier async save to this dirname must finish before we touch
    # the directory (sync path included): the old writer could otherwise
    # overwrite our shards or install its stale meta.json over them
    key = os.path.abspath(dirname)
    prev = _inflight_saves.pop(key, None)
    if prev is not None:
        prev.join()

    if asynchronous:
        # force a real host copy: np.asarray of a jax.Array can be a
        # zero-copy view on CPU backends, and the next training step may
        # donate/overwrite the live buffer while the background thread
        # still reads it
        def _snap(a):
            return np.array(a, copy=True)
    else:
        _snap = np.asarray
    blobs = {}
    index = {}
    meta = {}
    for n in names:
        val = scope.find_var(n)
        if val is None:
            continue
        if isinstance(val, LoDValue):
            val = val.data  # lengths are per-batch, not checkpoint state
        arr = val if isinstance(val, jax.Array) else jax.numpy.asarray(val)
        meta[n] = {
            "shape": [int(d) for d in arr.shape],
            "dtype": str(np.dtype(arr.dtype)),
        }
        shards = (
            arr.addressable_shards if isinstance(arr, jax.Array) else []
        )
        # replica 0 only: a dp-replicated parameter is written by exactly
        # one host cluster-wide, not once per host
        shards = [s for s in shards if getattr(s, "replica_id", 0) == 0]
        if shards or (
            isinstance(arr, jax.Array) and not arr.is_fully_addressable
        ):
            # dedup replicated shards: keep one per distinct index
            seen = set()
            for s in shards:
                idx_key = tuple(
                    (sl.start, sl.stop, sl.step) for sl in s.index
                )
                if idx_key in seen:
                    continue
                seen.add(idx_key)
                slot = f"{n}@@{len(seen) - 1}"
                blobs[slot] = _snap(s.data)
                index[slot] = {
                    "var": n,
                    "index": [
                        [sl.start, sl.stop, sl.step] for sl in s.index
                    ],
                }
        else:
            blobs[f"{n}@@0"] = _snap(arr)
            index[f"{n}@@0"] = {"var": n, "index": None}
    def _write():
        np.savez(os.path.join(dirname, f"shard_{pid}.npz"), **blobs)
        with open(os.path.join(dirname, f"index_{pid}.json"), "w") as f:
            json.dump(index, f)

    def _finish():
        if pid == 0:
            # write-then-rename: a crashed/killed writer never leaves a
            # meta.json marking a truncated checkpoint complete (and an
            # overwritten dir's STALE meta.json is replaced atomically)
            tmp = os.path.join(dirname, ".meta.json.tmp")
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, os.path.join(dirname, "meta.json"))

    if asynchronous and jax.process_count() == 1:
        import threading

        # an existing meta.json would mark the dir complete while the new
        # shard files are still being written over the old ones
        try:
            os.remove(os.path.join(dirname, "meta.json"))
        except FileNotFoundError:
            pass
        exc_box: list = []

        def _bg():
            try:
                _write()
                _finish()
            except BaseException as e:  # surfaced by AsyncCheckpoint.wait
                exc_box.append(e)
            finally:
                # self-prune, unless a newer save already took the slot
                if _inflight_saves.get(key) is t:
                    _inflight_saves.pop(key, None)

        t = threading.Thread(target=_bg, name="save_sharded", daemon=True)
        _inflight_saves[key] = t
        _ensure_save_atexit()
        t.start()
        return AsyncCheckpoint(t, exc_box)

    _write()
    if jax.process_count() > 1:
        # all shard files durable before meta.json marks the checkpoint
        # complete (and before any process returns to its caller)
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("save_sharded")
    _finish()
    if asynchronous:
        # multi-process fallback wrote synchronously; hand back a
        # completed handle so caller code stays uniform across scales
        import threading

        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        return AsyncCheckpoint(t, [])
    return None


def load_sharded(
    dirname: str,
    main_program: Optional[Program] = None,
    scope=None,
    mesh=None,
    predicate: Optional[Callable] = None,
) -> None:
    """Restore a save_sharded checkpoint.  Every process reads all shard
    files (shared filesystem, as the reference's pserver checkpoints
    assume), reassembles each var, and — when `mesh` is given — places it
    sharded again via jax.device_put so no full copy stays live per device.
    With main_program=None every var recorded in the checkpoint loads."""
    import jax

    scope = scope or global_scope()
    with open(os.path.join(dirname, "meta.json")) as f:
        meta = json.load(f)

    if main_program is None:
        wanted = set(meta)
    else:
        if predicate is None:
            predicate = is_persistable
        wanted = {
            v.name for v in main_program.global_block().vars.values()
            if predicate(v)
        }

    assembled = {}
    for fn in sorted(os.listdir(dirname)):
        if not fn.startswith("index_"):
            continue
        pid = fn[len("index_"):-len(".json")]
        with open(os.path.join(dirname, fn)) as f:
            index = json.load(f)
        with np.load(os.path.join(dirname, f"shard_{pid}.npz")) as z:
            for slot, entry in index.items():
                n = entry["var"]
                if n not in wanted or n not in meta:
                    continue
                buf = assembled.get(n)
                if buf is None:
                    buf = np.zeros(
                        meta[n]["shape"], dtype=meta[n]["dtype"]
                    )
                    assembled[n] = buf
                if entry["index"] is None:
                    assembled[n] = z[slot]
                else:
                    sl = tuple(
                        slice(s[0], s[1], s[2]) for s in entry["index"]
                    )
                    buf[sl] = z[slot]

    block0 = (
        main_program.desc.block(0) if main_program is not None else None
    )
    for n, arr in assembled.items():
        if mesh is not None:
            vd = block0.vars.get(n) if block0 is not None else None
            logical = vd.sharding if vd is not None else None
            sharding = (
                mesh.sharding(logical) if logical else mesh.replicated()
            )
            scope.set_var(n, jax.device_put(arr, sharding))
        else:
            scope.set_var(n, arr)
