"""Checkpoint / model IO (reference: python/paddle/fluid/io.py).

The reference saves by appending `save`/`load` ops (operators/save_op.cc)
and running them through an executor; variables serialize as LoDTensor blobs
with a version header.  TPU-native equivalent: checkpointing is a *host*
concern — values are pulled from the Scope (device->host), written as numpy
blobs, and restored by name.  The public API mirrors io.py:89-704:
save/load_vars, save/load_params, save/load_persistables,
save/load_inference_model.

Layout on disk (dirname/):
    <var_name>            one numpy .npy blob per var (save_vars default)
    <filename>            single .npz when filename= given (save_combine)
    __model__             program desc JSON (save_inference_model)
    __lod__/<var_name>    sequence lengths sidecar for LoDValues
"""

from __future__ import annotations

import json
import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from .core.framework import Program, Variable, default_main_program
from .core.lod import LoDValue
from .core.proto import VarType
from .core.scope import global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables",
    "load_vars", "load_params", "load_persistables",
    "save_inference_model", "load_inference_model",
    "get_inference_program",
    "save_sharded", "load_sharded", "AsyncCheckpoint",
    "CheckpointCorruptError",
]


class CheckpointCorruptError(RuntimeError):
    """A sharded checkpoint failed verification: a shard file is missing,
    truncated, or digest-mismatched, or a tensor is not fully covered by
    the index.  The message names the offending file/variable.  Raised
    instead of ever loading garbage (the pre-manifest loader silently
    zero-filled missing shards); CheckpointManager.restore_or_init walks
    past checkpoints that raise this."""


def is_persistable(var: Variable) -> bool:
    """reference: io.py is_persistable — skips reader/raw vars."""
    if var.desc.type in (VarType.RAW, VarType.READER, VarType.LOD_TENSOR_ARRAY):
        return False
    return bool(var.persistable)


def is_parameter(var: Variable) -> bool:
    from .core.framework import Parameter

    return isinstance(var, Parameter)


def _var_value(scope, name: str):
    v = scope.find_var(name)
    if v is None:
        raise ValueError(f"variable '{name}' has no value in scope")
    return v


def _to_host(value):
    if isinstance(value, LoDValue):
        return np.asarray(value.data), np.asarray(value.lengths)
    return np.asarray(value), None


def save_vars(
    executor,
    dirname: str,
    main_program: Optional[Program] = None,
    vars: Optional[Sequence] = None,
    predicate: Optional[Callable] = None,
    filename: Optional[str] = None,
) -> None:
    """Save selected vars from the executor's scope (reference: io.py:89)."""
    main_program = main_program or default_main_program()
    if vars is None:
        vars = [
            v
            for v in main_program.global_block().vars.values()
            if predicate is None or predicate(v)
        ]
    names = [v.name if isinstance(v, Variable) else str(v) for v in vars]

    os.makedirs(dirname, exist_ok=True)
    scope = getattr(executor, "scope", None) or global_scope()
    blobs = {}
    lods = {}
    for n in names:
        data, lengths = _to_host(_var_value(scope, n))
        blobs[n] = data
        if lengths is not None:
            lods[n] = lengths
    if filename is not None:
        np.savez(os.path.join(dirname, filename), **blobs)
    else:
        for n, data in blobs.items():
            np.save(os.path.join(dirname, n + ".npy"), data)
    if lods:
        lod_dir = os.path.join(dirname, "__lod__")
        os.makedirs(lod_dir, exist_ok=True)
        for n, lengths in lods.items():
            np.save(os.path.join(lod_dir, n + ".npy"), lengths)


def save_params(executor, dirname, main_program=None, filename=None):
    """reference: io.py save_params."""
    return save_vars(
        executor, dirname, main_program, predicate=is_parameter,
        filename=filename,
    )


def save_persistables(executor, dirname, main_program=None, filename=None):
    """reference: io.py:270 save_persistables."""
    return save_vars(
        executor, dirname, main_program, predicate=is_persistable,
        filename=filename,
    )


def load_vars(
    executor,
    dirname: str,
    main_program: Optional[Program] = None,
    vars: Optional[Sequence] = None,
    predicate: Optional[Callable] = None,
    filename: Optional[str] = None,
) -> None:
    """reference: io.py load_vars; values land directly in the scope."""
    main_program = main_program or default_main_program()
    if vars is None:
        vars = [
            v
            for v in main_program.global_block().vars.values()
            if predicate is None or predicate(v)
        ]
    names = [v.name if isinstance(v, Variable) else str(v) for v in vars]

    scope = getattr(executor, "scope", None) or global_scope()
    combined = None
    if filename is not None:
        path = os.path.join(dirname, filename)
        if not os.path.exists(path):
            path = path + ".npz"
        combined = np.load(path)
    lod_dir = os.path.join(dirname, "__lod__")
    for n in names:
        if combined is not None:
            if n not in combined:
                raise ValueError(f"variable '{n}' missing from {filename}")
            data = combined[n]
        else:
            path = os.path.join(dirname, n + ".npy")
            if not os.path.exists(path):
                raise ValueError(f"no saved file for variable '{n}' in {dirname}")
            data = np.load(path)
        lod_path = os.path.join(lod_dir, n + ".npy")
        if os.path.exists(lod_path):
            scope.set_var(n, LoDValue(data, np.load(lod_path)))
        else:
            scope.set_var(n, data)


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(
        executor, dirname, main_program, predicate=is_parameter,
        filename=filename,
    )


def load_persistables(executor, dirname, main_program=None, filename=None):
    """reference: io.py:490 load_persistables."""
    return load_vars(
        executor, dirname, main_program, predicate=is_persistable,
        filename=filename,
    )


# ---------------------------------------------------------------------------
# program pruning + inference model export
# ---------------------------------------------------------------------------
def _prune_for_targets(
    program: Program, feed_names: Sequence[str], target_names: Sequence[str]
) -> Program:
    """Backward-reachability prune of block 0, stopping at fed vars
    (reference: framework/prune.cc via Program._prune).  Sub-blocks
    referenced by kept ops survive whole."""
    pruned = program.clone()
    block = pruned.desc.block(0)
    feeds = set(feed_names)
    needed = set(target_names) - feeds
    kept = []
    for op in reversed(block.ops):
        outs = set(op.output_arg_names())
        if outs & needed:
            kept.append(op)
            for n in op.input_arg_names():
                if n not in feeds:
                    needed.add(n)
    kept.reverse()
    # drop feed/fetch ops from prior runs; the predictor re-injects its own
    block.ops[:] = [op for op in kept if op.type not in ("feed", "fetch")]
    return pruned


def _referenced_persistables(program: Program) -> List[str]:
    """Persistable vars block 0's ops actually touch (shared by
    save_inference_model / load_inference_model)."""
    block = program.desc.block(0)
    referenced = set()
    for op in block.ops:
        referenced.update(op.input_arg_names())
        referenced.update(op.output_arg_names())
    return [
        name
        for name, vd in block.vars.items()
        if vd.persistable and name in referenced
        and vd.type not in (VarType.RAW, VarType.READER, VarType.LOD_TENSOR_ARRAY)
    ]


def get_inference_program(target_vars, main_program=None) -> Program:
    """reference: io.py get_inference_program."""
    main_program = main_program or default_main_program()
    targets = [
        t.name if isinstance(t, Variable) else str(t) for t in target_vars
    ]
    pruned = _prune_for_targets(main_program, [], targets)
    return _for_test(pruned)


def _for_test(program: Program) -> Program:
    return program.clone(for_test=True)


def save_inference_model(
    dirname: str,
    feeded_var_names: Sequence[str],
    target_vars: Sequence,
    executor,
    main_program: Optional[Program] = None,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
    export_for_deployment: bool = True,
) -> None:
    """Prune to the inference graph + save params (reference: io.py:570)."""
    main_program = main_program or default_main_program()
    target_names = [
        t.name if isinstance(t, Variable) else str(t) for t in target_vars
    ]
    inference_program = _for_test(
        _prune_for_targets(main_program, feeded_var_names, target_names)
    )

    os.makedirs(dirname, exist_ok=True)
    model = {
        "program": inference_program.desc.to_dict(),
        "feed_names": list(feeded_var_names),
        "fetch_names": target_names,
    }
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "w") as f:
        json.dump(model, f)

    # save every persistable the pruned program still references
    save_vars(
        executor, dirname, main_program,
        vars=_referenced_persistables(inference_program),
        filename=params_filename,
    )


def load_inference_model(
    dirname: str,
    executor,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
):
    """reference: io.py:704 — returns (program, feed_names, fetch_targets)."""
    from .core.proto import ProgramDesc

    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path) as f:
        model = json.load(f)
    program = Program()
    program.desc = ProgramDesc.from_dict(model["program"])
    from .core.framework import Block

    program.blocks = [Block(program, i) for i in range(program.desc.num_blocks())]
    program.current_block_idx = 0

    load_vars(
        executor, dirname, program, vars=_referenced_persistables(program),
        filename=params_filename,
    )
    fetch_targets = [
        program.global_block().var(n) for n in model["fetch_names"]
    ]
    return program, model["feed_names"], fetch_targets


# ---------------------------------------------------------------------------
# sharded (per-process) checkpointing
# ---------------------------------------------------------------------------
# one writer thread per checkpoint dirname; a new async save joins the
# previous one before touching the directory
_inflight_saves: dict = {}
_save_atexit_registered = False


def _ensure_save_atexit():
    # one process-wide hook (not one per save): interpreter exit joins
    # every pending checkpoint write
    global _save_atexit_registered
    if _save_atexit_registered:
        return
    import atexit

    def _join_all():
        for t in list(_inflight_saves.values()):
            t.join()

    atexit.register(_join_all)
    _save_atexit_registered = True


class AsyncCheckpoint:
    """Handle for an in-flight save_sharded(asynchronous=True) write.  The
    device->host snapshot happened before the call returned; wait() joins
    the disk write and re-raises any IO error.  With no thread the handle
    is pre-completed (`AsyncCheckpoint.completed()`) — the multi-process
    fallback writes synchronously and hands one back so caller code stays
    uniform across scales.

    `stats` is a caller-shared dict of save accounting
    (CheckpointManager fills save_seconds / gc_seconds / step there —
    previously measured nowhere and dropped); for async saves it is
    complete once wait() returns."""

    def __init__(self, thread=None, exc_box=None, stats=None):
        self._thread = thread
        self._exc_box = exc_box if exc_box is not None else []
        self.stats = {} if stats is None else stats

    @classmethod
    def completed(cls) -> "AsyncCheckpoint":
        return cls()

    def done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
        if self._exc_box:
            raise self._exc_box[0]


def _file_digest(path: str):
    """(byte size, crc32) of a file, streamed."""
    import zlib

    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return size, crc


def _checkpoint_barrier(tag: str) -> None:
    from .parallel.multihost import checkpoint_barrier

    checkpoint_barrier(tag)


def save_sharded(
    dirname: str,
    main_program: Optional[Program] = None,
    scope=None,
    predicate: Optional[Callable] = None,
    asynchronous: bool = False,
    step: Optional[int] = None,
    extra: Optional[dict] = None,
):
    """Per-process sharded checkpoint (reference analogue: the per-pserver
    parameter slices of distribute_transpiler.py:990; modern shape:
    tensorstore-style per-host shard files).

    Each process writes ONLY the addressable shards of each persistable
    value into `<dirname>/shard_<process_index>.npz`, with per-shard global
    index slices recorded alongside, plus (process 0) a `meta.json` of
    global shapes/dtypes.  No host ever materializes a full pod-scale
    tensor.  Works identically for single-process runs (every shard is
    addressable).

    meta.json also carries a verification manifest under "__manifest__":
    the expected process count and shard-file list with per-file byte
    sizes + CRC32 digests, the global `step`, wall time, and the caller's
    `extra` metadata dict (CheckpointManager stores its cursor there).
    load_sharded verifies all of it — a truncated/corrupt/missing shard
    raises CheckpointCorruptError instead of loading garbage.  Because
    meta.json is written LAST (after the all-shards-durable barrier,
    write-then-rename), its presence marks the checkpoint complete.

    asynchronous=True snapshots device state to host synchronously, then
    writes the files on a background thread and returns an AsyncCheckpoint
    — training continues (and may donate/overwrite the live buffers)
    while the checkpoint persists.  Multi-process runs write synchronously
    (the completion barrier is a collective, which must not run off the
    main thread) and return a pre-completed handle."""
    import jax

    main_program = main_program or default_main_program()
    scope = scope or global_scope()
    if predicate is None:
        predicate = is_persistable
    names = [
        v.name for v in main_program.global_block().vars.values()
        if predicate(v)
    ]

    os.makedirs(dirname, exist_ok=True)
    pid = jax.process_index()

    # any earlier async save to this dirname must finish before we touch
    # the directory (sync path included): the old writer could otherwise
    # overwrite our shards or install its stale meta.json over them
    key = os.path.abspath(dirname)
    prev = _inflight_saves.pop(key, None)
    if prev is not None:
        prev.join()

    if asynchronous:
        # force a real host copy: np.asarray of a jax.Array can be a
        # zero-copy view on CPU backends, and the next training step may
        # donate/overwrite the live buffer while the background thread
        # still reads it
        def _snap(a):
            return np.array(a, copy=True)
    else:
        _snap = np.asarray
    blobs = {}
    index = {}
    meta = {}
    for n in names:
        val = scope.find_var(n)
        if val is None:
            continue
        if isinstance(val, LoDValue):
            val = val.data  # lengths are per-batch, not checkpoint state
        arr = val if isinstance(val, jax.Array) else jax.numpy.asarray(val)
        meta[n] = {
            "shape": [int(d) for d in arr.shape],
            "dtype": str(np.dtype(arr.dtype)),
        }
        shards = (
            arr.addressable_shards if isinstance(arr, jax.Array) else []
        )
        # replica 0 only: a dp-replicated parameter is written by exactly
        # one host cluster-wide, not once per host
        shards = [s for s in shards if getattr(s, "replica_id", 0) == 0]
        if shards or (
            isinstance(arr, jax.Array) and not arr.is_fully_addressable
        ):
            # dedup replicated shards: keep one per distinct index
            seen = set()
            for s in shards:
                idx_key = tuple(
                    (sl.start, sl.stop, sl.step) for sl in s.index
                )
                if idx_key in seen:
                    continue
                seen.add(idx_key)
                slot = f"{n}@@{len(seen) - 1}"
                blobs[slot] = _snap(s.data)
                index[slot] = {
                    "var": n,
                    "index": [
                        [sl.start, sl.stop, sl.step] for sl in s.index
                    ],
                }
        else:
            blobs[f"{n}@@0"] = _snap(arr)
            index[f"{n}@@0"] = {"var": n, "index": None}
    proc_count = jax.process_count()

    def _write():
        from .resilience import faultinject

        shard_path = os.path.join(dirname, f"shard_{pid}.npz")
        np.savez(shard_path, **blobs)
        faultinject.shard_write_kill(shard_path)  # no-op unless armed
        with open(os.path.join(dirname, f"index_{pid}.json"), "w") as f:
            json.dump(index, f)

    def _finish():
        if pid == 0:
            # manifest: every process's shard files sized + digested, so
            # the loader can prove completeness and integrity before a
            # single byte lands in the scope.  All shard files are
            # durable at this point (single writer, or post-barrier).
            import time as _time

            files = {}
            for p in range(proc_count):
                for fn in (f"shard_{p}.npz", f"index_{p}.json"):
                    size, crc = _file_digest(os.path.join(dirname, fn))
                    files[fn] = {"bytes": size, "crc32": crc}
            manifest = {
                "version": 1,
                "process_count": proc_count,
                "step": None if step is None else int(step),
                "wall_time": _time.time(),
                "files": files,
            }
            if extra is not None:
                manifest["extra"] = extra
            meta["__manifest__"] = manifest
            # write-then-rename: a crashed/killed writer never leaves a
            # meta.json marking a truncated checkpoint complete (and an
            # overwritten dir's STALE meta.json is replaced atomically)
            tmp = os.path.join(dirname, ".meta.json.tmp")
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, os.path.join(dirname, "meta.json"))
        from .resilience import faultinject

        faultinject.maybe_corrupt_after_save(dirname)  # chaos hook

    if asynchronous and jax.process_count() == 1:
        import threading

        # an existing meta.json would mark the dir complete while the new
        # shard files are still being written over the old ones
        try:
            os.remove(os.path.join(dirname, "meta.json"))
        except FileNotFoundError:
            pass
        exc_box: list = []

        def _bg():
            try:
                _write()
                _finish()
            except BaseException as e:  # surfaced by AsyncCheckpoint.wait
                exc_box.append(e)
            finally:
                # self-prune, unless a newer save already took the slot
                if _inflight_saves.get(key) is t:
                    _inflight_saves.pop(key, None)

        t = threading.Thread(target=_bg, name="save_sharded", daemon=True)
        _inflight_saves[key] = t
        _ensure_save_atexit()
        t.start()
        return AsyncCheckpoint(t, exc_box)

    if pid == 0:
        # overwriting an EXISTING checkpoint (e.g. a preemption drain
        # re-saving the current step): invalidate it first — a kill
        # mid-rewrite must leave "no meta.json" (skipped by restore), not
        # the old manifest's digests over half-new shards masquerading as
        # the old checkpoint (the async path below does the same)
        try:
            os.remove(os.path.join(dirname, "meta.json"))
        except FileNotFoundError:
            pass
    _write()
    # all shard files durable before meta.json marks the checkpoint
    # complete (and before any process returns to its caller); no-op for
    # single-process runs
    _checkpoint_barrier("save_sharded")
    _finish()
    if asynchronous:
        # multi-process fallback wrote synchronously; hand back a
        # pre-completed handle so caller code stays uniform across scales
        return AsyncCheckpoint.completed()
    return None


def _verify_manifest(dirname: str, manifest: dict) -> List[str]:
    """Check every manifest-listed file exists with the recorded byte
    size and CRC32 digest; return the index-file list to assemble from.
    Reading ONLY manifest-listed files also keeps stale shards from an
    older save in the same directory out of the assembly."""
    files = manifest.get("files", {})
    for fn in sorted(files):
        want = files[fn]
        path = os.path.join(dirname, fn)
        if not os.path.exists(path):
            raise CheckpointCorruptError(
                f"{path}: shard file missing (manifest expects "
                f"{len(files)} files from "
                f"{manifest.get('process_count')} processes)"
            )
        size = os.path.getsize(path)
        if size != want["bytes"]:
            raise CheckpointCorruptError(
                f"{path}: truncated or overgrown ({size} bytes on disk, "
                f"manifest recorded {want['bytes']})"
            )
        # streamed CRC (1 MB chunks): O(1 MB) extra memory even for
        # pod-scale shards; np.load's subsequent read of the same file
        # is page-cache warm, so the second pass is cheap
        _, crc = _file_digest(path)
        if crc != want["crc32"]:
            raise CheckpointCorruptError(
                f"{path}: digest mismatch (crc32 {crc:#010x} on disk, "
                f"manifest recorded {want['crc32']:#010x})"
            )
    return sorted(fn for fn in files if fn.startswith("index_"))


def load_sharded(
    dirname: str,
    main_program: Optional[Program] = None,
    scope=None,
    mesh=None,
    predicate: Optional[Callable] = None,
) -> Optional[dict]:
    """Restore a save_sharded checkpoint.  Every process reads all shard
    files (shared filesystem, as the reference's pserver checkpoints
    assume), reassembles each var, and — when `mesh` is given — places it
    sharded again via jax.device_put so no full copy stays live per device.
    With main_program=None every var recorded in the checkpoint loads.

    Verification happens BEFORE anything lands in the scope: every
    manifest-listed shard file must exist with the recorded size + CRC32
    digest, and every tensor the checkpoint claims must be fully covered
    by index slices — a missing, truncated, or corrupt shard raises
    CheckpointCorruptError naming the offending file instead of silently
    zero-filling (the pre-manifest behavior this replaces).  Checkpoints
    written before the manifest existed still get the coverage check.

    Returns the checkpoint's manifest dict (step / wall_time / extra
    metadata), or None for a pre-manifest checkpoint."""
    import jax
    import zipfile
    import zlib

    scope = scope or global_scope()
    meta_path = os.path.join(dirname, "meta.json")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"{meta_path}: missing — the checkpoint never completed "
            "(meta.json is written last)"
        )
    except ValueError as e:
        raise CheckpointCorruptError(f"{meta_path}: unreadable ({e})")
    manifest = meta.pop("__manifest__", None)
    if manifest is not None:
        index_files = _verify_manifest(dirname, manifest)
    else:
        index_files = sorted(
            fn for fn in os.listdir(dirname)
            if fn.startswith("index_") and fn.endswith(".json")
        )

    if main_program is None:
        wanted = set(meta)
    else:
        if predicate is None:
            predicate = is_persistable
        wanted = {
            v.name for v in main_program.global_block().vars.values()
            if predicate(v)
        }

    assembled = {}
    covered = {}  # var -> True (full) | bool mask of covered elements
    for fn in index_files:
        pid = fn[len("index_"):-len(".json")]
        shard_fn = f"shard_{pid}.npz"
        try:
            with open(os.path.join(dirname, fn)) as f:
                index = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                f"{os.path.join(dirname, fn)}: unreadable index ({e})"
            )
        try:
            with np.load(os.path.join(dirname, shard_fn)) as z:
                for slot, entry in index.items():
                    n = entry["var"]
                    if n not in wanted or n not in meta:
                        continue
                    buf = assembled.get(n)
                    if buf is None:
                        buf = np.zeros(
                            meta[n]["shape"], dtype=meta[n]["dtype"]
                        )
                        assembled[n] = buf
                    if entry["index"] is None:
                        assembled[n] = z[slot]
                        covered[n] = True
                    else:
                        sl = tuple(
                            slice(s[0], s[1], s[2]) for s in entry["index"]
                        )
                        buf[sl] = z[slot]
                        if covered.get(n) is not True:
                            mask = covered.get(n)
                            if mask is None:
                                mask = np.zeros(
                                    meta[n]["shape"], dtype=bool
                                )
                                covered[n] = mask
                            mask[sl] = True
        except (OSError, ValueError, KeyError, zipfile.BadZipFile,
                zlib.error) as e:
            raise CheckpointCorruptError(
                f"{os.path.join(dirname, shard_fn)}: unreadable shard "
                f"({type(e).__name__}: {e})"
            )

    # full-coverage assertion: every tensor the checkpoint CLAIMS (is in
    # meta) and the caller wants must be entirely written by some shard —
    # no silent zero-fill of absent/partial shards, ever
    for n in sorted(set(meta) & wanted):
        cov = covered.get(n)
        if cov is None:
            raise CheckpointCorruptError(
                f"{dirname}: no shard covers variable '{n}' "
                "(its index entries are missing entirely)"
            )
        if cov is not True and not cov.all():
            missing = int(cov.size - np.count_nonzero(cov))
            raise CheckpointCorruptError(
                f"{dirname}: variable '{n}' is only partially covered by "
                f"the shard index ({missing} of {cov.size} elements have "
                "no shard)"
            )

    block0 = (
        main_program.desc.block(0) if main_program is not None else None
    )
    for n, arr in assembled.items():
        if mesh is not None:
            vd = block0.vars.get(n) if block0 is not None else None
            logical = vd.sharding if vd is not None else None
            sharding = (
                mesh.sharding(logical) if logical else mesh.replicated()
            )
            scope.set_var(n, jax.device_put(arr, sharding))
        else:
            scope.set_var(n, arr)
    # deliberately NO collective barrier here: a process that raises
    # CheckpointCorruptError (local read error, torn NFS view) would
    # strand the others in the collective forever, and independent
    # newest->oldest walks (restore_or_init) could pair barriers from
    # DIFFERENT checkpoints — silently loading divergent params.
    # Multi-host restore agreement is the caller's job: pick the
    # checkpoint once (e.g. process 0 broadcasts the step), then load.
    return manifest
