"""Checkpoint / model IO (reference: python/paddle/fluid/io.py).

The reference saves by appending `save`/`load` ops (operators/save_op.cc)
and running them through an executor; variables serialize as LoDTensor blobs
with a version header.  TPU-native equivalent: checkpointing is a *host*
concern — values are pulled from the Scope (device->host), written as numpy
blobs, and restored by name.  The public API mirrors io.py:89-704:
save/load_vars, save/load_params, save/load_persistables,
save/load_inference_model.

Layout on disk (dirname/):
    <var_name>            one numpy .npy blob per var (save_vars default)
    <filename>            single .npz when filename= given (save_combine)
    __model__             program desc JSON (save_inference_model)
    __lod__/<var_name>    sequence lengths sidecar for LoDValues
"""

from __future__ import annotations

import json
import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from .core.framework import Program, Variable, default_main_program
from .core.lod import LoDValue
from .core.proto import VarType
from .core.scope import global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables",
    "load_vars", "load_params", "load_persistables",
    "save_inference_model", "load_inference_model",
    "get_inference_program",
]


def is_persistable(var: Variable) -> bool:
    """reference: io.py is_persistable — skips reader/raw vars."""
    if var.desc.type in (VarType.RAW, VarType.READER, VarType.LOD_TENSOR_ARRAY):
        return False
    return bool(var.persistable)


def is_parameter(var: Variable) -> bool:
    from .core.framework import Parameter

    return isinstance(var, Parameter)


def _var_value(scope, name: str):
    v = scope.find_var(name)
    if v is None:
        raise ValueError(f"variable '{name}' has no value in scope")
    return v


def _to_host(value):
    if isinstance(value, LoDValue):
        return np.asarray(value.data), np.asarray(value.lengths)
    return np.asarray(value), None


def save_vars(
    executor,
    dirname: str,
    main_program: Optional[Program] = None,
    vars: Optional[Sequence] = None,
    predicate: Optional[Callable] = None,
    filename: Optional[str] = None,
) -> None:
    """Save selected vars from the executor's scope (reference: io.py:89)."""
    main_program = main_program or default_main_program()
    if vars is None:
        vars = [
            v
            for v in main_program.global_block().vars.values()
            if predicate is None or predicate(v)
        ]
    names = [v.name if isinstance(v, Variable) else str(v) for v in vars]

    os.makedirs(dirname, exist_ok=True)
    scope = getattr(executor, "scope", None) or global_scope()
    blobs = {}
    lods = {}
    for n in names:
        data, lengths = _to_host(_var_value(scope, n))
        blobs[n] = data
        if lengths is not None:
            lods[n] = lengths
    if filename is not None:
        np.savez(os.path.join(dirname, filename), **blobs)
    else:
        for n, data in blobs.items():
            np.save(os.path.join(dirname, n + ".npy"), data)
    if lods:
        lod_dir = os.path.join(dirname, "__lod__")
        os.makedirs(lod_dir, exist_ok=True)
        for n, lengths in lods.items():
            np.save(os.path.join(lod_dir, n + ".npy"), lengths)


def save_params(executor, dirname, main_program=None, filename=None):
    """reference: io.py save_params."""
    return save_vars(
        executor, dirname, main_program, predicate=is_parameter,
        filename=filename,
    )


def save_persistables(executor, dirname, main_program=None, filename=None):
    """reference: io.py:270 save_persistables."""
    return save_vars(
        executor, dirname, main_program, predicate=is_persistable,
        filename=filename,
    )


def load_vars(
    executor,
    dirname: str,
    main_program: Optional[Program] = None,
    vars: Optional[Sequence] = None,
    predicate: Optional[Callable] = None,
    filename: Optional[str] = None,
) -> None:
    """reference: io.py load_vars; values land directly in the scope."""
    main_program = main_program or default_main_program()
    if vars is None:
        vars = [
            v
            for v in main_program.global_block().vars.values()
            if predicate is None or predicate(v)
        ]
    names = [v.name if isinstance(v, Variable) else str(v) for v in vars]

    scope = getattr(executor, "scope", None) or global_scope()
    combined = None
    if filename is not None:
        path = os.path.join(dirname, filename)
        if not os.path.exists(path):
            path = path + ".npz"
        combined = np.load(path)
    lod_dir = os.path.join(dirname, "__lod__")
    for n in names:
        if combined is not None:
            if n not in combined:
                raise ValueError(f"variable '{n}' missing from {filename}")
            data = combined[n]
        else:
            path = os.path.join(dirname, n + ".npy")
            if not os.path.exists(path):
                raise ValueError(f"no saved file for variable '{n}' in {dirname}")
            data = np.load(path)
        lod_path = os.path.join(lod_dir, n + ".npy")
        if os.path.exists(lod_path):
            scope.set_var(n, LoDValue(data, np.load(lod_path)))
        else:
            scope.set_var(n, data)


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(
        executor, dirname, main_program, predicate=is_parameter,
        filename=filename,
    )


def load_persistables(executor, dirname, main_program=None, filename=None):
    """reference: io.py:490 load_persistables."""
    return load_vars(
        executor, dirname, main_program, predicate=is_persistable,
        filename=filename,
    )


# ---------------------------------------------------------------------------
# program pruning + inference model export
# ---------------------------------------------------------------------------
def _prune_for_targets(
    program: Program, feed_names: Sequence[str], target_names: Sequence[str]
) -> Program:
    """Backward-reachability prune of block 0, stopping at fed vars
    (reference: framework/prune.cc via Program._prune).  Sub-blocks
    referenced by kept ops survive whole."""
    pruned = program.clone()
    block = pruned.desc.block(0)
    feeds = set(feed_names)
    needed = set(target_names) - feeds
    kept = []
    for op in reversed(block.ops):
        outs = set(op.output_arg_names())
        if outs & needed:
            kept.append(op)
            for n in op.input_arg_names():
                if n not in feeds:
                    needed.add(n)
    kept.reverse()
    # drop feed/fetch ops from prior runs; the predictor re-injects its own
    block.ops[:] = [op for op in kept if op.type not in ("feed", "fetch")]
    return pruned


def _referenced_persistables(program: Program) -> List[str]:
    """Persistable vars block 0's ops actually touch (shared by
    save_inference_model / load_inference_model)."""
    block = program.desc.block(0)
    referenced = set()
    for op in block.ops:
        referenced.update(op.input_arg_names())
        referenced.update(op.output_arg_names())
    return [
        name
        for name, vd in block.vars.items()
        if vd.persistable and name in referenced
        and vd.type not in (VarType.RAW, VarType.READER, VarType.LOD_TENSOR_ARRAY)
    ]


def get_inference_program(target_vars, main_program=None) -> Program:
    """reference: io.py get_inference_program."""
    main_program = main_program or default_main_program()
    targets = [
        t.name if isinstance(t, Variable) else str(t) for t in target_vars
    ]
    pruned = _prune_for_targets(main_program, [], targets)
    return _for_test(pruned)


def _for_test(program: Program) -> Program:
    return program.clone(for_test=True)


def save_inference_model(
    dirname: str,
    feeded_var_names: Sequence[str],
    target_vars: Sequence,
    executor,
    main_program: Optional[Program] = None,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
    export_for_deployment: bool = True,
) -> None:
    """Prune to the inference graph + save params (reference: io.py:570)."""
    main_program = main_program or default_main_program()
    target_names = [
        t.name if isinstance(t, Variable) else str(t) for t in target_vars
    ]
    inference_program = _for_test(
        _prune_for_targets(main_program, feeded_var_names, target_names)
    )

    os.makedirs(dirname, exist_ok=True)
    model = {
        "program": inference_program.desc.to_dict(),
        "feed_names": list(feeded_var_names),
        "fetch_names": target_names,
    }
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "w") as f:
        json.dump(model, f)

    # save every persistable the pruned program still references
    save_vars(
        executor, dirname, main_program,
        vars=_referenced_persistables(inference_program),
        filename=params_filename,
    )


def load_inference_model(
    dirname: str,
    executor,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
):
    """reference: io.py:704 — returns (program, feed_names, fetch_targets)."""
    from .core.proto import ProgramDesc

    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path) as f:
        model = json.load(f)
    program = Program()
    program.desc = ProgramDesc.from_dict(model["program"])
    from .core.framework import Block

    program.blocks = [Block(program, i) for i in range(program.desc.num_blocks())]
    program.current_block_idx = 0

    load_vars(
        executor, dirname, program, vars=_referenced_persistables(program),
        filename=params_filename,
    )
    fetch_targets = [
        program.global_block().var(n) for n in model["fetch_names"]
    ]
    return program, model["feed_names"], fetch_targets
