"""Checkpoint-restart elastic trainer
(reference roles: go/pserver periodic checkpoint + LoadCheckpoint
(service.go:346/:175) and the stateless v2 trainer pulling tasks from the
master; Fluid-side persistence via io.py save/load_persistables).

A worker is stateless between tasks: it leases a task from the
MasterService, trains over the task's chunks, reports completion, and
checkpoints params + its pass cursor.  Kill it at any point and a
restarted worker recovers the params from the checkpoint and the queue
from the master's snapshot — the leased task's timeout re-dispatches it.
That is the whole elasticity contract: add/remove workers freely, each
one runs this same loop.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Iterable, Optional

from .. import io as fluid_io
from ..core.framework import (
    Program,
    default_main_program,
    default_startup_program,
)
from .master import (
    AllTasksFailedError,
    MasterService,
    NoMoreAvailableError,
    PassAfterError,
    PassBeforeError,
)

__all__ = ["ElasticTrainer"]

_META = "elastic_meta.json"


class ElasticTrainer:
    """Pull tasks, train, checkpoint; resume transparently after a crash.

    Args:
        master: the MasterService (or an RPC proxy with the same surface).
        executor: a fluid Executor.
        feed_fn: chunk path -> iterable of feed dicts (one per batch).
        fetch_list: vars fetched every step (first is reported as loss).
        checkpoint_dir: where params + the pass cursor persist.
        num_passes: total passes over the dataset.
        program / startup_program: default to the global programs.
    """

    def __init__(self, master: MasterService, executor, feed_fn: Callable,
                 fetch_list, checkpoint_dir: str, num_passes: int = 1,
                 program: Optional[Program] = None,
                 startup_program: Optional[Program] = None,
                 worker_id: str = "worker-0",
                 idle_wait: float = 0.05):
        self.master = master
        self.exe = executor
        self.feed_fn = feed_fn
        self.fetch_list = fetch_list
        self.ckpt_dir = checkpoint_dir
        self.num_passes = num_passes
        self.program = program or default_main_program()
        self.startup_program = startup_program or default_startup_program()
        self.worker_id = worker_id
        self.idle_wait = idle_wait
        self.pass_id = 0
        self.tasks_done = 0
        self.last_loss: Optional[float] = None

    # -- persistence ---------------------------------------------------
    def _meta_path(self) -> str:
        return os.path.join(self.ckpt_dir, _META)

    def _checkpoint(self) -> None:
        fluid_io.save_persistables(self.exe, self.ckpt_dir,
                                   main_program=self.program)
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"pass_id": self.pass_id,
                       "tasks_done": self.tasks_done}, f)
        os.replace(tmp, self._meta_path())

    def _resume(self) -> bool:
        if not os.path.exists(self._meta_path()):
            return False
        with open(self._meta_path()) as f:
            meta = json.load(f)
        fluid_io.load_persistables(self.exe, self.ckpt_dir,
                                   main_program=self.program)
        self.pass_id = int(meta["pass_id"])
        self.tasks_done = int(meta.get("tasks_done", 0))
        return True

    # -- the loop ------------------------------------------------------
    def train(self) -> None:
        """Run until num_passes complete.  Safe to call on a fresh
        process after a crash: params and the pass cursor come back from
        the checkpoint, unfinished work from the master's lease expiry."""
        if not self._resume():
            self.exe.run(self.startup_program)
        while self.pass_id < self.num_passes:
            try:
                task = self.master.get_task(self.pass_id)
            except PassBeforeError:
                # master rolled the pass past us (a checkpoint older than
                # the queue snapshot): catch up
                self.pass_id = self.master.counts()["cur_pass"]
                continue
            except PassAfterError:
                time.sleep(self.idle_wait)
                continue
            except NoMoreAvailableError:
                # pass draining: other workers hold the pending tasks (or
                # the master just rolled over)
                cur = self.master.counts()["cur_pass"]
                if cur > self.pass_id:
                    self.pass_id = cur
                    continue
                if cur >= self.num_passes:
                    return
                time.sleep(self.idle_wait)
                continue
            except AllTasksFailedError:
                raise RuntimeError(
                    f"pass {self.pass_id}: every task failed "
                    f"{self.master.failure_max}+ times; giving up"
                )
            try:
                for chunk in task.chunks:
                    for feed in self.feed_fn(chunk):
                        vals = self.exe.run(
                            program=self.program, feed=feed,
                            fetch_list=self.fetch_list,
                        )
                        if vals:
                            import numpy as np

                            self.last_loss = float(
                                np.ravel(np.asarray(vals[0]))[0]
                            )
            except Exception:
                # report and surface: the master re-queues immediately
                # instead of waiting for the lease to expire
                self.master.task_failed(task.id, task.epoch)
                raise
            # checkpoint BEFORE reporting: a crash between the two means the
            # lease expires and the task re-runs (at-least-once); the other
            # order would mark it done with its updates lost
            self.tasks_done += 1
            self._checkpoint()
            self.master.task_finished(task.id)
            self.master.heartbeat(self.worker_id)
            # master may have rolled the pass on our report
            cur = self.master.counts()["cur_pass"]
            if cur > self.pass_id:
                self.pass_id = cur
