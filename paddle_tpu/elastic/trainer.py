"""Checkpoint-restart elastic trainer
(reference roles: go/pserver periodic checkpoint + LoadCheckpoint
(service.go:346/:175) and the stateless v2 trainer pulling tasks from the
master; Fluid-side persistence via io.py save/load_persistables).

A worker is stateless between tasks: it leases a task from the
MasterService, trains over the task's chunks, reports completion, and
checkpoints params + its pass cursor.  Kill it at any point and a
restarted worker recovers the params from the checkpoint and the queue
from the master's snapshot — the leased task's timeout re-dispatches it.
That is the whole elasticity contract: add/remove workers freely, each
one runs this same loop.

Checkpoints are crash-atomic: each one is a fresh verified
`checkpoint_dir/step_N/` directory written through CheckpointManager
(manifest digests + write-then-rename LATEST pointer), with the pass
cursor riding in the manifest's `extra` — params and cursor commit
together, so a crash mid-save can never leave the cursor pointing at
half-new params (the old layout overwrote param files in place before
renaming the meta cursor).  Resume walks newest -> oldest past corrupt
or torn checkpoints.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

from .. import observability as _obs
from ..core.framework import (
    Program,
    default_main_program,
    default_startup_program,
)
from ..resilience.manager import CheckpointManager
from .master import (
    AllTasksFailedError,
    MasterService,
    NoMoreAvailableError,
    PassAfterError,
    PassBeforeError,
)

__all__ = ["ElasticTrainer"]


class ElasticTrainer:
    """Pull tasks, train, checkpoint; resume transparently after a crash.

    Args:
        master: the MasterService (or an RPC proxy with the same surface).
        executor: a fluid Executor.
        feed_fn: chunk path -> iterable of feed dicts (one per batch).
        fetch_list: vars fetched every step (first is reported as loss).
        checkpoint_dir: CheckpointManager run dir (params + pass cursor).
        num_passes: total passes over the dataset.
        program / startup_program: default to the global programs.
        keep_last: checkpoints retained by rotation GC.
        drain: optional resilience.PreemptionDrain; when its signal fires
            the trainer finishes the in-flight step, drains an emergency
            checkpoint, and returns cleanly WITHOUT reporting the leased
            task done — the lease timeout re-dispatches it (same
            at-least-once contract as a crash, minus the lost progress).
    """

    def __init__(self, master: MasterService, executor, feed_fn: Callable,
                 fetch_list, checkpoint_dir: str, num_passes: int = 1,
                 program: Optional[Program] = None,
                 startup_program: Optional[Program] = None,
                 worker_id: str = "worker-0",
                 idle_wait: float = 0.05,
                 keep_last: int = 3,
                 drain=None):
        self.master = master
        self.exe = executor
        self.feed_fn = feed_fn
        self.fetch_list = fetch_list
        self.ckpt_dir = checkpoint_dir
        self.num_passes = num_passes
        self.program = program or default_main_program()
        self.startup_program = startup_program or default_startup_program()
        self.worker_id = worker_id
        self.idle_wait = idle_wait
        self.drain = drain
        self.pass_id = 0
        self.tasks_done = 0
        self.last_loss: Optional[float] = None
        self.last_save = None  # AsyncCheckpoint of the newest _checkpoint
        # (its .stats carries the save/GC durations)
        self.ckpt = CheckpointManager(
            checkpoint_dir, keep_last=keep_last, program=self.program
        )
        # save-sequence counter, distinct from tasks_done: every save —
        # including a preemption drain arriving MID-task, after the last
        # completed task's checkpoint — gets a FRESH step dir, so the
        # previous valid checkpoint stays intact until the new one is
        # durable (a kill during the drain write must not tear it)
        self._ckpt_seq = 0

    # -- persistence ---------------------------------------------------
    def _checkpoint(self) -> None:
        # params AND the pass cursor commit in one verified checkpoint
        # (crash-atomic: a new step_N dir, LATEST flipped last); the
        # save/GC durations ride on the handle and the checkpoint metrics
        # (CheckpointManager._record_save)
        self._ckpt_seq += 1
        self.last_save = self.ckpt.save(
            self._ckpt_seq,
            extra={"pass_id": self.pass_id, "tasks_done": self.tasks_done},
        )

    def _resume(self) -> bool:
        res = self.ckpt.restore_or_init()
        if res is None:
            legacy = os.path.join(self.ckpt_dir, "elastic_meta.json")
            if os.path.exists(legacy):
                # a pre-resilience flat checkpoint (save_persistables
                # files + meta cursor): refusing beats silently
                # re-initializing trained params from scratch
                raise RuntimeError(
                    f"{self.ckpt_dir}: found a legacy flat checkpoint "
                    "(elastic_meta.json); this layout is no longer read. "
                    "Recover it explicitly with io.load_persistables + "
                    "the cursor in elastic_meta.json, or point the "
                    "trainer at a fresh checkpoint_dir."
                )
            return False
        extra = res.extra or {}
        self.pass_id = int(extra.get("pass_id", 0))
        self.tasks_done = int(extra.get("tasks_done", res.step))
        self._ckpt_seq = res.step
        return True

    def _drain_requested(self) -> bool:
        return self.drain is not None and self.drain.requested

    # -- the loop ------------------------------------------------------
    def train(self) -> None:
        """Run until num_passes complete.  Safe to call on a fresh
        process after a crash: params and the pass cursor come back from
        the newest VALID checkpoint (corrupt ones are skipped), unfinished
        work from the master's lease expiry."""
        if not self._resume():
            self.exe.run(self.startup_program)
        while self.pass_id < self.num_passes:
            if self._drain_requested():
                self._checkpoint()
                return
            try:
                task = self.master.get_task(self.pass_id)
            except PassBeforeError:
                # master rolled the pass past us (a checkpoint older than
                # the queue snapshot): catch up
                self.pass_id = self.master.counts()["cur_pass"]
                continue
            except PassAfterError:
                time.sleep(self.idle_wait)
                continue
            except NoMoreAvailableError:
                # pass draining: other workers hold the pending tasks (or
                # the master just rolled over)
                cur = self.master.counts()["cur_pass"]
                if cur > self.pass_id:
                    self.pass_id = cur
                    continue
                if cur >= self.num_passes:
                    return
                time.sleep(self.idle_wait)
                continue
            except AllTasksFailedError:
                raise RuntimeError(
                    f"pass {self.pass_id}: every task failed "
                    f"{self.master.failure_max}+ times; giving up"
                )
            draining = False
            try:
                with _obs.span("elastic.task", task=task.id,
                               pass_id=self.pass_id):
                    for chunk in task.chunks:
                        for feed in self.feed_fn(chunk):
                            vals = self.exe.run(
                                program=self.program, feed=feed,
                                fetch_list=self.fetch_list,
                            )
                            if vals:
                                import numpy as np

                                self.last_loss = float(
                                    np.ravel(np.asarray(vals[0]))[0]
                                )
                            if self._drain_requested():
                                # preemption notice: the in-flight step
                                # just finished; stop HERE and
                                # checkpoint below
                                draining = True
                                break
                        if draining:
                            break
            except Exception:
                # report and surface: the master re-queues immediately
                # instead of waiting for the lease to expire.  This also
                # covers the FLAGS_check_numerics NonFiniteStepError —
                # the checkpoint below never runs, so the poisoned task's
                # params (which the sentinel never wrote back anyway) are
                # not published; the lease machinery re-dispatches.
                _obs.default_registry().counter(
                    "paddle_tpu_elastic_tasks",
                    "elastic tasks by outcome",
                ).inc(outcome="failed")
                self.master.task_failed(task.id, task.epoch)
                raise
            if draining:
                # emergency checkpoint WITHOUT task_finished: the task's
                # lease expires and a surviving worker re-runs it
                # (at-least-once); params/cursor persist so the restart
                # is cheap
                _obs.default_registry().counter(
                    "paddle_tpu_elastic_drains",
                    "preemption drains that checkpointed and returned",
                ).inc()
                self._checkpoint()
                return
            # checkpoint BEFORE reporting: a crash between the two means the
            # lease expires and the task re-runs (at-least-once); the other
            # order would mark it done with its updates lost
            self.tasks_done += 1
            self._checkpoint()
            self.master.task_finished(task.id)
            self.master.heartbeat(self.worker_id)
            reg = _obs.default_registry()
            reg.counter(
                "paddle_tpu_elastic_tasks", "elastic tasks by outcome",
            ).inc(outcome="finished")
            if self.last_loss is not None:
                reg.gauge(
                    "paddle_tpu_elastic_last_loss",
                    "most recent fetched loss",
                ).set(self.last_loss, worker=self.worker_id)
            # master may have rolled the pass on our report
            cur = self.master.counts()["cur_pass"]
            if cur > self.pass_id:
                self.pass_id = cur
