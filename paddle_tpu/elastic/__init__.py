"""Failure detection / elastic training
(reference: the Go cloud stack — go/master/service.go task queue with
timeout re-dispatch + snapshot/recover, go/pserver etcd registration and
periodic checkpoints; Fluid itself has only RPC deadlines).

The TPU-native design is checkpoint-restart elasticity: a master leases
dataset tasks to stateless workers and re-dispatches them when a lease
times out (worker died); all persistent state — master queue snapshot,
model params, PS tables — checkpoints to a store so any process can be
killed and restarted without losing the pass.  On a TPU pod the "worker"
is a whole slice process group; slice-aware restart reduces to the same
protocol with the mesh re-built at startup (parallel/env.py).
"""

from .master import (
    AllTasksFailedError,
    FileStore,
    InMemStore,
    MasterService,
    NoMoreAvailableError,
    PassAfterError,
    PassBeforeError,
    Task,
    partition,
)
from .trainer import ElasticTrainer

__all__ = [
    "MasterService",
    "Task",
    "partition",
    "InMemStore",
    "FileStore",
    "ElasticTrainer",
    "PassBeforeError",
    "PassAfterError",
    "NoMoreAvailableError",
    "AllTasksFailedError",
]
