"""Cross-process transport for the elastic master.

The reference's Go master serves trainers over net/rpc with etcd state
(go/master/service.go:89; trainers call GetTask/TaskFinished/TaskFailed
remotely).  This is the same plane for `elastic.MasterService`: a
line-delimited JSON protocol over TCP (tasks are plain id/chunks/epoch
records — no arrays, no pickle), with master-side exceptions re-raised by
name on the client so worker code is identical in- and cross-process.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Optional

from .master import (
    AllTasksFailedError,
    NoMoreAvailableError,
    PassAfterError,
    PassBeforeError,
    Task,
)

__all__ = ["MasterServer", "RemoteMaster", "serve_master"]

_ERRORS = {
    "PassBeforeError": PassBeforeError,
    "PassAfterError": PassAfterError,
    "NoMoreAvailableError": NoMoreAvailableError,
    "AllTasksFailedError": AllTasksFailedError,
}


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        svc = self.server.master_service
        while True:
            line = self.rfile.readline()
            if not line:
                return
            try:
                req = json.loads(line.decode())
                cmd = req.get("cmd")
                if cmd == "get_task":
                    t = svc.get_task(int(req["pass_id"]))
                    resp = {"ok": True, "task": {
                        "id": t.id, "chunks": list(t.chunks),
                        "epoch": t.epoch}}
                elif cmd == "task_finished":
                    svc.task_finished(int(req["task_id"]))
                    resp = {"ok": True}
                elif cmd == "task_failed":
                    svc.task_failed(int(req["task_id"]), int(req["epoch"]))
                    resp = {"ok": True}
                elif cmd == "heartbeat":
                    svc.heartbeat(str(req["worker_id"]),
                                  req.get("payload"))
                    resp = {"ok": True}
                elif cmd == "forget_worker":
                    svc.forget_worker(str(req["worker_id"]))
                    resp = {"ok": True}
                elif cmd == "worker_status":
                    resp = {"ok": True, "workers": svc.worker_status()}
                elif cmd == "set_dataset":
                    svc.set_dataset(list(req["globs"]))
                    resp = {"ok": True}
                elif cmd == "counts":
                    resp = {"ok": True, "counts": svc.counts()}
                elif cmd == "config":
                    resp = {"ok": True,
                            "failure_max": svc.failure_max,
                            "chunks_per_task": svc.chunks_per_task}
                elif cmd == "dead_workers":
                    resp = {"ok": True, "workers": svc.dead_workers(
                        float(req["max_silence"]))}
                elif cmd == "shutdown":
                    resp = {"ok": True}
                    self.wfile.write(
                        (json.dumps(resp) + "\n").encode())

                    def _stop(srv=self.server):
                        srv.shutdown()
                        srv.server_close()  # release the listening fd

                    threading.Thread(target=_stop, daemon=True).start()
                    return
                else:
                    resp = {"ok": False, "error": "ValueError",
                            "message": f"unknown cmd {cmd!r}"}
            except tuple(_ERRORS.values()) as e:
                resp = {"ok": False, "error": type(e).__name__,
                        "message": str(e)}
            except Exception as e:  # noqa: BLE001 — surfaced to the client
                resp = {"ok": False, "error": "RuntimeError",
                        "message": f"{type(e).__name__}: {e}"}
            self.wfile.write((json.dumps(resp) + "\n").encode())


class MasterServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _Handler)
        self.master_service = service

    @property
    def endpoint(self) -> str:
        h, p = self.server_address
        return f"{h}:{p}"


def serve_master(service, host: str = "127.0.0.1", port: int = 0):
    srv = MasterServer(service, host, port)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class RemoteMaster:
    """Client-side MasterService facade — same methods, same exceptions.

    Transient transport failures (master restart, dropped connection,
    connect refused while the master comes back up) are absorbed by
    bounded exponential backoff + jitter around each call, reconnecting
    each attempt — a master restart must not kill workers.  Master-side
    protocol errors (PassBefore/After, NoMoreAvailable, ...) are NOT
    retried; they re-raise by name as before.  A retried `get_task` whose
    response was lost may double-lease a task; the orphaned lease times
    out and re-queues — the queue's at-least-once contract already
    covers it.

    Retry accounting is surfaced instead of dropped: `retry_stats` holds
    the running totals ({"calls", "retries", "backoff_s"}) and
    `last_call_retries` the most recent call's retry count; with
    FLAGS_observability on each transient failure also lands on the
    `paddle_tpu_resilience_retries{label="elastic.rpc", ...}` counter."""

    def __init__(self, endpoint: str, timeout: float = 120.0,
                 max_retries: int = 5, retry_base_delay: float = 0.05,
                 retry_max_delay: float = 2.0):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self._max_retries = max_retries
        self._retry_base_delay = retry_base_delay
        self._retry_max_delay = retry_max_delay
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._stats_lock = threading.Lock()
        self.retry_stats = {"calls": 0, "retries": 0, "backoff_s": 0.0}
        self.last_call_retries = 0

    def _call_once(self, req: dict) -> dict:
        from ..resilience import faultinject

        faultinject.rpc_drop(req.get("cmd"))  # no-op unless armed
        with self._lock:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
                self._rfile = self._sock.makefile("rb")
            try:
                self._sock.sendall((json.dumps(req) + "\n").encode())
                line = self._rfile.readline()
                if not line:
                    raise ConnectionError("master closed the connection")
            except BaseException:
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                    self._rfile = None
                raise
        resp = json.loads(line.decode())
        if not resp.get("ok"):
            exc = _ERRORS.get(resp.get("error"), RuntimeError)
            raise exc(resp.get("message", ""))
        return resp

    def _call(self, req: dict) -> dict:
        from ..resilience.retry import retry_with_backoff

        stats: dict = {}
        try:
            return retry_with_backoff(
                lambda: self._call_once(req),
                retries=self._max_retries,
                base_delay=self._retry_base_delay,
                max_delay=self._retry_max_delay,
                retry_on=(ConnectionError, TimeoutError, OSError),
                stats=stats,
                label="elastic.rpc",
            )
        finally:
            # accumulate even when retries are exhausted: the raised
            # call's attempts are part of the proxy's story
            with self._stats_lock:
                self.retry_stats["calls"] += 1
                self.retry_stats["retries"] += stats.get("retries", 0)
                self.retry_stats["backoff_s"] += stats.get("backoff_s", 0.0)
                self.last_call_retries = stats.get("retries", 0)

    def set_dataset(self, globs) -> None:
        self._call({"cmd": "set_dataset", "globs": list(globs)})

    def get_task(self, pass_id: int) -> Task:
        t = self._call({"cmd": "get_task", "pass_id": pass_id})["task"]
        return Task(t["id"], list(t["chunks"]), t["epoch"])

    def task_finished(self, task_id: int) -> None:
        self._call({"cmd": "task_finished", "task_id": task_id})

    def task_failed(self, task_id: int, epoch: int) -> None:
        self._call({"cmd": "task_failed", "task_id": task_id,
                    "epoch": epoch})

    def heartbeat(self, worker_id: str,
                  payload: Optional[dict] = None) -> None:
        req = {"cmd": "heartbeat", "worker_id": worker_id}
        if payload is not None:  # wire-compatible with older masters
            req["payload"] = payload
        self._call(req)

    def forget_worker(self, worker_id: str) -> None:
        self._call({"cmd": "forget_worker", "worker_id": worker_id})

    def worker_status(self) -> dict:
        return self._call({"cmd": "worker_status"})["workers"]

    def dead_workers(self, max_silence: float):
        return self._call({"cmd": "dead_workers",
                           "max_silence": max_silence})["workers"]

    def counts(self) -> dict:
        return self._call({"cmd": "counts"})["counts"]

    @property
    def failure_max(self) -> int:
        # ElasticTrainer reads master.failure_max for its give-up message
        if not hasattr(self, "_failure_max"):
            self._failure_max = int(
                self._call({"cmd": "config"})["failure_max"])
        return self._failure_max

    def shutdown_server(self) -> None:
        self._call({"cmd": "shutdown"})
        self.close()

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                    self._rfile = None
