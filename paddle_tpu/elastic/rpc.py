"""Cross-process transport for the elastic master and the fleet.

The reference's Go master serves trainers over net/rpc with etcd state
(go/master/service.go:89; trainers call GetTask/TaskFinished/TaskFailed
remotely).  This is the same plane for `elastic.MasterService`: a
line-delimited JSON protocol over TCP (tasks are plain id/chunks/epoch
records — no arrays, no pickle), with master-side exceptions re-raised by
name on the client so worker code is identical in- and cross-process.

The fleet's DATA plane (serving/fleet/proc.py) rides a second,
length-prefixed sub-protocol on the same TCP machinery: line-JSON cannot
carry numpy, but a `SeqExport` handoff payload pickles, so frames are
``b"PTF1" + !Q length + pickle``.  `FrameServer` dispatches
``{"verb", "args"}`` request frames; `FrameClient` wraps every verb in
per-call timeouts plus `resilience.retry` bounded backoff.  A short read
anywhere — a peer SIGKILLed mid-write — surfaces as `FrameError`, a
`ConnectionError` subclass, so one `retry_on` tuple covers refused
connects, resets, timeouts, and half-written frames alike.  Server-side
exceptions re-raise by NAME on the client via `register_error`, the
frame plane's extensible `_ERRORS` map.
"""

from __future__ import annotations

import json
import pickle
import socket
import socketserver
import struct
import threading
from typing import Callable, Dict, Optional, Type

from .master import (
    AllTasksFailedError,
    NoMoreAvailableError,
    PassAfterError,
    PassBeforeError,
    Task,
)

__all__ = [
    "MasterServer", "RemoteMaster", "serve_master",
    "FrameError", "FrameClient", "FrameServer", "serve_frames",
    "read_frame", "write_frame", "register_error",
]

_ERRORS = {
    "PassBeforeError": PassBeforeError,
    "PassAfterError": PassAfterError,
    "NoMoreAvailableError": NoMoreAvailableError,
    "AllTasksFailedError": AllTasksFailedError,
}


def _send_line(wfile, resp: dict) -> bool:
    """Write one JSON response line; False when the armed mid-write
    truncate fault fired (the handler must then drop the connection so
    the client sees a half-written line, not a clean close)."""
    from ..resilience import faultinject

    data = (json.dumps(resp) + "\n").encode()
    if faultinject.rpc_truncate():
        wfile.write(data[: max(1, len(data) // 2)])
        wfile.flush()
        return False
    wfile.write(data)
    return True


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        svc = self.server.master_service
        while True:
            line = self.rfile.readline()
            if not line:
                return
            try:
                req = json.loads(line.decode())
                cmd = req.get("cmd")
                if cmd == "get_task":
                    t = svc.get_task(int(req["pass_id"]))
                    resp = {"ok": True, "task": {
                        "id": t.id, "chunks": list(t.chunks),
                        "epoch": t.epoch}}
                elif cmd == "task_finished":
                    svc.task_finished(int(req["task_id"]))
                    resp = {"ok": True}
                elif cmd == "task_failed":
                    svc.task_failed(int(req["task_id"]), int(req["epoch"]))
                    resp = {"ok": True}
                elif cmd == "heartbeat":
                    svc.heartbeat(str(req["worker_id"]),
                                  req.get("payload"))
                    resp = {"ok": True}
                elif cmd == "forget_worker":
                    svc.forget_worker(str(req["worker_id"]))
                    resp = {"ok": True}
                elif cmd == "worker_status":
                    resp = {"ok": True, "workers": svc.worker_status()}
                elif cmd == "set_dataset":
                    svc.set_dataset(list(req["globs"]))
                    resp = {"ok": True}
                elif cmd == "counts":
                    resp = {"ok": True, "counts": svc.counts()}
                elif cmd == "config":
                    resp = {"ok": True,
                            "failure_max": svc.failure_max,
                            "chunks_per_task": svc.chunks_per_task}
                elif cmd == "dead_workers":
                    resp = {"ok": True, "workers": svc.dead_workers(
                        float(req["max_silence"]))}
                elif cmd == "shutdown":
                    resp = {"ok": True}
                    _send_line(self.wfile, resp)

                    def _stop(srv=self.server):
                        srv.shutdown()
                        srv.server_close()  # release the listening fd

                    threading.Thread(target=_stop, daemon=True).start()
                    return
                else:
                    resp = {"ok": False, "error": "ValueError",
                            "message": f"unknown cmd {cmd!r}"}
            except tuple(_ERRORS.values()) as e:
                resp = {"ok": False, "error": type(e).__name__,
                        "message": str(e)}
            except Exception as e:  # noqa: BLE001 — surfaced to the client
                resp = {"ok": False, "error": "RuntimeError",
                        "message": f"{type(e).__name__}: {e}"}
            if not _send_line(self.wfile, resp):
                return


class MasterServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _Handler)
        self.master_service = service

    @property
    def endpoint(self) -> str:
        h, p = self.server_address
        return f"{h}:{p}"


def serve_master(service, host: str = "127.0.0.1", port: int = 0):
    srv = MasterServer(service, host, port)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class RemoteMaster:
    """Client-side MasterService facade — same methods, same exceptions.

    Transient transport failures (master restart, dropped connection,
    connect refused while the master comes back up) are absorbed by
    bounded exponential backoff + jitter around each call, reconnecting
    each attempt — a master restart must not kill workers.  Master-side
    protocol errors (PassBefore/After, NoMoreAvailable, ...) are NOT
    retried; they re-raise by name as before.  A retried `get_task` whose
    response was lost may double-lease a task; the orphaned lease times
    out and re-queues — the queue's at-least-once contract already
    covers it.

    Retry accounting is surfaced instead of dropped: `retry_stats` holds
    the running totals ({"calls", "retries", "backoff_s"}) and
    `last_call_retries` the most recent call's retry count; with
    FLAGS_observability on each transient failure also lands on the
    `paddle_tpu_resilience_retries{label="elastic.rpc", ...}` counter."""

    def __init__(self, endpoint: str, timeout: float = 120.0,
                 max_retries: int = 5, retry_base_delay: float = 0.05,
                 retry_max_delay: float = 2.0):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self._max_retries = max_retries
        self._retry_base_delay = retry_base_delay
        self._retry_max_delay = retry_max_delay
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._stats_lock = threading.Lock()
        self.retry_stats = {"calls": 0, "retries": 0, "backoff_s": 0.0}
        self.last_call_retries = 0

    def _call_once(self, req: dict) -> dict:
        from ..resilience import faultinject

        faultinject.rpc_drop(req.get("cmd"))  # no-op unless armed
        with self._lock:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
                self._rfile = self._sock.makefile("rb")
            try:
                self._sock.sendall((json.dumps(req) + "\n").encode())
                line = self._rfile.readline()
                if not line:
                    raise ConnectionError("master closed the connection")
                if not line.endswith(b"\n"):
                    # A peer killed mid-write leaves a half line; it must
                    # surface typed+retryable, never as json's ValueError.
                    raise FrameError(
                        f"partial response from master ({len(line)} bytes,"
                        " no terminator) — peer died mid-write")
            except BaseException:
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                    self._rfile = None
                raise
        resp = json.loads(line.decode())
        if not resp.get("ok"):
            exc = _ERRORS.get(resp.get("error"), RuntimeError)
            raise exc(resp.get("message", ""))
        return resp

    def _call(self, req: dict) -> dict:
        from ..resilience.retry import retry_with_backoff

        stats: dict = {}
        try:
            return retry_with_backoff(
                lambda: self._call_once(req),
                retries=self._max_retries,
                base_delay=self._retry_base_delay,
                max_delay=self._retry_max_delay,
                retry_on=(ConnectionError, TimeoutError, OSError),
                stats=stats,
                label="elastic.rpc",
            )
        finally:
            # accumulate even when retries are exhausted: the raised
            # call's attempts are part of the proxy's story
            with self._stats_lock:
                self.retry_stats["calls"] += 1
                self.retry_stats["retries"] += stats.get("retries", 0)
                self.retry_stats["backoff_s"] += stats.get("backoff_s", 0.0)
                self.last_call_retries = stats.get("retries", 0)

    def set_dataset(self, globs) -> None:
        self._call({"cmd": "set_dataset", "globs": list(globs)})

    def get_task(self, pass_id: int) -> Task:
        t = self._call({"cmd": "get_task", "pass_id": pass_id})["task"]
        return Task(t["id"], list(t["chunks"]), t["epoch"])

    def task_finished(self, task_id: int) -> None:
        self._call({"cmd": "task_finished", "task_id": task_id})

    def task_failed(self, task_id: int, epoch: int) -> None:
        self._call({"cmd": "task_failed", "task_id": task_id,
                    "epoch": epoch})

    def heartbeat(self, worker_id: str,
                  payload: Optional[dict] = None) -> None:
        req = {"cmd": "heartbeat", "worker_id": worker_id}
        if payload is not None:  # wire-compatible with older masters
            req["payload"] = payload
        self._call(req)

    def forget_worker(self, worker_id: str) -> None:
        self._call({"cmd": "forget_worker", "worker_id": worker_id})

    def worker_status(self) -> dict:
        return self._call({"cmd": "worker_status"})["workers"]

    def dead_workers(self, max_silence: float):
        return self._call({"cmd": "dead_workers",
                           "max_silence": max_silence})["workers"]

    def counts(self) -> dict:
        return self._call({"cmd": "counts"})["counts"]

    @property
    def failure_max(self) -> int:
        # ElasticTrainer reads master.failure_max for its give-up message
        if not hasattr(self, "_failure_max"):
            self._failure_max = int(
                self._call({"cmd": "config"})["failure_max"])
        return self._failure_max

    def shutdown_server(self) -> None:
        self._call({"cmd": "shutdown"})
        self.close()

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                    self._rfile = None


# -- framed binary sub-protocol (the fleet's data plane) ---------------------
#
# Frame layout:  b"PTF1" | !Q payload length | pickle(payload)
# Request:       {"verb": str, "args": dict}
# Response:      {"ok": True, "result": ...}
#            or  {"ok": False, "error": <class name>, "message": str}

FRAME_MAGIC = b"PTF1"
_FRAME_HEADER = struct.Struct("!Q")
MAX_FRAME_BYTES = 1 << 31  # 2 GiB — far above any handoff payload


class FrameError(ConnectionError):
    """A frame could not be read or written whole (short read, bad
    magic, oversized length): the peer died mid-frame or the stream is
    desynchronized.  Subclasses ConnectionError so the standard
    `retry_on=(ConnectionError, TimeoutError, OSError)` tuple retries
    it after a reconnect."""


class _FrameTruncated(Exception):
    """Internal: the armed truncate fault cut a response mid-write; the
    server handler must drop the connection without a traceback."""


# Frame-plane error registry: server-side exceptions cross the socket as
# (class name, message) and re-raise by NAME here, exactly like the
# line-JSON `_ERRORS` map — but extensible, so layers above elastic/
# (serving.fleet's typed replica errors) can register theirs without an
# import inversion.
_FRAME_ERRORS: Dict[str, Type[BaseException]] = {
    **_ERRORS,
    "ValueError": ValueError,
    "KeyError": KeyError,
    "RuntimeError": RuntimeError,
    "TimeoutError": TimeoutError,
    "NotImplementedError": NotImplementedError,
}


def register_error(cls: Type[BaseException]) -> Type[BaseException]:
    """Register an exception class for by-name re-raise on FrameClient.
    Returns the class, so it works as a decorator."""
    _FRAME_ERRORS[cls.__name__] = cls
    return cls


def _read_exact(rfile, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            raise FrameError(
                f"short read: wanted {n} bytes, got {len(buf)} before EOF"
                " — peer died mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def read_frame(rfile):
    """Read one length-prefixed pickle frame; raises FrameError on any
    torn/garbled stream (including EOF mid-frame)."""
    header = _read_exact(rfile, len(FRAME_MAGIC) + _FRAME_HEADER.size)
    if header[: len(FRAME_MAGIC)] != FRAME_MAGIC:
        raise FrameError(f"bad frame magic {header[:4]!r}")
    (length,) = _FRAME_HEADER.unpack(header[len(FRAME_MAGIC):])
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds cap")
    payload = _read_exact(rfile, length)
    try:
        return pickle.loads(payload)
    except Exception as e:  # noqa: BLE001 — a torn pickle is a torn frame
        raise FrameError(f"undecodable frame payload: {e}") from e


def write_frame(wfile, obj, _allow_truncate_fault: bool = False) -> None:
    """Write one frame.  With `_allow_truncate_fault` (server response
    path only) an armed FAULT_RPC_TRUNCATE_ONCE cuts the write in half
    and raises `_FrameTruncated` so the handler drops the connection —
    the client must see a typed, retryable half-frame."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    data = FRAME_MAGIC + _FRAME_HEADER.pack(len(payload)) + payload
    if _allow_truncate_fault:
        from ..resilience import faultinject

        if faultinject.rpc_truncate():
            wfile.write(data[: max(1, len(data) // 2)])
            wfile.flush()
            raise _FrameTruncated()
    wfile.write(data)
    wfile.flush()


class _FrameHandler(socketserver.StreamRequestHandler):
    def handle(self):
        dispatch = self.server.dispatch
        while True:
            try:
                req = read_frame(self.rfile)
            except FrameError:
                return  # peer gone or stream torn — drop the connection
            try:
                result = dispatch(req.get("verb"), **(req.get("args") or {}))
                resp = {"ok": True, "result": result}
            except Exception as e:  # noqa: BLE001 — surfaced to the client
                resp = {"ok": False, "error": type(e).__name__,
                        "message": str(e)}
            try:
                write_frame(self.wfile, resp, _allow_truncate_fault=True)
            except _FrameTruncated:
                return
            except OSError:
                return
            if resp.get("ok") and isinstance(resp.get("result"), dict) \
                    and resp["result"].get("__close__"):
                return


class FrameServer(socketserver.ThreadingTCPServer):
    """Threaded frame-protocol server around a `dispatch(verb, **kwargs)`
    callable.  Each connection is a long-lived request/response stream;
    dispatch exceptions cross the socket typed by name."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, dispatch: Callable, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__((host, port), _FrameHandler)
        self.dispatch = dispatch

    @property
    def endpoint(self) -> str:
        h, p = self.server_address
        return f"{h}:{p}"


def serve_frames(dispatch: Callable, host: str = "127.0.0.1",
                 port: int = 0) -> FrameServer:
    srv = FrameServer(dispatch, host, port)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name="frame-server").start()
    return srv


class FrameClient:
    """One persistent frame-protocol connection with the same transport
    contract as `RemoteMaster`: lazy connect, per-verb timeout override,
    close-and-reconnect on ANY failure, bounded backoff around transient
    transport errors, and retry accounting in `retry_stats`.  Retrying a
    verb whose response was lost re-sends the request, so verbs must be
    idempotent (the fleet's submit dedups on a client-minted request id;
    collect is ack-based)."""

    def __init__(self, endpoint: str, timeout: float = 30.0,
                 max_retries: int = 3, retry_base_delay: float = 0.05,
                 retry_max_delay: float = 0.5):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self._max_retries = max_retries
        self._retry_base_delay = retry_base_delay
        self._retry_max_delay = retry_max_delay
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._wfile = None
        self._stats_lock = threading.Lock()
        self.retry_stats = {"calls": 0, "retries": 0, "backoff_s": 0.0}
        self.last_call_retries = 0

    def _call_once(self, verb: str, args: dict, timeout: float):
        from ..resilience import faultinject

        faultinject.rpc_drop(verb)  # no-op unless armed
        with self._lock:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self._addr, timeout=timeout)
                self._rfile = self._sock.makefile("rb")
                self._wfile = self._sock.makefile("wb")
            try:
                self._sock.settimeout(timeout)
                write_frame(self._wfile, {"verb": verb, "args": args})
                resp = read_frame(self._rfile)
            except BaseException:
                self._close_locked()
                raise
        if not resp.get("ok"):
            exc = _FRAME_ERRORS.get(resp.get("error"), RuntimeError)
            raise exc(resp.get("message", ""))
        return resp.get("result")

    def call(self, verb: str, timeout: Optional[float] = None,
             retry: bool = True, **args):
        """Invoke `verb` on the peer.  `timeout` overrides the client
        default for this verb only (slow verbs: drain, swap_params);
        `retry=False` makes exactly one attempt (fire-and-forget verbs
        like shutdown)."""
        from ..resilience.retry import retry_with_backoff

        t = self._timeout if timeout is None else timeout
        if not retry:
            return self._call_once(verb, args, t)
        stats: dict = {}
        try:
            return retry_with_backoff(
                lambda: self._call_once(verb, args, t),
                retries=self._max_retries,
                base_delay=self._retry_base_delay,
                max_delay=self._retry_max_delay,
                retry_on=(ConnectionError, TimeoutError, OSError),
                stats=stats,
                label="fleet.rpc",
            )
        finally:
            with self._stats_lock:
                self.retry_stats["calls"] += 1
                self.retry_stats["retries"] += stats.get("retries", 0)
                self.retry_stats["backoff_s"] += stats.get("backoff_s", 0.0)
                self.last_call_retries = stats.get("retries", 0)

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._rfile = None
                self._wfile = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()
