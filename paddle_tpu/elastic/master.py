"""Elastic master: dataset task queue with failure detection
(reference: go/master/service.go — Service.GetTask :366 leases a task and
arms a timeout, processFailedTask :311 re-queues it up to failureMax,
TaskFinished :410 rolls the pass over, snapshot/recover :166-229 persist
the queue state to etcd).

Differences from the Go original, by design:
- The store is pluggable (in-memory for tests, a file for single-host,
  anything with save/load for cluster use); etcd is not assumed.
- Tasks carry file paths + a chunk index range instead of recordio chunk
  descriptors; any sharded dataset works.
- Timeout checks run on threading.Timer (the Go version's AfterFunc) and
  liveness is lease-based: a worker that dies simply never finishes its
  task, and the lease expiry re-queues it.  An explicit heartbeat
  registry is layered on top for faster detection (the pserver etcd
  registration role, go/pserver/etcd_client.go).
"""

from __future__ import annotations

import glob as globlib
import gzip
import pickle
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

__all__ = [
    "Task", "partition", "MasterService", "InMemStore", "FileStore",
    "PassBeforeError", "PassAfterError", "NoMoreAvailableError",
    "AllTasksFailedError",
]


class PassBeforeError(Exception):
    """Client's pass count is behind the master's (ErrPassBefore)."""


class PassAfterError(Exception):
    """Client ran ahead of the master's pass (ErrPassAfter) — retry later."""


class NoMoreAvailableError(Exception):
    """All tasks of this pass are leased or done (ErrNoMoreAvailable)."""


class AllTasksFailedError(Exception):
    """Every task failed permanently this pass (ErrAllTaskFailed)."""


@dataclass
class Task:
    id: int
    chunks: List[str]
    epoch: int = 0  # bumped on every (re-)dispatch; stale reports ignored


@dataclass
class _TaskEntry:
    task: Task
    num_failure: int = 0


@dataclass
class _MasterState:
    todo: List[_TaskEntry] = field(default_factory=list)
    pending: Dict[int, _TaskEntry] = field(default_factory=dict)
    done: List[_TaskEntry] = field(default_factory=list)
    failed: List[_TaskEntry] = field(default_factory=list)
    cur_pass: int = 0


def partition(chunks: Sequence[str], chunks_per_task: int) -> List[_TaskEntry]:
    """Group chunks into tasks (reference: service.go partition :106)."""
    if chunks_per_task <= 0:
        chunks_per_task = 1
    entries: List[_TaskEntry] = []
    for i in range(0, len(chunks), chunks_per_task):
        entries.append(_TaskEntry(
            task=Task(id=len(entries), chunks=list(chunks[i:i + chunks_per_task]))
        ))
    return entries


class InMemStore:
    """The Go test double (go/master/inmem_store.go)."""

    def __init__(self):
        self._buf: Optional[bytes] = None
        self._lock = threading.Lock()

    def save(self, state: bytes) -> None:
        with self._lock:
            self._buf = state

    def load(self) -> Optional[bytes]:
        with self._lock:
            return self._buf


class FileStore:
    """Snapshot to a local file (the etcd role for single-host jobs)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def save(self, state: bytes) -> None:
        import os

        tmp = self.path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(state)
            os.replace(tmp, self.path)  # atomic: a crash never half-writes

    def load(self) -> Optional[bytes]:
        with self._lock:
            try:
                with open(self.path, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                return None


class MasterService:
    """Task-queue master with lease timeouts and snapshot/recover."""

    def __init__(self, store, chunks_per_task: int = 1,
                 timeout_dur: float = 60.0, failure_max: int = 3):
        self.chunks_per_task = chunks_per_task
        self.timeout_dur = timeout_dur
        self.failure_max = failure_max
        self.store = store
        self._mu = threading.Lock()
        self._state = _MasterState()
        self._init_done = False
        self._timers: List[threading.Timer] = []
        self._heartbeats: Dict[str, float] = {}
        # optional per-worker status dict delivered with the beat — the
        # serving fleet's control-plane signals (queue depth, shed
        # counts, health state) ride the same liveness RPC
        self._payloads: Dict[str, dict] = {}
        if self._recover():
            self._init_done = True

    # -- persistence ---------------------------------------------------
    def _snapshot_locked(self) -> None:
        buf = gzip.compress(pickle.dumps(self._state))
        self.store.save(buf)

    def _recover(self) -> bool:
        raw = self.store.load()
        if raw is None:
            return False
        self._state = pickle.loads(gzip.decompress(raw))
        # re-arm timeout checks for tasks that were leased when the
        # previous master died (service.go recover :196)
        for entry in self._state.pending.values():
            self._arm_timeout(entry.task.id, entry.task.epoch)
        return True

    def _arm_timeout(self, task_id: int, epoch: int) -> None:
        t = threading.Timer(
            self.timeout_dur, self._check_timeout, args=(task_id, epoch)
        )
        t.daemon = True
        t.start()
        # prune fired timers so a long job doesn't accumulate one dead
        # Timer object per lease
        self._timers = [x for x in self._timers if x.is_alive()]
        self._timers.append(t)

    def _check_timeout(self, task_id: int, epoch: int) -> None:
        with self._mu:
            entry = self._state.pending.get(task_id)
            if entry is None:
                return
            self._process_failed_locked(entry, epoch)

    # -- dataset -------------------------------------------------------
    def set_dataset(self, glob_paths: Sequence[str]) -> None:
        """Partition matching files into tasks.  Only the first call is
        honored — every trainer calls this (service.go SetDataset :275)."""
        if not glob_paths:
            raise ValueError("no dataset specified")
        with self._mu:
            if self._init_done:
                return
            paths: List[str] = []
            for g in glob_paths:
                paths.extend(sorted(globlib.glob(g)))
            if not paths:
                raise ValueError("no valid dataset specified")
            self._state.todo = partition(paths, self.chunks_per_task)
            self._snapshot_locked()
            self._init_done = True

    # -- task protocol -------------------------------------------------
    def get_task(self, pass_id: int) -> Task:
        """Lease the next task (service.go GetTask :366).  Raises
        PassBefore/PassAfter for pass skew, NoMoreAvailable when the pass
        is draining, AllTasksFailed when nothing survived."""
        with self._mu:
            if not self._init_done:
                raise NoMoreAvailableError("dataset not set")
            st = self._state
            if pass_id < st.cur_pass:
                raise PassBeforeError(f"{pass_id} < master {st.cur_pass}")
            if pass_id > st.cur_pass:
                raise PassAfterError(f"{pass_id} > master {st.cur_pass}")
            if not st.todo:
                if not st.done and not st.pending:
                    raise AllTasksFailedError()
                raise NoMoreAvailableError()
            entry = st.todo.pop(0)
            entry.task.epoch += 1
            st.pending[entry.task.id] = entry
            self._snapshot_locked()
            self._arm_timeout(entry.task.id, entry.task.epoch)
            return Task(entry.task.id, list(entry.task.chunks),
                        entry.task.epoch)

    def task_finished(self, task_id: int) -> None:
        """Report success; rolls the pass when the queue drains
        (service.go TaskFinished :410)."""
        with self._mu:
            st = self._state
            entry = st.pending.pop(task_id, None)
            if entry is None:
                return  # stale report (already timed out and re-queued)
            entry.num_failure = 0
            st.done.append(entry)
            self._maybe_rollover_locked()
            self._snapshot_locked()

    def task_failed(self, task_id: int, epoch: int) -> None:
        """Report failure; re-queues up to failure_max then discards
        (service.go TaskFailed :452 -> processFailedTask :311)."""
        with self._mu:
            entry = self._state.pending.get(task_id)
            if entry is None:
                return
            self._process_failed_locked(entry, epoch)

    def _process_failed_locked(self, entry: _TaskEntry, epoch: int) -> None:
        if entry.task.epoch != epoch:
            return  # this lease was already re-dispatched; stale check
        self._state.pending.pop(entry.task.id, None)
        entry.num_failure += 1
        if entry.num_failure > self.failure_max:
            self._state.failed.append(entry)
            # the discarded task may have been the last outstanding work of
            # this pass — roll over, or workers idle-loop forever
            self._maybe_rollover_locked()
        else:
            self._state.todo.append(entry)
        self._snapshot_locked()

    def _maybe_rollover_locked(self) -> None:
        """Advance the pass when nothing is left to lease or report; failed
        tasks get another shot next pass (service.go TaskFinished :438).
        If *everything* failed, leave the state as-is so get_task raises
        AllTasksFailedError instead of silently spinning passes."""
        st = self._state
        if st.todo or st.pending or not st.done:
            return
        st.cur_pass += 1
        st.todo = st.done + st.failed
        st.done = []
        st.failed = []

    # -- liveness ------------------------------------------------------
    def heartbeat(self, worker_id: str,
                  payload: Optional[dict] = None) -> None:
        """Optional fast failure detection on top of lease expiry
        (the pserver etcd-registration role).  ``payload`` piggybacks a
        small status dict on the beat (the serving fleet reports queue
        depth / shed rate / health state this way); omitted payloads
        leave the previous one in place."""
        with self._mu:
            self._heartbeats[worker_id] = time.monotonic()
            if payload is not None:
                self._payloads[worker_id] = dict(payload)

    def dead_workers(self, max_silence: float) -> List[str]:
        now = time.monotonic()
        with self._mu:
            return [w for w, t in self._heartbeats.items()
                    if now - t > max_silence]

    def forget_worker(self, worker_id: str) -> None:
        """Drop a worker from the liveness registry — the deregister
        half of heartbeat().  Without it a deliberately-removed worker
        (a drained serving replica) reports lease-expired in every
        later dead_workers() poll forever (the ghost-lease bug)."""
        with self._mu:
            self._heartbeats.pop(worker_id, None)
            self._payloads.pop(worker_id, None)

    def worker_status(self) -> Dict[str, dict]:
        """Every registered worker's beat age and latest payload —
        the fleet controller's signal read, one call for the whole
        fleet (works identically over the RPC plane)."""
        now = time.monotonic()
        with self._mu:
            return {w: {"age_s": now - t,
                        "payload": self._payloads.get(w)}
                    for w, t in self._heartbeats.items()}

    # -- introspection -------------------------------------------------
    def counts(self) -> dict:
        with self._mu:
            st = self._state
            return {
                "todo": len(st.todo), "pending": len(st.pending),
                "done": len(st.done), "failed": len(st.failed),
                "cur_pass": st.cur_pass,
            }

    def shutdown(self) -> None:
        for t in self._timers:
            t.cancel()
        self._timers = []
