"""Device mesh construction and the sharding vocabulary.

The reference addresses devices as a flat list of places cloned into an SSA
graph (multi_devices_graph_pass.cc:386); the TPU-native model is a named
logical mesh over the chip slice.  Axis names used across the framework:

    dp  - data parallel (batch dim)
    tp  - tensor/model parallel (hidden dims)
    pp  - pipeline parallel (layer stages)
    sp  - sequence/context parallel (sequence dim, ring attention)
    ep  - expert parallel

A `DeviceMesh` wraps `jax.sharding.Mesh` and converts per-variable logical
sharding specs (lists of axis names, stored on VarDesc.sharding) into
`NamedSharding`s.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["DeviceMesh", "make_mesh", "default_mesh", "mesh_guard",
           "AXIS_DP", "AXIS_TP", "AXIS_PP", "AXIS_SP", "AXIS_EP"]

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_PP = "pp"
AXIS_SP = "sp"
AXIS_EP = "ep"


class DeviceMesh:
    """Named logical mesh over a set of JAX devices."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    @property
    def axis_names(self):
        return tuple(self.mesh.axis_names)

    @property
    def shape(self) -> Dict[str, int]:
        return dict(self.mesh.shape)

    @property
    def num_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values()))) if self.mesh.shape else 1

    def axis_size(self, name: str) -> int:
        return self.mesh.shape.get(name, 1)

    def has_axis(self, name: str) -> bool:
        return name in self.mesh.axis_names

    # -- sharding construction ----------------------------------------------
    def spec(self, logical: Optional[Sequence[Any]]) -> PartitionSpec:
        """logical: per-dim entry of None / axis-name / tuple of axis names.
        Axes absent from this mesh degrade to replication, so one program
        text runs on any mesh shape (the reference re-transpiles instead)."""
        if logical is None:
            return PartitionSpec()
        dims = []
        for entry in logical:
            if entry is None:
                dims.append(None)
            elif isinstance(entry, (list, tuple)):
                present = tuple(a for a in entry if self.has_axis(a))
                dims.append(present if present else None)
            else:
                dims.append(entry if self.has_axis(entry) else None)
        while dims and dims[-1] is None:
            dims.pop()
        return PartitionSpec(*dims)

    def sharding(self, logical: Optional[Sequence[Any]]) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec(logical))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def batch_sharding(self, batch_axis: str = AXIS_DP) -> NamedSharding:
        """Default feed sharding: dim 0 over the data axis when present."""
        if not self.has_axis(batch_axis):
            return self.replicated()
        return NamedSharding(self.mesh, PartitionSpec(batch_axis))

    def __enter__(self):
        self._cm = self.mesh
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        return self._cm.__exit__(*exc)

    def __repr__(self):
        return f"DeviceMesh({self.shape})"


def make_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[Any]] = None,
) -> DeviceMesh:
    """Build a DeviceMesh.  `axes` maps axis name -> size; a -1 size (at most
    one) absorbs all remaining devices.  Default: pure data parallel over all
    local devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None or not axes:
        axes = {AXIS_DP: n}
    names = list(axes)
    sizes = [int(s) for s in axes.values()]
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1])) or 1
        if n % known:
            raise ValueError(f"{n} devices not divisible by fixed axes {axes}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, have {n}")
    dev_array = np.asarray(devices).reshape(sizes)
    return DeviceMesh(Mesh(dev_array, axis_names=tuple(names)))


_default_mesh: Optional[DeviceMesh] = None


def default_mesh() -> DeviceMesh:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


@contextlib.contextmanager
def mesh_guard(mesh: DeviceMesh):
    global _default_mesh
    prev, _default_mesh = _default_mesh, mesh
    try:
        yield mesh
    finally:
        _default_mesh = prev
