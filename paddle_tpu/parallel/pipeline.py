"""GPipe-style pipeline parallelism over a named `pp` mesh axis.

The reference has no pipeline parallelism (SURVEY §2.6: absent in 2018);
this is the TPU-native design the scaling literature prescribes: identical
stages hold their layer slice (stacked params sharded on `pp` dim 0),
micro-batches stream through the stages, and activations hop stage->stage
over ICI via `lax.ppermute` inside one `shard_map`-compiled program — no
host scheduler, no RPC, one XLA computation for the whole schedule.

The schedule is the classic GPipe fill-drain loop: with S stages and M
micro-batches the loop runs M + S - 1 ticks; stage 0 injects micro-batch t
at tick t, stage s processes what stage s-1 produced last tick, and the
last stage emits finished micro-batches from tick S-1 on.  Bubble fraction
(S-1)/(M+S-1) — callers pick M >> S for efficiency, exactly as in GPipe.

The streamed activation is a PYTREE (a bare array is the trivial
one-leaf tree).  Per-micro-batch side inputs every stage merely READS
(attention masks, segment ids, encoder outputs) go in `aux`: they stay
replicated and each stage indexes its current micro-batch locally —
no ppermute hops or output psums are spent on data that never changes.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_apply"]


def _bcast_from_last(o, stage, S, pp_axis):
    """psum-broadcast stage S-1's copy, preserving the leaf dtype (a
    float literal in jnp.where would silently promote int/bool leaves)."""
    if o.dtype == jnp.bool_:
        picked = jnp.where(stage == S - 1, o, False).astype(jnp.int32)
        return jax.lax.psum(picked, pp_axis).astype(jnp.bool_)
    picked = jnp.where(stage == S - 1, o, jnp.zeros((), o.dtype))
    return jax.lax.psum(picked, pp_axis)


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x_microbatches,
    mesh,
    pp_axis: str = "pp",
    aux=None,
):
    """Run `y = stage_{S-1}(... stage_0(x))` for every micro-batch, with
    stages laid out over the `pp_axis` of `mesh`.

    stage_fn(params, x[, aux_mb]) -> y   x and y are pytrees with the
        SAME structure and leaf shapes (the streamed activation; a bare
        array is fine)
    stage_params: pytree whose leaves have leading dim S (one slice per
        stage) — sharded onto the pp axis, so each device holds only its
        stage's parameters
    x_microbatches: pytree of arrays [M, ...] of micro-batches
        (replicated across pp; other mesh axes may shard the trailing
        dims through the caller's own in_shardings)
    aux: optional pytree of [M, ...] per-micro-batch side inputs
        (attention masks, segment ids) that every stage READS but does
        not transform.  Replicated on every device, so each stage
        indexes its current micro-batch LOCALLY — no ppermute hops, no
        output psum for them (streaming them through the ring would
        all-reduce M mask-sized buffers for nothing).  When aux is
        given, stage_fn takes a third argument: the aux slice for the
        micro-batch that stage is processing this tick.
    returns the x pytree of [M, ...] outputs, replicated across pp.
    """
    jmesh = mesh.mesh if hasattr(mesh, "mesh") else mesh
    S = jmesh.shape[pp_axis]
    leaves = jax.tree_util.tree_leaves(x_microbatches)
    if not leaves:
        raise ValueError("x_microbatches has no array leaves")
    M = leaves[0].shape[0]
    for leaf in jax.tree_util.tree_leaves((x_microbatches, aux)):
        if leaf.shape[0] != M:
            raise ValueError(
                "every x_microbatches/aux leaf needs the same leading "
                f"micro-batch dim: got {leaf.shape[0]} vs {M}")
    ticks = M + S - 1
    tmap = jax.tree_util.tree_map
    has_aux = aux is not None

    def per_stage(params, xs, auxs):
        # params: leaves [1, ...] (this stage's slice); xs: leaves [M, ...]
        stage = jax.lax.axis_index(pp_axis)
        local = tmap(lambda p: p[0], params)

        def tick(carry, t):
            incoming, outputs = carry
            # stage 0 injects micro-batch t (zeros once the input drains)
            x_in = tmap(
                lambda f, inc: jnp.where(
                    stage == 0,
                    jnp.where(t < M, f[jnp.minimum(t, M - 1)],
                              jnp.zeros(f.shape[1:], f.dtype)),
                    inc,
                ),
                xs, incoming,
            )
            if has_aux:
                # stage s processes micro-batch t - s at tick t; the aux
                # arrays are replicated, so index locally (out-of-range
                # ticks read a clamped slice whose result is discarded)
                mb = jnp.clip(t - stage, 0, M - 1)
                aux_mb = tmap(lambda a: a[mb], auxs)
                y = stage_fn(local, x_in, aux_mb)
            else:
                y = stage_fn(local, x_in)
            # the last stage finishes micro-batch t - (S - 1) at tick t
            done_idx = t - (S - 1)
            outputs = tmap(
                lambda o, yl: jnp.where(
                    (stage == S - 1) & (done_idx >= 0),
                    o.at[jnp.maximum(done_idx, 0)].set(yl),
                    o,
                ),
                outputs, y,
            )
            # hand the activation to the next stage (ring; stage S-1's
            # send wraps to stage 0, which ignores it)
            incoming = tmap(
                lambda yl: jax.lax.ppermute(
                    yl, pp_axis, [(i, (i + 1) % S) for i in range(S)]
                ),
                y,
            )
            return (incoming, outputs), None

        zeros_mb = tmap(lambda f: jnp.zeros(f.shape[1:], f.dtype), xs)
        outputs0 = tmap(lambda f: jnp.zeros_like(f), xs)
        (_, outputs), _ = jax.lax.scan(
            tick, (zeros_mb, outputs0), jnp.arange(ticks),
        )
        # every device returns [M, ...]; only stage S-1's copy is real
        return tmap(
            lambda o: _bcast_from_last(o, stage, S, pp_axis), outputs,
        )

    param_specs = tmap(
        lambda p: P(pp_axis, *([None] * (p.ndim - 1))), stage_params
    )
    x_specs = tmap(lambda f: P(*([None] * f.ndim)), x_microbatches)
    aux_specs = tmap(lambda f: P(*([None] * f.ndim)), aux)

    fn = shard_map(
        per_stage, mesh=jmesh,
        in_specs=(param_specs, x_specs, aux_specs),
        out_specs=x_specs,
        check_vma=False,
    )
    return fn(stage_params, x_microbatches, aux)
