"""GPipe-style pipeline parallelism over a named `pp` mesh axis.

The reference has no pipeline parallelism (SURVEY §2.6: absent in 2018);
this is the TPU-native design the scaling literature prescribes: identical
stages hold their layer slice (stacked params sharded on `pp` dim 0),
micro-batches stream through the stages, and activations hop stage->stage
over ICI via `lax.ppermute` inside one `shard_map`-compiled program — no
host scheduler, no RPC, one XLA computation for the whole schedule.

The schedule is the classic GPipe fill-drain loop: with S stages and M
micro-batches the loop runs M + S - 1 ticks; stage 0 injects micro-batch t
at tick t, stage s processes what stage s-1 produced last tick, and the
last stage emits finished micro-batches from tick S-1 on.  Bubble fraction
(S-1)/(M+S-1) — callers pick M >> S for efficiency, exactly as in GPipe.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

__all__ = ["pipeline_apply"]


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x_microbatches,
    mesh,
    pp_axis: str = "pp",
):
    """Run `y = stage_{S-1}(... stage_0(x))` for every micro-batch, with
    stages laid out over the `pp_axis` of `mesh`.

    stage_fn(params, x) -> y       same shape in and out (a layer block)
    stage_params: pytree whose leaves have leading dim S (one slice per
        stage) — sharded onto the pp axis, so each device holds only its
        stage's parameters
    x_microbatches: array [M, ...] of micro-batches (replicated across pp;
        other mesh axes may shard the trailing dims through the caller's
        own in_shardings)
    returns [M, ...] outputs, replicated across pp.
    """
    jmesh = mesh.mesh if hasattr(mesh, "mesh") else mesh
    S = jmesh.shape[pp_axis]
    M = x_microbatches.shape[0]
    ticks = M + S - 1

    def per_stage(params, xs):
        # params: leaves [1, ...] (this stage's slice); xs: [M, ...] local
        stage = jax.lax.axis_index(pp_axis)
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        mb_shape = xs.shape[1:]

        def tick(carry, t):
            incoming, outputs = carry
            # stage 0 injects micro-batch t (zeros once the input drains)
            inject = jnp.where(
                t < M, xs[jnp.minimum(t, M - 1)], jnp.zeros(mb_shape, xs.dtype)
            )
            x_in = jnp.where(stage == 0, inject, incoming)
            y = stage_fn(local, x_in)
            # the last stage finishes micro-batch t - (S - 1) at tick t
            done_idx = t - (S - 1)
            outputs = jnp.where(
                (stage == S - 1) & (done_idx >= 0),
                outputs.at[jnp.maximum(done_idx, 0)].set(y),
                outputs,
            )
            # hand the activation to the next stage (ring; stage S-1's
            # send wraps to stage 0, which ignores it)
            incoming = jax.lax.ppermute(
                y, pp_axis, [(i, (i + 1) % S) for i in range(S)]
            )
            return (incoming, outputs), None

        outputs0 = jnp.zeros((M,) + mb_shape, xs.dtype)
        (_, outputs), _ = jax.lax.scan(
            tick, (jnp.zeros(mb_shape, xs.dtype), outputs0),
            jnp.arange(ticks),
        )
        # every device returns [M, ...]; only stage S-1's copy is real —
        # psum over pp broadcasts it (other stages contribute zeros)
        outputs = jnp.where(stage == S - 1, outputs, 0.0)
        return jax.lax.psum(outputs, pp_axis)

    param_specs = jax.tree_util.tree_map(
        lambda p: P(pp_axis, *([None] * (p.ndim - 1))), stage_params
    )
    x_spec = P(*([None] * x_microbatches.ndim))

    fn = shard_map(
        per_stage, mesh=jmesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
    return fn(stage_params, x_microbatches)
