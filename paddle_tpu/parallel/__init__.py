"""Parallel execution over a TPU device mesh.

Replaces the reference's multi-device machinery — ParallelExecutor's SSA
graph + NCCL op-handles (paddle/fluid/framework/parallel_executor.cc:191,
details/all_reduce_op_handle.cc:48) and the transpiler's nccl2 mode — with
SPMD over a `jax.sharding.Mesh`: shardings are annotations, XLA inserts the
collectives over ICI/DCN, and one jitted program runs on every chip.
"""

from .mesh import DeviceMesh, make_mesh, default_mesh, mesh_guard  # noqa: F401
from .strategy import BuildStrategy, ExecutionStrategy, ShardingStrategy  # noqa: F401
from .executor import ParallelExecutor, CompiledProgram  # noqa: F401
from .env import init_distributed, trainer_id, num_trainers  # noqa: F401
from .pipeline import pipeline_apply  # noqa: F401
from .pipeline_program import ProgramPipeline  # noqa: F401
from .moe import switch_moe  # noqa: F401
