"""ParallelExecutor: SPMD training over a device mesh.

Parity target: python/paddle/fluid/parallel_executor.py:32 and the C++ engine
behind it (parallel_executor.cc:191).  The reference clones the op graph onto
every GPU, inserts NCCL allreduce op-handles at each gradient, and runs the
SSA graph with a thread pool.  Here the SAME compiled program used by the
serial Executor is jitted with `in_shardings` over a `DeviceMesh`: feeds are
sharded batch-dim over `dp`, parameters follow their logical sharding spec
(replicated by default), and XLA inserts the psum/all-gather collectives over
ICI that the reference issued through ncclAllReduce
(details/all_reduce_op_handle.cc:83).  Multi-host (the reference's "nccl2"
transpiler mode) is the same code over a process-spanning mesh after
`parallel.init_distributed()`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

import dataclasses

from .. import flags
from ..core.compiler import CompiledBlock
from ..core.executor import _RunPlan
from ..core.framework import Program, Variable, default_main_program
from ..core.scope import Scope, global_scope
from .mesh import DeviceMesh, default_mesh
from .strategy import BuildStrategy, ExecutionStrategy, ReduceStrategy, ShardingStrategy

__all__ = ["ParallelExecutor", "CompiledProgram"]


class ParallelExecutor:
    """Data-parallel (and tensor/pipeline-parallel, via sharding specs)
    executor with the reference's constructor/run surface."""

    def __init__(
        self,
        use_cuda: bool = False,
        loss_name: Optional[str] = None,
        main_program: Optional[Program] = None,
        share_vars_from: Optional["ParallelExecutor"] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        build_strategy: Optional[BuildStrategy] = None,
        num_trainers: int = 1,
        trainer_id: int = 0,
        scope: Optional[Scope] = None,
        mesh: Optional[DeviceMesh] = None,
        sharding_strategy: Optional[ShardingStrategy] = None,
    ):
        self.program = main_program or default_main_program()
        self.loss_name = loss_name
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        self.sharding_strategy = sharding_strategy or ShardingStrategy()
        if mesh is None:
            if self.sharding_strategy.mesh_axes:
                from .mesh import make_mesh

                mesh = make_mesh(self.sharding_strategy.mesh_axes)
            else:
                mesh = default_mesh()
        self.mesh = mesh
        if share_vars_from is not None:
            scope = scope or share_vars_from.scope
        self.scope = scope or global_scope()
        self._cache: Dict[Tuple, Tuple[CompiledBlock, _RunPlan]] = {}
        # Reduce strategy => shard optimizer/param state over dp (ZeRO-style
        # sibling of the reference's reduce+broadcast placement); copy the
        # strategy so a caller-shared instance isn't mutated
        if self.build_strategy.reduce_strategy == ReduceStrategy.Reduce:
            self.sharding_strategy = dataclasses.replace(
                self.sharding_strategy, shard_optimizer_state=True
            )

    @property
    def device_count(self) -> int:
        return self.mesh.num_devices

    # ------------------------------------------------------------------
    def _state_sharding(self, name: str, block0) -> Any:
        override = self.sharding_strategy.param_shardings.get(name)
        if override is not None:
            return self.mesh.sharding(override)
        vd = block0.vars.get(name)
        if vd is not None and vd.sharding:
            return self.mesh.sharding(vd.sharding)
        # ZeRO-style state sharding (Reduce strategy): split dim 0 of each
        # float state over dp when it divides evenly; XLA all-gathers on use
        if self.sharding_strategy.shard_optimizer_state and vd is not None:
            axis = self.sharding_strategy.batch_axis
            n = self.mesh.axis_size(axis)
            shape = vd.shape
            if n > 1 and shape and shape[0] > 0 and shape[0] % n == 0:
                return self.mesh.sharding([axis] + [None] * (len(shape) - 1))
        return self.mesh.replicated()

    def _feed_sharding(self, name: str, block0) -> Any:
        vd = block0.vars.get(name)
        if vd is not None and vd.sharding:
            return self.mesh.sharding(vd.sharding)
        return self.mesh.batch_sharding(self.sharding_strategy.batch_axis)

    def _compile(self, plan: _RunPlan) -> CompiledBlock:
        feed_names, fetch_names, state_names = (
            plan.feed_names, plan.fetch_names, plan.state_names,
        )
        block0 = self.program.desc.block(0)
        state_shardings = tuple(self._state_sharding(n, block0) for n in state_names)
        in_shardings = (
            tuple(self._feed_sharding(n, block0) for n in feed_names),
            state_shardings,
            self.mesh.replicated(),
        )
        # pin state outputs to their input shardings so persistable state
        # round-trips across steps without resharding; fetches gather to
        # replicated (they head to host anyway)
        out_shardings = (
            tuple(self.mesh.replicated() for _ in fetch_names),
            state_shardings,
            self.mesh.replicated(),
        )
        return CompiledBlock(
            self.program,
            0,
            feed_names,
            fetch_names,
            state_names,
            donate_states=True,
            mesh=self.mesh,
            in_shardings=in_shardings,
            out_shardings=out_shardings,
        )

    def run(
        self,
        fetch_list: Optional[Sequence] = None,
        feed: Optional[Any] = None,
        feed_dict: Optional[Dict[str, Any]] = None,
        return_numpy: bool = True,
    ) -> List[Any]:
        # trace-time defaults scope keyed off the mesh's actual devices
        # (see core/executor.py Executor.run)
        with flags.tpu_trace_scope(self._mesh_is_tpu()):
            return self._run_scoped(fetch_list, feed, feed_dict, return_numpy)

    def _mesh_is_tpu(self) -> bool:
        from ..core.place import device_is_tpu

        devs = np.asarray(self.mesh.mesh.devices).ravel()
        return bool(len(devs)) and device_is_tpu(devs[0])

    def _run_scoped(
        self,
        fetch_list=None,
        feed=None,
        feed_dict=None,
        return_numpy=True,
    ) -> List[Any]:
        feed = feed if feed is not None else feed_dict
        if isinstance(feed, (list, tuple)):
            # reference accepts one dict per device; global batch == concat.
            # Every per-device dict must feed the same vars, else batches
            # would silently mispair (reference validates the same way).
            if not feed:
                raise ValueError("feed list must contain at least one dict")
            keys = set(feed[0])
            for i, d in enumerate(feed):
                if set(d) != keys:
                    raise ValueError(
                        f"feed dict {i} keys {sorted(d)} != feed dict 0 keys "
                        f"{sorted(keys)}; all per-device feeds must match"
                    )
            feed = {
                k: np.concatenate([np.asarray(d[k]) for d in feed], axis=0)
                for k in sorted(keys)
            }
        feed = feed or {}
        fetch_list = list(fetch_list or [])

        feed_names = sorted(feed)
        fetch_names = [v.name if isinstance(v, Variable) else str(v) for v in fetch_list]

        # fingerprint-validated cache: an in-place desc mutation recompiles
        # and replaces the stale entry (see core/executor.py for rationale)
        from ..core import amp

        fp = self.program.desc.fingerprint()
        key = (tuple(feed_names), tuple(fetch_names), amp.state_key(),
               flags.trace_key())
        entry = self._cache.get(key)
        if entry is not None and entry[0] != fp:
            entry = None
        if entry is None:
            plan = _RunPlan(self.program, feed_names, fetch_names)
            entry = (fp, self._compile(plan), plan)
            self._cache[key] = entry
        _, compiled, plan = entry

        from .multihost import global_feed_value, is_multiprocess

        block0 = self.program.desc.block(0)
        feed_vals = plan.feed_values(feed, block0)
        if not is_multiprocess(self.mesh):
            # multihost feeds are per-process shards assembled into the
            # global array below — their local dim 0 is a fraction of the
            # dp axis, so the single-process divisibility contract does
            # not apply
            self._check_batch_divisible(plan.feed_names, feed_vals, block0)
        state_vals = plan.state_values(self.scope, block0)
        rng = plan.rng_value(self.scope, self.program)

        if is_multiprocess(self.mesh):
            # each process feeds ITS batch shard; jax assembles the global
            # array (reference: per-trainer reader shards under nccl2)
            feed_vals = tuple(
                global_feed_value(self._feed_sharding(n, block0), v)
                for n, v in zip(plan.feed_names, feed_vals)
            )

        if not is_multiprocess(self.mesh):
            state_vals, rng = self._reshard_serial_state(
                state_vals, rng, plan, block0)
        with self.mesh.mesh:
            fetches, new_states, new_rng = compiled(feed_vals, state_vals, rng)

        plan.write_back(self.scope, new_states, new_rng)
        from ..core.executor import _check_nan_inf

        _check_nan_inf(plan, fetches, new_states)
        return plan.convert_fetches(fetches, block0, return_numpy)

    def run_steps(
        self,
        feed_list: Optional[Sequence[Dict[str, Any]]] = None,
        fetch_list: Optional[Sequence] = None,
        steps: Optional[int] = None,
        return_numpy: bool = True,
        mode: str = "scan",
    ) -> List[Any]:
        with flags.tpu_trace_scope(self._mesh_is_tpu()):
            return self._run_steps_scoped(
                feed_list, fetch_list, steps, return_numpy, mode)

    def _run_steps_scoped(
        self,
        feed_list=None,
        fetch_list=None,
        steps=None,
        return_numpy=True,
        mode="scan",
    ) -> List[Any]:
        """Run `steps` SPMD iterations in ONE device dispatch: the compiled
        block body runs under `lax.scan` inside a single pjit over the mesh,
        so per-step host dispatch (the dominant overhead on fast chips)
        is paid once per call.  Mirrors Executor.run_steps (see its
        docstring for the feed-cycling, fetch and check_nan_inf contract);
        feeds keep their usual shardings with a replicated leading steps
        dim, persistable state round-trips in its sharding.  Dense feeds
        only (scan needs shape-stable slices)."""
        from jax.sharding import NamedSharding, PartitionSpec

        from ..core import amp
        from ..core.executor import (
            _check_nan_inf,
            scan_multi_fn,
            stacked_feeds,
        )
        from .multihost import is_multiprocess

        if is_multiprocess(self.mesh):
            # per-process shard assembly (run()'s global_feed_value path)
            # has no scan equivalent yet; fail clearly instead of letting
            # jax reject non-addressable arrays mid-call
            raise NotImplementedError(
                "ParallelExecutor.run_steps is single-process only; on a "
                "multi-host mesh call run() per step"
            )
        if not feed_list:
            raise ValueError("run_steps requires a non-empty feed_list")
        steps = int(steps if steps is not None else len(feed_list))
        if steps < 1:
            raise ValueError("run_steps requires steps >= 1")
        feed_names = sorted(feed_list[0])
        for i, feed in enumerate(feed_list):
            if sorted(feed) != feed_names:
                raise ValueError(
                    f"run_steps feed_list[{i}] keys {sorted(feed)} differ "
                    f"from feed_list[0] keys {feed_names}"
                )
        fetch_names = [
            v.name if isinstance(v, Variable) else str(v)
            for v in (fetch_list or [])
        ]
        block0 = self.program.desc.block(0)

        fp = self.program.desc.fingerprint()
        if mode not in ("scan", "flat"):
            raise ValueError(f"run_steps mode must be 'scan' or 'flat', "
                             f"got {mode!r}")
        key = ("pe_run_steps", steps, len(feed_list), tuple(feed_names),
               tuple(fetch_names), amp.state_key(), flags.trace_key(), mode)
        entry = self._cache.get(key)
        if entry is not None and entry[0] != fp:
            entry = None
        if entry is None:
            plan = _RunPlan(self.program, feed_names, fetch_names)
            compiled = CompiledBlock(
                self.program, 0, plan.feed_names, plan.fetch_names,
                plan.state_names, donate_states=False, mesh=self.mesh,
            )
            multi = scan_multi_fn(compiled.raw_fn, len(feed_list), steps,
                                  flat=(mode == "flat"))
            state_sh = tuple(
                self._state_sharding(n, block0) for n in plan.state_names
            )
            stack_sh = tuple(
                NamedSharding(
                    self.mesh.mesh,
                    PartitionSpec(
                        None, *self._feed_sharding(n, block0).spec
                    ),
                )
                for n in plan.feed_names
            )
            fn = jax.jit(
                multi,
                in_shardings=(stack_sh, state_sh, self.mesh.replicated()),
                out_shardings=(
                    tuple(self.mesh.replicated() for _ in plan.fetch_names),
                    state_sh,
                    self.mesh.replicated(),
                ),
                donate_argnums=(1,),
            )
            entry = (fp, fn, plan)
            self._cache[key] = entry
        _, fn, plan = entry

        feeds_stack = stacked_feeds(
            self._cache, key + ("feeds",), fp, plan, feed_list, block0,
            lambda t: t,  # pjit's in_shardings own device placement
        )
        self._check_batch_divisible(
            plan.feed_names, tuple(f[0] for f in feeds_stack), block0
        )
        state_vals = plan.state_values(self.scope, block0)
        rng = plan.rng_value(self.scope, self.program)

        state_vals, rng = self._reshard_serial_state(
            state_vals, rng, plan, block0)
        with self.mesh.mesh:
            fetches, new_states, new_rng = fn(feeds_stack, state_vals, rng)

        plan.write_back(self.scope, new_states, new_rng)
        _check_nan_inf(plan, fetches, new_states)
        return plan.convert_fetches(fetches, block0, return_numpy)

    def _check_batch_divisible(self, feed_names, feed_vals, block0) -> None:
        """A dim-0-sharded feed whose batch isn't divisible by its mesh
        axes would die inside pjit with a sharding ValueError; raise the
        framework-level message first.  Applies to ANY dim-0 sharding (dp,
        sp, or a ("dp", "sp") tuple — the divisor is the product of those
        axis sizes), not just the configured batch axis.  The reference
        redistributed uneven tail batches at run time
        (data_balance_op_handle.cc) because its per-device graphs took
        ragged sizes; XLA's static shapes make the even-batch contract
        explicit instead — pad or trim the tail batch (reader decorators
        `batch(..., drop_last=True)` do this)."""
        if self.mesh.num_devices <= 1:
            return  # no axis can shard dim 0; skip the per-feed pass
        for name, val in zip(feed_names, feed_vals):
            sh = self._feed_sharding(name, block0)
            spec = getattr(sh, "spec", None)
            if not spec or spec[0] is None:
                continue
            dim0 = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
            div = 1
            for a in dim0:
                div *= self.mesh.axis_size(a)
            if div <= 1:
                continue
            data = getattr(val, "data", val)
            n = np.shape(data)[0] if np.ndim(data) else 0
            if n % div:
                raise ValueError(
                    f"feed '{name}' batch size {n} is not divisible by its "
                    f"dim-0 mesh axes {dim0} ({div} shards); SPMD batch "
                    f"sharding needs equal per-device shards — pad or drop "
                    f"the tail batch (e.g. paddle_tpu.reader decorators "
                    f"batch(..., drop_last=True))"
                )

    def _reshard_serial_state(self, state_vals, rng, plan, block0):
        """The ONE copy of the serial->SPMD handoff: the serial Executor
        commits state/rng to ITS device (lowering-cache stability), and
        pjit raises on committed single-device args that mismatch
        in_shardings — explicitly reshard them to this mesh's shardings.
        One-time copy: arrays come back FROM pjit already in place."""
        state_vals = tuple(
            jax.device_put(v, self._state_sharding(n, block0))
            if isinstance(v, jax.Array) else v
            for n, v in zip(plan.state_names, state_vals)
        )
        rng = jax.device_put(rng, self.mesh.replicated())
        return state_vals, rng

    def drop_local_exe_scopes(self):  # reference API; scopes are XLA-owned
        pass


class CompiledProgram:
    """fluid.compiler.CompiledProgram-style wrapper: build configuration
    fluently, execute through ParallelExecutor."""

    def __init__(self, program: Optional[Program] = None):
        self.program = program or default_main_program()
        self._pe_kwargs: Dict[str, Any] = {}
        self._pe_by_scope: Dict[int, ParallelExecutor] = {}

    def with_data_parallel(
        self,
        loss_name: Optional[str] = None,
        build_strategy: Optional[BuildStrategy] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        share_vars_from=None,
        mesh: Optional[DeviceMesh] = None,
    ) -> "CompiledProgram":
        self._pe_kwargs.update(
            loss_name=loss_name,
            build_strategy=build_strategy,
            exec_strategy=exec_strategy,
            share_vars_from=share_vars_from,
            mesh=mesh,
        )
        self._pe_by_scope.clear()  # reconfiguration invalidates bound executors
        return self

    def executor(self, scope: Optional[Scope] = None) -> ParallelExecutor:
        return ParallelExecutor(
            main_program=self.program, scope=scope, **self._pe_kwargs
        )

    def _executor_for_scope(self, scope: Scope) -> ParallelExecutor:
        """Bound executor per scope, so Executor.run(compiled_prog) keeps its
        XLA compilation cache across steps (and across alternating scopes)."""
        pe = self._pe_by_scope.get(id(scope))
        if pe is None:
            pe = self.executor(scope=scope)
            self._pe_by_scope[id(scope)] = pe
        return pe
