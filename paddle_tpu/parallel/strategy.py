"""Execution/build strategy knobs (reference: paddle/fluid/framework/details/
build_strategy.h:34, execution_strategy.h:22).

Most reference knobs configured the SSA executor (thread counts, scope drop
cadence) or graph passes (fuse, memory-early-delete); under XLA those are
compiler-owned, so they are accepted-and-ignored for script compatibility.
The knobs that still mean something steer sharding:

- `reduce_strategy`: AllReduce == keep params replicated (grads psum);
  Reduce == shard optimizer state over dp (ZeRO-ish), beyond reference parity.
- `gradient_scale_strategy`: kept for API parity; mean-type losses already
  average over the *global* batch under SPMD, matching CoeffNumDevice.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


class ReduceStrategy:
    AllReduce = 0
    Reduce = 1


class GradientScaleStrategy:
    CoeffNumDevice = 0
    One = 1
    Customized = 2


class ExecutorType:
    Default = 0
    Experimental = 1


@dataclass
class BuildStrategy:
    ReduceStrategy = ReduceStrategy
    GradientScaleStrategy = GradientScaleStrategy

    reduce_strategy: int = ReduceStrategy.AllReduce
    gradient_scale_strategy: int = GradientScaleStrategy.CoeffNumDevice
    debug_graphviz_path: str = ""
    enable_data_balance: bool = False
    memory_early_delete: bool = False
    enable_sequential_execution: bool = False
    fuse_elewise_add_act_ops: bool = False
    fuse_broadcast_op: bool = False
    fuse_relu_depthwise_conv: bool = False
    remove_unnecessary_lock: bool = True


@dataclass
class ExecutionStrategy:
    ExecutorType = ExecutorType

    num_threads: int = 0
    use_cuda: bool = False
    allow_op_delay: bool = False
    num_iteration_per_drop_scope: int = 1
    type: int = ExecutorType.Default
    dry_run: bool = False


@dataclass
class ShardingStrategy:
    """TPU-native extension: how to lay the program over the mesh.

    `mesh_axes` names the mesh (axis -> size, -1 absorbs); per-variable
    overrides come from Variable.sharding.  `shard_optimizer_state` shards
    persistable optimizer accumulators over dp (set by Reduce strategy)."""

    mesh_axes: Optional[Dict[str, int]] = None
    batch_axis: str = "dp"
    shard_optimizer_state: bool = False
    param_shardings: Dict[str, Any] = field(default_factory=dict)
