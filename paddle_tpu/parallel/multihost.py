"""Multi-host data plumbing (reference: the nccl2 transpiler mode +
test_dist_base.py's localhost subprocess clusters).

After `parallel.env.init_distributed()` every host sees the pod-wide device
list, and a mesh built from `jax.devices()` spans processes.  What remains
is feeding: each process holds only ITS batch shard, so dp-sharded feeds go
through `jax.make_array_from_process_local_data` (each process contributes
its local rows), while replicated values (parameters, fetches) are the same
bytes on every host and flow through jit's sharding-annotated parameters.
The reference's equivalent machinery is the per-trainer reader shard plus
ncclAllReduce over the trainer ranks."""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ..core.lod import LoDValue

__all__ = ["is_multiprocess", "global_feed_value", "checkpoint_barrier"]


def checkpoint_barrier(tag: str) -> None:
    """Pod-wide sync point for checkpoint manifests: on save, every
    process's shard files must be durable before process 0's meta.json
    (whose manifest digests them all) marks the checkpoint complete; on
    load, every process must pass verification before any starts training
    on the restored params.  No-op for single-process runs, so io.py can
    call it unconditionally."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def is_multiprocess(mesh) -> bool:
    """True when `mesh` spans more than one jax process."""
    procs = {d.process_index for d in mesh.mesh.devices.flat}
    return len(procs) > 1


def _from_local(sharding, arr) -> jax.Array:
    arr = np.asarray(arr)
    return jax.make_array_from_process_local_data(sharding, arr)


def global_feed_value(sharding, value) -> Any:
    """Per-process batch shard -> global sharded jax.Array (LoD-aware)."""
    if isinstance(value, LoDValue):
        return LoDValue(
            _from_local(sharding, value.data),
            _from_local(sharding, np.asarray(value.lengths)),
        )
    return _from_local(sharding, value)

