"""Expert parallelism (`ep` mesh axis): switch-routed mixture-of-experts
FFN with an `all_to_all` dispatch over ICI.

The reference has no MoE (2018); this is the TPU-native shape: experts
shard over the `ep` axis (each device owns E/ep experts), tokens pick an
expert by a learned gate (top-1 switch routing), and two `all_to_all`
collectives move token blocks expert-ward and back inside one compiled
program — the standard Switch-Transformer dataflow.

Static shapes throughout: every (device, expert) pair gets a fixed
`capacity` token slot block; overflow tokens pass through unchanged
(the usual capacity-factor semantics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

__all__ = ["switch_moe"]


def switch_moe(
    x,
    gate_w,
    w1,
    b1,
    w2,
    b2,
    mesh,
    ep_axis: str = "ep",
    capacity: int | None = None,
):
    """Top-1 switch MoE FFN.

    x: [T, D] tokens (replicated over ep; shard T over dp outside)
    gate_w: [D, E] router weights
    w1, b1: [E, D, H], [E, H]   per-expert FFN in
    w2, b2: [E, H, D], [E, D]   per-expert FFN out
    capacity: per-expert token slots (default: 2 * ceil(T / E))
    returns [T, D]: expert output for routed tokens, 0 for dropped ones,
    plus the router probability scaling (Switch-Transformer convention).
    """
    jmesh = mesh.mesh if hasattr(mesh, "mesh") else mesh
    ep = jmesh.shape[ep_axis]
    T, D = x.shape
    E = gate_w.shape[1]
    assert E % ep == 0, f"experts {E} must divide over ep={ep}"
    e_local = E // ep
    cap = capacity or max(2 * ((T + E - 1) // E), 1)

    logits = x @ gate_w                               # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)               # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    # slot position of each token within its expert's capacity block
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)       # [T, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot                 # 1-based
    slot = jnp.sum(pos, axis=1) - 1                           # [T]
    keep = slot < cap

    # scatter tokens into the [E, cap, D] dispatch buffer
    buf = jnp.zeros((E, cap, D), x.dtype)
    tok_idx = (expert, jnp.where(keep, slot, cap - 1))
    buf = buf.at[tok_idx].add(jnp.where(keep[:, None], x, 0.0))

    def local_experts(bufs, w1l, b1l, w2l, b2l):
        # bufs: [E_local, cap * ep_from, D] after all_to_all regroup
        h = jnp.einsum("ecd,edh->ech", bufs, w1l) + b1l[:, None, :]
        h = jax.nn.relu(h)
        return jnp.einsum("ech,ehd->ecd", h, w2l) + b2l[:, None, :]

    def per_device(buf_l, w1l, b1l, w2l, b2l):
        # buf_l [E, cap, D] (each device built the full buffer from its
        # token shard — here tokens are replicated over ep, so buf is
        # identical; the all_to_all still exercises the real dataflow)
        b = buf_l.reshape(ep, e_local, cap, D)
        # expert-ward: device i receives every device's block for ITS experts
        b = jax.lax.all_to_all(b, ep_axis, 0, 0, tiled=False)
        b = b.reshape(ep, e_local, cap, D).transpose(1, 0, 2, 3)
        b = b.reshape(e_local, ep * cap, D)
        y = local_experts(b, w1l, b1l, w2l, b2l)
        # token-ward: send results back where they came from
        y = y.reshape(e_local, ep, cap, D).transpose(1, 0, 2, 3)
        y = jax.lax.all_to_all(y.reshape(ep, e_local, cap, D),
                               ep_axis, 0, 0, tiled=False)
        return y.reshape(E, cap, D)

    espec = P(ep_axis, *([None] * 2))
    out_buf = shard_map(
        per_device, mesh=jmesh,
        in_specs=(P(*([None] * 3)), espec, P(ep_axis, None),
                  espec, P(ep_axis, None)),
        out_specs=P(*([None] * 3)),
        check_vma=False,
    )(buf, w1, b1, w2, b2)

    y = out_buf[tok_idx]                              # [T, D]
    y = jnp.where(keep[:, None], y, 0.0)
    return y * gate[:, None]
