"""Program-path pipeline parallelism: derive GPipe stages from a Program.

`pipeline_apply` (pipeline.py) is the raw primitive — a hand-written
stage_fn over stacked params.  This module closes the gap to the
*Program* path: a fluid-built network whose body is a chain of
structurally identical segments (transformer layers, repeated fc
blocks) is split at user-named boundary variables, ONE segment's op
descs are lowered into the stage function, every segment's parameter
values are stacked stage-major from the scope, and the whole GPipe
fill-drain schedule runs as one XLA computation over the `pp` mesh
axis.

The reference has no pipeline parallelism to port (SURVEY §2.6 — absent
in the 2018 tree); its closest structure is the multi-device SSA graph
builder cloning op-ranges per place
(framework/details/multi_devices_graph_pass.cc:335).  Here the split is
at trace time over the same ProgramDesc the serial Executor runs, so
pipeline parity against `Executor.run` is checkable op-for-op.

Contract:
- boundaries = [x0, b1, ..., bS]: S stages; stage s computes b_{s+1}
  from b_s.  x0 must be a feed (dense, no LoD); every boundary var must
  have the same shape/dtype (GPipe streams one activation shape).
- the segments must be structurally identical: same op-type sequence,
  same attrs, and positionally matching parameter shapes/dtypes —
  verified up front, mismatches raise before any compile.
- segments must be parameter-pure (no random ops, no state writes):
  batch_norm in train mode or dropout inside a stage raises.
- stages may read shared FEED vars besides the chain (attention masks,
  segment ids): these "carried" inputs ride as replicated aux arrays —
  each stage indexes its current micro-batch locally, no ppermute hops
  (pass `carried={name: [M, ...]}` to run/train_step); every stage must
  read the same carried names.

Training: `train_step` runs the full pipelined forward+backward (the
backward GPipe schedule falls out of jax.grad over `pipeline_apply` —
scan/ppermute transpose to the reverse hops) with an SGD(+momentum)
update on the stacked per-stage params, written back to the scope.
Gradient and updated-weight parity with serial per-microbatch execution
is the test contract.  Full fluid-optimizer parity (Adam state on
stage-sharded params) stays with ParallelExecutor's dp/tp/sp path.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core.compiler import LoweringContext, lower_op
from ..core.framework import Program, default_main_program
from ..core.scope import Scope, global_scope
from .mesh import DeviceMesh
from .pipeline import pipeline_apply

__all__ = ["ProgramPipeline"]

# ops that may appear in a stage but perform no computation
_SKIP = {"feed", "fetch"}
# random / stateful op types that would break stage purity
_IMPURE = {"dropout", "uniform_random", "gaussian_random",
           "truncated_gaussian_random", "sampling_id", "random_crop"}


class _Segment:
    def __init__(self, ops, params: List[str], carried: List[str],
                 in_name: str, out_name: str):
        self.ops = ops            # OpDesc list, program order
        self.params = params      # persistable input names, first-use order
        self.carried = carried    # feed-var side inputs streamed alongside
        self.in_name = in_name
        self.out_name = out_name

    # attrs that don't change the computed function: name scopes and role
    # annotations differ between otherwise identical per-layer blocks
    _COSMETIC_ATTRS = {"op_namescope", "op_role", "op_role_var",
                       "op_callstack", "op_device"}

    def signature(self, bdesc) -> tuple:
        """Structural fingerprint: op types + attrs + param shapes."""
        sig = []
        for op in self.ops:
            attrs = {k: v for k, v in sorted(op.attrs.items())
                     if not k.startswith("__")
                     and k not in self._COSMETIC_ATTRS}
            sig.append((op.type, tuple(sorted(
                (k, repr(v)) for k, v in attrs.items()))))
        shapes = tuple(
            (tuple(bdesc.vars[p].shape), str(bdesc.vars[p].dtype))
            for p in self.params
        )
        return tuple(sig), shapes


class ProgramPipeline:
    """Split `program` into GPipe stages at `boundaries` and run
    micro-batches through them over the mesh's `pp` axis."""

    def __init__(
        self,
        boundaries: Sequence,
        mesh: DeviceMesh,
        main_program: Optional[Program] = None,
        scope: Optional[Scope] = None,
        pp_axis: str = "pp",
    ):
        self.program = main_program or default_main_program()
        self.scope = scope or global_scope()
        self.mesh = mesh
        self.pp_axis = pp_axis
        names = [b.name if hasattr(b, "name") else str(b) for b in boundaries]
        if len(names) < 3:
            raise ValueError(
                "need >= 2 stages: boundaries = [input, b1, ..., output]")
        self.boundary_names = names
        self.num_stages = len(names) - 1
        jmesh = mesh.mesh if hasattr(mesh, "mesh") else mesh
        axis_size = dict(jmesh.shape).get(pp_axis)
        if axis_size is None:
            raise ValueError(
                f"mesh has no '{pp_axis}' axis (axes: "
                f"{list(dict(jmesh.shape))}); build it with "
                f"make_mesh({{'{pp_axis}': {self.num_stages}}})")
        if axis_size != self.num_stages:
            raise ValueError(
                f"mesh axis '{pp_axis}' has {axis_size} devices "
                f"but boundaries define {self.num_stages} stages")
        self._segments = self._split()
        self._check_isomorphic()
        self._stage_fn = None
        self._stacked = None
        self._prefix = None
        self._serve_fn = None
        self._train_cache: Dict = {}

    def _check_untied(self) -> None:
        """Training-only constraint: a parameter shared across stages
        stacks the same value per stage — fine for forward/serving, but
        per-slice updates would diverge the copies (grads are not
        summed), so train_step rejects it."""
        seen: Dict[str, int] = {}
        for s, seg in enumerate(self._segments):
            for n in seg.params:
                if n in seen:
                    raise ValueError(
                        f"parameter '{n}' is read by stages {seen[n]} and "
                        f"{s}: tied weights cannot be stage-stacked for "
                        "TRAINING (each stage needs its own copy; forward "
                        "run() supports them)")
                seen[n] = s

    # ------------------------------------------------------------------
    def _split(self) -> List[_Segment]:
        # work on the desc layer: a cloned/pruned program's python-side
        # Variable wrappers are rebuilt lazily, but the VarDescs are
        # always complete
        bdesc = self.program.desc.block(0)
        ops = list(bdesc.ops)
        producer: Dict[str, int] = {}
        for i, op in enumerate(ops):
            for n in op.output_arg_names():
                producer[n] = i

        names = self.boundary_names
        for b in names[1:]:
            if b not in producer:
                raise ValueError(f"boundary '{b}' is not produced by any op")
        idxs = [producer[b] for b in names[1:]]
        if idxs != sorted(idxs):
            raise ValueError(
                "boundary variables must appear in program order: "
                f"{list(zip(names[1:], idxs))}")

        # PREFIX: when boundaries[0] is itself produced by an op (an
        # embedding output, a computed attention bias's sibling), the ops
        # up to and including its producer run OUTSIDE the pipeline —
        # vmapped over the micro-batches from raw feeds (see
        # _make_prefix_fn); the isomorphic stages start after it
        prefix_end = producer.get(names[0], -1)
        if prefix_end >= idxs[0]:
            raise ValueError(
                f"boundary '{names[0]}' is produced after '{names[1]}' — "
                "boundaries must be in program order")
        self._prefix_ops = [op for op in ops[:prefix_end + 1]
                            if op.type not in _SKIP]
        for op in self._prefix_ops:
            # the prefix is lowered in test mode (run_feeds serves): the
            # same purity rules as stages apply, or serial parity breaks
            # silently (train-mode dropout disabled, moving-stat writes
            # dropped)
            if (op.type in _IMPURE
                    and op.attrs.get("is_test") is not True):
                raise ValueError(
                    f"op '{op.type}' in the pipeline prefix breaks "
                    "purity (random/stateful ops); build the program "
                    "with is_test=True (clone(for_test=True))")
            if op.attrs.get("is_test") is False:
                raise ValueError(
                    f"op '{op.type}' in the pipeline prefix runs in "
                    "training mode; build the program with is_test=True")
            for n in op.output_arg_names():
                v = bdesc.vars.get(n)
                if v is not None and v.persistable:
                    raise ValueError(
                        f"op '{op.type}' in the pipeline prefix writes "
                        f"persistable variable '{n}' — state writes are "
                        "not serveable")

        # shape/dtype uniformity (GPipe streams one activation shape)
        v0 = bdesc.vars[names[0]]
        want = (tuple(v0.shape), str(v0.dtype))
        for b in names[1:]:
            vb = bdesc.vars[b]
            got = (tuple(vb.shape), str(vb.dtype))
            if got != want:
                raise ValueError(
                    f"boundary '{b}' shape/dtype {got} != input {want}; "
                    "pipeline stages must map like to like")

        segments = []
        start = prefix_end + 1
        for s in range(self.num_stages):
            end = idxs[s]
            seg_ops = [op for op in ops[start:end + 1]
                       if op.type not in _SKIP]
            produced = set()
            params: List[str] = []
            carried: List[str] = []
            in_name = names[s]
            for op in seg_ops:
                if (op.type in _IMPURE
                        and op.attrs.get("is_test") is not True):
                    # test-mode dropout is a deterministic pass-through;
                    # anything random/stateful in train mode is rejected
                    raise ValueError(
                        f"op '{op.type}' in stage {s} breaks stage purity "
                        "(random/stateful ops are not pipelineable)")
                if op.attrs.get("is_test") is False:
                    raise ValueError(
                        f"op '{op.type}' in stage {s} runs in training mode "
                        "(state writes are not pipelineable); build the "
                        "program with is_test=True")
                for n in op.output_arg_names():
                    v = bdesc.vars.get(n)
                    if v is not None and v.persistable:
                        raise ValueError(
                            f"op '{op.type}' in stage {s} writes persistable "
                            f"variable '{n}' — state writes (LR counters, "
                            "moving statistics) are not pipelineable; the "
                            "serial Executor would update it, the pipeline "
                            "would silently drop it")
                for n in op.input_arg_names():
                    if (n in produced or n == in_name or n in params
                            or n in carried):
                        continue
                    v = bdesc.vars.get(n)
                    if v is not None and v.persistable:
                        params.append(n)
                        continue
                    if v is not None and producer.get(n, -1) <= prefix_end:
                        # a feed var, or a value the PREFIX computes
                        # (attention bias, sequence lengths): a carried
                        # side input — every stage must read the same
                        # names (checked below)
                        carried.append(n)
                        continue
                    raise ValueError(
                        f"stage {s} reads '{n}' which is neither the "
                        f"stage input '{in_name}', a stage-internal "
                        "value, a parameter, a feed, nor a prefix "
                        "output — stages must be self-contained chains")
                produced.update(op.output_arg_names())
            if names[s + 1] not in produced:
                raise ValueError(
                    f"stage {s} ops do not produce boundary "
                    f"'{names[s + 1]}'")
            segments.append(_Segment(seg_ops, params, carried, in_name,
                                     names[s + 1]))
            start = end + 1

        want_carried = segments[0].carried
        for s, seg in enumerate(segments[1:], start=1):
            if seg.carried != want_carried:
                raise ValueError(
                    f"stage {s} carried inputs {seg.carried} differ from "
                    f"stage 0's {want_carried}; side inputs must be the "
                    "same feed vars in every stage")
        return segments

    def _check_isomorphic(self) -> None:
        bdesc = self.program.desc.block(0)
        want = self._segments[0].signature(bdesc)
        for s, seg in enumerate(self._segments[1:], start=1):
            got = seg.signature(bdesc)
            if got != want:
                raise ValueError(
                    f"stage {s} is not structurally identical to stage 0 "
                    "(op sequence/attrs/param shapes differ); GPipe "
                    "stacking needs isomorphic stages.\n"
                    f"stage0: {want}\nstage{s}: {got}")

    # ------------------------------------------------------------------
    def _make_stage_fn(self):
        """Lower stage 0's op descs into stage_fn(params, x): the segments
        are isomorphic, so stage 0's graph with stage s's parameter VALUES
        computes stage s."""
        seg0 = self._segments[0]
        block = self.program.global_block()
        param_names = list(seg0.params)
        program = self.program

        carried_names = list(seg0.carried)

        def stage_fn(params, x, carried_vals):
            env: Dict[str, Any] = {seg0.in_name: x}
            env.update(zip(carried_names, carried_vals))
            env.update(zip(param_names, params))
            ctx = LoweringContext(
                program, block, env, jax.random.PRNGKey(0), is_test=True)
            for op in seg0.ops:
                lower_op(ctx, op, set())
            return env[seg0.out_name]

        return stage_fn

    def _make_prefix_fn(self):
        """Lower the prefix ops into prefix_fn(feeds_dict) ->
        (x0, carried_tuple) over ONE micro-batch; run()/train_step vmap
        it over the micro-batch dim.  Prefix params (embedding tables,
        bias tables) are read from the scope and closed over as
        replicated constants."""
        bdesc = self.program.desc.block(0)
        block = self.program.global_block()
        program = self.program
        carried_names = list(self._segments[0].carried)
        out_name = self.boundary_names[0]

        # prune the prefix to the ops the pipeline actually needs: the
        # program region before boundaries[0] can hold unrelated work
        # (the transformer builds decoder-side biases before the encoder
        # embedding) whose feeds run_feeds must not demand
        needed = {out_name, *carried_names}
        prefix_ops = []
        for op in reversed(self._prefix_ops):
            if any(n in needed for n in op.output_arg_names()):
                prefix_ops.append(op)
                needed.update(op.input_arg_names())
        prefix_ops.reverse()

        # feeds = non-persistable inputs with no producer
        produced = set()
        for op in prefix_ops:
            produced.update(op.output_arg_names())
        feed_names, param_names = [], []
        for op in prefix_ops:
            for n in op.input_arg_names():
                if n in produced or n in feed_names or n in param_names:
                    continue
                v = bdesc.vars.get(n)
                if v is not None and v.persistable:
                    param_names.append(n)
                else:
                    feed_names.append(n)
        # a carried var may be a raw feed the prefix never touches
        for n in carried_names:
            if n not in produced and n not in feed_names:
                feed_names.append(n)
        param_vals = []
        for n in param_names:
            v = self.scope.find_var(n)
            if v is None:
                raise ValueError(f"prefix parameter '{n}' not found in "
                                 "scope — run the startup program first")
            # device-resident ARGUMENTS, not jit constants: a numpy
            # closure would bake the embedding table into the compiled
            # HLO (duplicated memory, table-sized recompiles on refresh)
            param_vals.append(jax.device_put(np.asarray(v)))

        def prefix_fn(params, feed_dict):
            env: Dict[str, Any] = dict(zip(param_names, params))
            env.update({n: feed_dict[n] for n in feed_names})
            ctx = LoweringContext(
                program, block, env, jax.random.PRNGKey(0), is_test=True)
            for op in prefix_ops:
                lower_op(ctx, op, set())
            return env[out_name], tuple(env[n] for n in carried_names)

        self._prefix_raw_fn = prefix_fn
        self._prefix_param_names = list(param_names)
        return prefix_fn, feed_names, tuple(param_vals)

    def run_feeds(self, feeds) -> np.ndarray:
        """Full path from RAW FEEDS: `feeds` maps each data var to a
        micro-batched [M, batch, ...] array; the program's prefix
        (embedding, attention-bias computation) is vmapped over the
        micro-batch dim to produce the pipeline input and every carried
        side input, then the stages stream as usual.  This is how an
        embedding-fronted encoder stack serves without the caller
        precomputing hidden states."""
        import jax.numpy as jnp

        if not self._prefix_ops:
            raise ValueError(
                "this pipeline has no prefix (boundaries[0] is a feed); "
                "call run(x_microbatches, carried=...) directly")
        if self._prefix is None:
            prefix_fn, feed_names, pvals = self._make_prefix_fn()
            # jit the vmapped prefix ONCE (params replicated across the
            # micro-batch vmap): a serving loop must not pay op-by-op
            # dispatch or param-table re-upload per request
            self._prefix = (
                jax.jit(jax.vmap(prefix_fn, in_axes=(None, 0))),
                feed_names, pvals)
        prefix_jit, feed_names, pvals = self._prefix
        missing = [n for n in feed_names if n not in feeds]
        if missing:
            raise ValueError(f"run_feeds needs micro-batched arrays for "
                             f"{feed_names}; missing {missing}")
        fvals = {n: jnp.asarray(feeds[n]) for n in feed_names}
        x0, ctup = prefix_jit(pvals, fvals)
        if self._stage_fn is None:
            self._stage_fn = self._make_stage_fn()
        if self._stacked is None:
            self._stacked = self._stacked_params()
        out = self._serve()(self._stacked, x0, ctup)
        return np.asarray(out)

    def _stacked_params(self):
        """Stack segment s's parameter values stage-major: leaf j has
        shape [S, *param_j.shape], sharded on pp by pipeline_apply."""
        import jax.numpy as jnp

        from jax.sharding import NamedSharding, PartitionSpec

        per_stage = []
        for seg in self._segments:
            vals = []
            for n in seg.params:
                v = self.scope.find_var(n)
                if v is None:
                    raise ValueError(f"parameter '{n}' not found in scope — "
                                     "run the startup program first")
                vals.append(np.asarray(v))
            per_stage.append(vals)
        stacked = tuple(
            jnp.stack([np.asarray(per_stage[s][j])
                       for s in range(self.num_stages)])
            for j in range(len(per_stage[0]))
        )
        # commit each leaf with its pipeline sharding up front: fresh
        # host arrays and the sharded arrays a previous train_step
        # returned must present the SAME aval, or the second call pays a
        # silent full recompile (committed-ness is part of jax's
        # lowering cache key — the executor rng bug's sibling)
        jmesh = self.mesh.mesh if hasattr(self.mesh, "mesh") else self.mesh
        return tuple(
            jax.device_put(s, NamedSharding(
                jmesh,
                PartitionSpec(self.pp_axis, *([None] * (s.ndim - 1)))))
            for s in stacked
        )

    def _warn_cache_growth(self, cache_key) -> None:
        if cache_key not in self._train_cache and len(self._train_cache) >= 4:
            import logging

            logging.getLogger("paddle_tpu").warning(
                "ProgramPipeline has compiled %d distinct loss_fn "
                "variants — if you are passing a fresh lambda each step, "
                "hoist it out of the loop: every new object retraces and "
                "recompiles the whole pipelined fwd+bwd",
                len(self._train_cache) + 1)

    @staticmethod
    def _sgd_update(params, grads, vel, lr_, mom_, use_momentum):
        """The ONE copy of the tuple SGD(+momentum) rule shared by both
        training paths."""
        if use_momentum:
            vel = tuple(mom_ * v + g for v, g in zip(vel, grads))
            upd = vel
        else:
            upd = grads
        return tuple(p - lr_ * u for p, u in zip(params, upd)), vel

    def train_step(self, x_microbatches, y_microbatches, loss_fn,
                   lr: float = 0.01, momentum: float = 0.0,
                   carried=None) -> float:
        """One pipelined GPipe TRAINING step through the Program-derived
        stages: forward streams the micro-batches over the pp axis,
        backward flows through the same schedule (jax.grad over
        pipeline_apply — ppermute/scan transpose to the reverse hops;
        gradient parity with serial execution is the test contract), and
        the stacked per-stage parameters take an SGD(+momentum) update
        held device-side (call sync_to_scope() to publish the trained
        slices to the scope for Executor use / checkpoint io).

        loss_fn(out_m, y_m) -> scalar per micro-batch; the step optimizes
        mean over micro-batches.  Returns the step's mean loss.  This is
        the pipeline sibling of Executor.run on a program whose optimizer
        ops do the update; full fluid-optimizer parity on stage-sharded
        params (Adam state etc.) stays with ParallelExecutor's dp/tp/sp
        path."""
        import jax
        import jax.numpy as jnp

        self._check_untied()
        if self._stage_fn is None:
            self._stage_fn = self._make_stage_fn()
        if self._stacked is None:
            self._stacked = self._stacked_params()
        x = jnp.asarray(x_microbatches)
        y = jnp.asarray(y_microbatches)
        if x.ndim < 2:
            raise ValueError("x_microbatches must be [M, batch, ...]")
        ctup = self._carried_tuple(carried, x.shape[0])

        use_momentum = bool(momentum)
        # ONE jitted update per (loss_fn, momentum arity): a fresh
        # closure per call would silently recompile the whole pipelined
        # fwd+bwd every step (the executor rng-commit bug's sibling);
        # lr/momentum ride as dynamic scalars so tuning them is free.
        # REUSE THE SAME loss_fn OBJECT across steps — a lambda built
        # inside the training loop defeats the cache (warned below)
        cache_key = (id(loss_fn), use_momentum)
        self._warn_cache_growth(cache_key)
        entry = self._train_cache.get(cache_key)
        update = entry[0] if entry else None
        if update is None:
            stage_fn, mesh, pp_axis = self._stage_fn, self.mesh, self.pp_axis

            def update_fn(params, vel, xs, cs, ys, lr_, mom_):
                def objective(p):
                    out = pipeline_apply(stage_fn, p, xs, mesh,
                                         pp_axis=pp_axis, aux=cs)
                    return jnp.mean(jax.vmap(loss_fn)(out, ys))

                loss, grads = jax.value_and_grad(objective)(params)
                new_p, vel = ProgramPipeline._sgd_update(
                    params, grads, vel, lr_, mom_, use_momentum)
                return loss, new_p, vel

            update = jax.jit(update_fn)
            # store loss_fn alongside: the closure already pins it, but
            # the explicit reference makes the id()-keying safe by
            # construction (a dead object's id could otherwise recycle)
            self._train_cache[cache_key] = (update, loss_fn)

        if use_momentum and not hasattr(self, "_vel"):
            self._vel = tuple(jnp.zeros_like(p) for p in self._stacked)
        vel = self._vel if use_momentum else ()
        loss, self._stacked, vel = update(
            self._stacked, vel, x, ctup, y, jnp.float32(lr),
            jnp.float32(momentum))
        if use_momentum:
            self._vel = vel
        return float(loss)

    def sync_to_scope(self) -> None:
        """Write the trained per-stage parameter slices back to the
        scope (device->host, one transfer per param per stage).  Deferred
        out of train_step so a training loop pays it once before
        Executor use / checkpoint io, not every step."""
        if self._stacked is not None:
            for s, seg in enumerate(self._segments):
                for j, name in enumerate(seg.params):
                    self.scope.set_var(name,
                                       np.asarray(self._stacked[j][s]))
        # only TRAINED prefix params publish: the untrained snapshot must
        # not clobber scope values someone updated after it was taken
        if (getattr(self, "_prefix_trained", False)
                and self._prefix is not None):
            for name, val in zip(self._prefix_param_names,
                                 self._prefix[2]):
                self.scope.set_var(name, np.asarray(val))

    def train_step_feeds(self, feeds, y_microbatches, loss_fn,
                         lr: float = 0.01, momentum: float = 0.0) -> float:
        """End-to-end pipelined training from RAW FEEDS: gradients flow
        through the pipeline schedule AND the vmapped prefix, so the
        embedding/bias tables train together with the stage-stacked
        params (pretraining a pipelined encoder from tokens).  Same
        SGD(+momentum) and caching contract as train_step;
        sync_to_scope publishes both parameter sets."""
        import jax
        import jax.numpy as jnp

        self._check_untied()
        if not self._prefix_ops:
            raise ValueError("this pipeline has no prefix; use train_step")
        if self._stage_fn is None:
            self._stage_fn = self._make_stage_fn()
        if self._stacked is None:
            self._stacked = self._stacked_params()
        if self._prefix is None:
            prefix_fn, feed_names, pvals = self._make_prefix_fn()
            self._prefix = (
                jax.jit(jax.vmap(prefix_fn, in_axes=(None, 0))),
                feed_names, pvals)
        _, feed_names, pvals = self._prefix
        missing = [n for n in feed_names if n not in feeds]
        if missing:
            raise ValueError(f"train_step_feeds needs micro-batched "
                             f"arrays for {feed_names}; missing {missing}")
        fvals = {n: jnp.asarray(feeds[n]) for n in feed_names}
        y = jnp.asarray(y_microbatches)

        # a param read by BOTH the prefix and a stage would train as two
        # independent copies (split gradients, divergence): reject
        stage_params = {n for seg in self._segments for n in seg.params}
        tied = sorted(stage_params & set(self._prefix_param_names))
        if tied:
            raise ValueError(
                f"parameters {tied} are read by both the prefix and a "
                "stage: tied prefix/stage weights cannot be trained as "
                "two copies (forward run_feeds supports them)")

        use_momentum = bool(momentum)
        cache_key = ("feeds", id(loss_fn), use_momentum)
        self._warn_cache_growth(cache_key)
        entry = self._train_cache.get(cache_key)
        update = entry[0] if entry else None
        if update is None:
            stage_fn, mesh, pp_axis = (self._stage_fn, self.mesh,
                                       self.pp_axis)
            prefix_raw = self._prefix_raw_fn

            def update_fn(stacked, pparams, vel, fv, ys, lr_, mom_):
                def objective(both):
                    st, pp_ = both
                    x0, ctup = jax.vmap(
                        prefix_raw, in_axes=(None, 0))(pp_, fv)
                    out = pipeline_apply(stage_fn, st, x0, mesh,
                                         pp_axis=pp_axis, aux=ctup)
                    return jnp.mean(jax.vmap(loss_fn)(out, ys))

                loss, grads = jax.value_and_grad(objective)(
                    (stacked, pparams))
                gs, gp = grads
                vs, vp = vel if use_momentum else ((), ())
                new_s, vs = ProgramPipeline._sgd_update(
                    stacked, gs, vs, lr_, mom_, use_momentum)
                new_p, vp = ProgramPipeline._sgd_update(
                    pparams, gp, vp, lr_, mom_, use_momentum)
                return loss, new_s, new_p, (vs, vp)

            update = jax.jit(update_fn)
            self._train_cache[cache_key] = (update, loss_fn)

        if use_momentum and not hasattr(self, "_vel_feeds"):
            self._vel_feeds = (
                tuple(jnp.zeros_like(p) for p in self._stacked),
                tuple(jnp.zeros_like(p) for p in pvals),
            )
        vel = self._vel_feeds if use_momentum else ((), ())
        loss, self._stacked, new_pvals, vel = update(
            self._stacked, pvals, vel, fvals, y, jnp.float32(lr),
            jnp.float32(momentum))
        self._prefix = (self._prefix[0], feed_names, tuple(new_pvals))
        self._prefix_trained = True
        if use_momentum:
            self._vel_feeds = vel
        return float(loss)

    def refresh_params(self) -> None:
        """Drop the cached stacked parameters AND the momentum velocity;
        the next run()/train_step re-reads the scope.  Call after
        overwriting weights (e.g. a checkpoint load) — stale velocity
        from the discarded trajectory must not steer the restored
        weights (the prefix snapshots embedding tables at build time, so
        it re-reads the scope too)."""
        self._stacked = None
        self._prefix = None
        if hasattr(self, "_vel"):
            del self._vel
        if hasattr(self, "_vel_feeds"):
            del self._vel_feeds

    def _serve(self):
        """ONE jitted serving closure: pipeline_apply builds a fresh
        shard_map each call, so an unjitted serve would retrace and
        recompile the whole schedule per request (the train_step cache's
        sibling).  Params/activations ride as arguments; jax.jit caches
        per argument shape."""
        if self._serve_fn is None:
            stage_fn, mesh, pp_axis = (self._stage_fn, self.mesh,
                                       self.pp_axis)

            def serve(stacked, x, ctup):
                return pipeline_apply(stage_fn, stacked, x, mesh,
                                      pp_axis=pp_axis, aux=ctup)

            self._serve_fn = jax.jit(serve)
        return self._serve_fn

    def _carried_tuple(self, carried, M: int) -> tuple:
        """Validate/order the carried side inputs (dict name -> [M, ...]
        arrays) against the segments' carried names."""
        import jax.numpy as jnp

        names = self._segments[0].carried
        carried = carried or {}
        missing = [n for n in names if n not in carried]
        if missing:
            raise ValueError(
                f"stages read side inputs {names}; pass carried= with "
                f"per-micro-batch arrays (missing {missing})")
        unknown = sorted(set(carried) - set(names))
        if unknown:
            raise ValueError(
                f"carried keys {unknown} are not read by any stage "
                f"(stages read {names}) — a misnamed side input would "
                "otherwise be silently dropped")
        vals = []
        for n in names:
            v = jnp.asarray(carried[n])
            if v.shape[0] != M:
                raise ValueError(
                    f"carried '{n}' leading dim {v.shape[0]} != micro-"
                    f"batch count {M}")
            vals.append(v)
        return tuple(vals)

    def run(self, x_microbatches, carried=None) -> np.ndarray:
        """Stream [M, ...]-shaped micro-batches through the stages; returns
        [M, ...] outputs (replicated over pp).  `carried` maps each feed
        var the stages read (masks, segment ids) to its own [M, ...]
        micro-batched array — streamed alongside the activation.

        The stacked parameters are read from the scope ONCE and cached —
        a serving loop pays the host-side stack + device transfer only on
        the first call; refresh_params() invalidates after weight swaps."""
        if self._stage_fn is None:
            self._stage_fn = self._make_stage_fn()
        if self._stacked is None:
            self._stacked = self._stacked_params()
        import jax.numpy as jnp

        x = jnp.asarray(x_microbatches)
        if x.ndim < 2:
            raise ValueError("x_microbatches must be [M, batch, ...]")
        ctup = self._carried_tuple(carried, x.shape[0])
        out = self._serve()(self._stacked, x, ctup)
        return np.asarray(out)
