"""Multi-host coordination (reference: gen_nccl_id op + transpiler nccl2 mode,
distribute_transpiler.py:213, platform/nccl_helper.h:120 rank math).

The reference broadcast an ncclUniqueId over gRPC and computed global ranks
as trainer_id * ngpu + i.  JAX replaces all of that with the coordination
service: `jax.distributed.initialize` wires every host into one global
device list, and meshes built from `jax.devices()` span the pod.  The
PADDLE_* cluster env vars keep working as the spelling."""

from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["init_distributed", "trainer_id", "num_trainers"]

_initialized = False


def trainer_id() -> int:
    v = os.environ.get("PADDLE_TRAINER_ID")
    # don't touch jax.process_index() unless needed: it initializes the
    # backend, which must not happen before jax.distributed.initialize
    return int(v) if v is not None else jax.process_index()


def num_trainers() -> int:
    v = os.environ.get("PADDLE_TRAINERS_NUM")
    return int(v) if v is not None else jax.process_count()


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host job.  Arguments default from the reference's
    cluster env spelling (PADDLE_TRAINER_ENDPOINTS/PADDLE_TRAINER_ID,
    benchmark/fluid/fluid_benchmark.py:63-101) when present."""
    global _initialized
    if _initialized:
        return
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    if coordinator_address is None and eps:
        coordinator_address = eps.split(",")[0]
    if coordinator_address is None:
        _initialized = True  # single host
        return
    if num_processes is None:
        v = os.environ.get("PADDLE_TRAINERS_NUM")
        if v is not None:
            num_processes = int(v)
        elif eps:
            num_processes = len(eps.split(","))
    if process_id is None:
        v = os.environ.get("PADDLE_TRAINER_ID")
        process_id = int(v) if v is not None else None
    try:
        # CPU multiprocess collectives need the gloo transport; without it
        # jaxlib's CPU backend rejects multi-host computations outright
        # ("Multiprocess computations aren't implemented").  TPU backends
        # ignore this setting.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # older/newer jax may not expose the knob
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
