"""Program graph visualization
(reference: python/paddle/fluid/net_drawer.py — draws ops/vars of a
program as a Graphviz digraph).  Emits DOT text directly so no graphviz
python package is needed; feed the output to `dot -Tpng`.
"""

from __future__ import annotations

import json
from typing import Optional

from .core.framework import Program, default_main_program, default_startup_program

__all__ = ["draw_graph", "parse_graph"]

OP_STYLE = 'shape=box, style="rounded,filled", fillcolor="#b5d3ff"'
VAR_STYLE = 'shape=oval, style=filled, fillcolor="#dddddd"'
PARAM_STYLE = 'shape=oval, style=filled, fillcolor="#c8f7c5"'


def _esc(s: str) -> str:
    return s.replace('"', '\\"')


def parse_graph(program: Program, graph: list, var_dict: dict,
                name_prefix: str = "", params: Optional[set] = None) -> None:
    """Append DOT lines for one program (reference: net_drawer.parse_graph)."""
    block = program.global_block()
    if params is None:
        params = {p.name for p in block.all_parameters()}
    for name in block.desc.vars:
        if name in var_dict:
            continue
        var_dict[name] = f'var_{len(var_dict)}'
        style = PARAM_STYLE if name in params else VAR_STYLE
        graph.append(f'  {var_dict[name]} [label="{_esc(name)}", {style}];')
    for i, op in enumerate(block.desc.ops):
        op_id = f"op_{name_prefix}{i}"
        graph.append(f'  {op_id} [label="{_esc(op.type)}", {OP_STYLE}];')
        for n in op.input_arg_names():
            if n in var_dict:
                graph.append(f"  {var_dict[n]} -> {op_id};")
        for n in op.output_arg_names():
            if n in var_dict:
                graph.append(f"  {op_id} -> {var_dict[n]};")


def draw_graph(startup_program: Optional[Program] = None,
               main_program: Optional[Program] = None,
               name: str = "network", path: Optional[str] = None) -> str:
    """Render both programs into one DOT digraph; returns the DOT text and
    writes it to `path` when given (reference: net_drawer.draw_graph)."""
    startup_program = startup_program or default_startup_program()
    main_program = main_program or default_main_program()
    graph = [f'digraph "{_esc(name)}" {{', "  rankdir=TB;"]
    var_dict: dict = {}
    # params are registered on the MAIN program; the startup program sees
    # the same names first (it initializes them), so share the set
    params = {p.name for p in main_program.global_block().all_parameters()}
    parse_graph(startup_program, graph, var_dict, name_prefix="s",
                params=params)
    parse_graph(main_program, graph, var_dict, name_prefix="m",
                params=params)
    graph.append("}")
    dot = "\n".join(graph)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
