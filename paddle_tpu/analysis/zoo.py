"""The model zoo the chip-less linter gates on, and the gate itself.

Three programs cover the repo's three hot paths at CI scale (small
batch/sequence — the AOT v5e pipeline prices the same per-op structure
the banked full-scale artifacts measured, in ~2 min total on a CPU
host):

  resnet50_train     full ResNet-50 train step (Momentum), bs=2, 64x64
                     — the conv/BN pillar (AOT_COST_AB.json's program at
                     bench scale)
  transformer_train  2-layer flash-attention transformer train step
                     (Adam, fused qkv), bs=4, S=32 — the attention
                     pillar, pallas custom calls included
  paged_decode       the serving decode attention step at the banked
                     AOT_COST_PAGED shape (B=4 H=8 D=128, 512 cached
                     tokens), pallas page-streaming impl — bytes/step
                     counts the analytic page-stream traffic on top of
                     the XLA-visible bytes, same methodology as the
                     banked artifact
  gqa_decode         the paged_decode geometry with GROUPED-QUERY heads
                     (ISSUE 12): H_q=8 query heads over an H_kv=2 pool,
                     so the pallas grid walks (B, H_kv, pages) and each
                     KV page streams ONCE per sequence while its 4-head
                     query group shares it in VMEM — the banked KV
                     page-stream bytes/step must sit at ~H_kv/H_q x the
                     paged_decode baseline (tests assert within 10%),
                     and int8 pages halve it again (priced analytically
                     in the same test)
  spec_verify        the gqa_decode geometry fed Sq = 1+4 query rows
                     per sequence (ISSUE 13 speculative multi-token
                     verify, ragged q_lengths scalar-prefetched): the
                     page walk is UNCHANGED, so banked bytes/step at
                     d=4 must stay well under 2x the d=0 gqa_decode
                     step — >= 2x effective bytes-per-token reduction
                     at full acceptance (tests assert it), with a
                     known-bad corpus arm (spec_verify_gather) proving
                     the full-gather re-materialization trips the
                     bytes gate
  spec_verify_spmd   the sharded_decode step fed Sq = 1+4 query rows
                     per sequence (ISSUE 16 mesh speculation): the
                     shard-mapped verify body over an H_kv=4 GQA pool,
                     one KV head per chip — banked per-chip bytes/step
                     (plus each chip's analytic page-stream share)
                     proves mesh verify pays the decode step's page
                     walk, with a known-bad corpus arm
                     (spec_verify_spmd_gather) re-materializing each
                     shard's full gather and tripping the bytes gate
  lora_decode        the batched per-row LoRA apply at the multi-tenant
                     serving shape (ISSUE 19): each batch row gathers
                     its OWN adapter's packed A/B factors by slot index
                     (slot 0 = the zero identity for base-model rows)
                     and adds ``(x @ A) @ B`` on top of the dense
                     matmul, per layer — the banked bytes/step prices
                     the slot-gather traffic (rows x layers x
                     rank-factor bytes), holding the "adapters cost
                     gathers, not dense copies" property under the gate
  longctx_decode     the long-context serving decode step (ISSUE 20):
                     GQA int8 decode at ~1k pages/seq over a 16k-page
                     pool, sliding-window + attention-sink operands,
                     walked through the TWO-LEVEL page-table view so
                     the scalar-prefetch SMEM rides the walked L2
                     blocks — the flat contract at this shape
                     overflows the ~128 KB SMEM envelope (the
                     longctx_flat_pool corpus arm proves the
                     smem-overflow detector trips the gate there)
  prefix_decode      the same decode step under 8-way prefix sharing
                     (ISSUE 11): every sequence's page table walks ONE
                     refcounted shared 28-page prefix plus a private
                     4-page tail, so the pool is 60 pages instead of
                     256 — storage shrinks ~4x while the analytic
                     per-step stream (read-per-reader) stays honest
  sharded_decode     the tensor-parallel serving decode step
                     (serving/distributed/sharded.py) under shard_map
                     over a 4-chip v5e 2x2 mesh — full transformer
                     step with head-sharded QKV/pool, psum joins, and
                     the per-shard pallas page walk; the analyzed HLO
                     is the PER-CHIP partitioned module, so its banked
                     bytes/step is per-chip (plus each chip's analytic
                     page-stream share), and the SPMD collectives are
                     in scope for collective-placement

Baselines live in AOT_COST_ZOO.json: per-program finding counts by
detector plus AOT bytes/step + flops/step (extending AOT_COST_AB /
AOT_COST_PAGED into one gated table).  ``gate()`` fails on any new
finding (count above baseline, or a program with no banked entry) and on
a bytes/step regression past tolerance — the per-PR perf-regression CI
gate that runs with no chip attached.
"""

from __future__ import annotations

import contextlib
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .capture import ProgramArtifacts, capture_executor, capture_fn
from .detectors import run_detectors
from .findings import Finding, sort_findings

__all__ = ["ZOO", "ZooResult", "run_zoo", "bank", "gate",
           "default_baseline_path"]

DEFAULT_TOLERANCE = 0.02  # the AOT cost model is deterministic per
                          # jax/libtpu version; 2% absorbs pipeline noise


@dataclass
class ZooResult:
    name: str
    artifacts: ProgramArtifacts
    findings: List[Finding]
    bytes_per_step: float   # cost-model bytes + any analytic correction
    flops_per_step: float
    config: Dict = field(default_factory=dict)

    def finding_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.detector] = counts.get(f.detector, 0) + 1
        return counts


@contextlib.contextmanager
def _fresh_env():
    """Build a zoo model in a guarded program/scope/name-counter sandbox:
    run_zoo() is public API, so a caller's live default program and
    global scope must survive it untouched (fresh name counters keep the
    banked ProgramDesc fingerprints stable across process histories)."""
    import paddle_tpu as fluid

    with fluid.program_guard(fluid.Program(), fluid.Program()), \
            fluid.scope_guard(fluid.Scope()), \
            fluid.unique_name.guard():
        yield fluid


def _build_resnet50() -> Tuple[ProgramArtifacts, float, Dict]:
    from paddle_tpu import models

    cfg = {"depth": 50, "batch": 2, "img": 64, "optimizer": "momentum"}
    with _fresh_env() as fluid:
        spec = models.resnet_imagenet(
            depth=50, class_num=100, img_shape=(3, cfg["img"], cfg["img"]))
        fluid.optimizer.MomentumOptimizer(0.1, 0.9).minimize(spec.loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        batch = spec.synthetic_batch(cfg["batch"])
        art = capture_executor(exe, feed=batch, fetch_list=[spec.loss],
                               name="resnet50_train")
    return art, 0.0, cfg


def _build_transformer() -> Tuple[ProgramArtifacts, float, Dict]:
    from paddle_tpu import models

    cfg = {"n_layer": 2, "n_head": 4, "d_model": 128, "d_inner": 256,
           "max_length": 32, "vocab": 512, "batch": 4, "flash": True,
           "fuse_qkv": True, "optimizer": "adam"}
    mcfg = models.TransformerConfig(
        src_vocab_size=cfg["vocab"], trg_vocab_size=cfg["vocab"],
        max_length=cfg["max_length"], n_layer=cfg["n_layer"],
        n_head=cfg["n_head"], d_model=cfg["d_model"],
        d_inner=cfg["d_inner"], use_flash_attention=cfg["flash"],
        fuse_qkv=cfg["fuse_qkv"], shard_weights=False)
    with _fresh_env() as fluid:
        spec = models.transformer(mcfg)
        fluid.optimizer.AdamOptimizer(1e-4).minimize(spec.loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        batch = spec.synthetic_batch(cfg["batch"])
        art = capture_executor(exe, feed=batch, fetch_list=[spec.loss],
                               name="transformer_train")
    return art, 0.0, cfg


def _build_paged_decode() -> Tuple[ProgramArtifacts, float, Dict]:
    import jax
    import jax.numpy as jnp

    from ..kernels.paged_attention import (
        attention_bytes_per_step, paged_decode_attention)

    # the banked AOT_COST_PAGED decode shape: 512 cached tokens/sequence
    B, H, D, ps, maxp = 4, 8, 128, 16, 32
    cfg = {"batch": B, "heads": H, "head_dim": D, "page_size": ps,
           "max_pages": maxp, "impl": "pallas"}
    P = B * maxp
    q = jax.ShapeDtypeStruct((B, H, 1, D), jnp.float32)
    kp = jax.ShapeDtypeStruct((H, P, ps, D), jnp.float32)
    tb = jax.ShapeDtypeStruct((B, maxp), jnp.int32)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32)
    art = capture_fn(
        lambda q, k, v, t, l: paged_decode_attention(
            q, k, v, t, l, impl="pallas"),
        q, kp, kp, tb, ln, name="paged_decode")
    # the SMEM-table-driven page DMAs are invisible to the XLA cost model
    # (AOT_COST_PAGED.json "method") — charge the full analytic stream so
    # the gated number is the honest one
    extra = float(attention_bytes_per_step("pallas", B, maxp, ps, H, D))
    return art, extra, cfg


# the gqa_decode geometry: the paged_decode shape with an H_kv=2 GQA
# pool — query heads stay at 8, the pool (and its page stream) shrink
# 4x.  ONE source of truth: the known-bad corpus arm (gqa_full_pool)
# captures the SAME geometry over a full-H_q pool, so retuning these
# numbers retunes the regression check with them.
GQA_DECODE_GEOM = {"batch": 4, "heads": 8, "kv_heads": 2,
                   "head_dim": 128, "page_size": 16, "max_pages": 32}


def capture_gqa_decode(pool_heads: int) -> ProgramArtifacts:
    """Capture the gqa_decode program over a pool holding `pool_heads`
    KV heads — the zoo entry passes H_kv (the win), the known-bad
    corpus arm passes H_q (the regression).  Both artifacts carry the
    zoo entry's name so they gate against the same banked baseline."""
    import jax
    import jax.numpy as jnp

    from ..kernels.paged_attention import paged_decode_attention

    g = GQA_DECODE_GEOM
    B, Hq, D, ps, maxp = (g["batch"], g["heads"], g["head_dim"],
                          g["page_size"], g["max_pages"])
    P = B * maxp
    q = jax.ShapeDtypeStruct((B, Hq, 1, D), jnp.float32)
    kp = jax.ShapeDtypeStruct((pool_heads, P, ps, D), jnp.float32)
    tb = jax.ShapeDtypeStruct((B, maxp), jnp.int32)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32)
    return capture_fn(
        lambda q, k, v, t, l: paged_decode_attention(
            q, k, v, t, l, impl="pallas"),
        q, kp, kp, tb, ln, name="gqa_decode")


def gqa_decode_stream_bytes(pool_heads: int) -> float:
    """The analytic page-stream correction for `capture_gqa_decode` —
    scales with the POOL's head count, same methodology as
    paged_decode."""
    from ..kernels.paged_attention import attention_bytes_per_step

    g = GQA_DECODE_GEOM
    return float(attention_bytes_per_step(
        "pallas", g["batch"], g["max_pages"], g["page_size"],
        g["heads"], g["head_dim"], num_kv_heads=pool_heads))


def _build_gqa_decode() -> Tuple[ProgramArtifacts, float, Dict]:
    g = GQA_DECODE_GEOM
    art = capture_gqa_decode(g["kv_heads"])
    cfg = dict(g, impl="pallas")
    return art, gqa_decode_stream_bytes(g["kv_heads"]), cfg


# the spec_verify geometry: the gqa_decode shape fed Sq = 1+d query
# rows per sequence (the speculative multi-token verify step, ISSUE
# 13) with ragged q_lengths.  The whole point of banking it: the KV
# page stream is INVARIANT in d — verify bytes/step at d=4 must stay
# well under 2x the d=0 gqa_decode step (tests assert it), i.e. >= 2x
# effective bytes-per-token reduction at full acceptance.  ONE source
# of truth with the known-bad corpus arm (spec_verify_gather): the
# same geometry through the full [B,H,S,D] gather re-materialization
# prices far above the banked stream and must trip the bytes gate.
SPEC_VERIFY_Q_TOKENS = 5  # 1 + d at the banked draft depth d=4


def capture_spec_verify(gather: bool) -> ProgramArtifacts:
    """Capture the spec_verify program — ``gather=False`` is the zoo
    entry (pallas multi-token page walk, q_lengths scalar-prefetched);
    ``gather=True`` is the known-bad arm: the SAME verify contract
    re-materializing the contiguous [B, H, S, D] gather (the reference
    tier) instead of streaming pages.  Both artifacts carry the zoo
    entry's name so they gate against the same banked baseline."""
    import jax
    import jax.numpy as jnp

    from ..kernels.paged_attention import paged_decode_attention

    g = GQA_DECODE_GEOM
    B, Hq, Hkv, D, ps, maxp = (g["batch"], g["heads"], g["kv_heads"],
                               g["head_dim"], g["page_size"],
                               g["max_pages"])
    Sq = SPEC_VERIFY_Q_TOKENS
    P = B * maxp
    q = jax.ShapeDtypeStruct((B, Hq, Sq, D), jnp.float32)
    kp = jax.ShapeDtypeStruct((Hkv, P, ps, D), jnp.float32)
    tb = jax.ShapeDtypeStruct((B, maxp), jnp.int32)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32)
    impl = "reference" if gather else "pallas"
    # the serving step immediately folds the attention output into the
    # [rows, d_model] matmul operand; capturing that consumer shape
    # keeps the program boundary honest — a bare [B,H,Sq,D] output
    # would add an entry-layout relayout copy no real caller pays
    return capture_fn(
        lambda q, k, v, t, l, ql: paged_decode_attention(
            q, k, v, t, l, impl=impl,
            q_lengths=ql).reshape(B * Hq * Sq, D),
        q, kp, kp, tb, ln, ln, name="spec_verify")


def spec_verify_stream_bytes() -> float:
    """The analytic page-stream correction for the pallas spec_verify
    arm — the gqa_decode stream plus the q_tokens query/output term,
    the ONLY part that grows with d."""
    from ..kernels.paged_attention import attention_bytes_per_step

    g = GQA_DECODE_GEOM
    return float(attention_bytes_per_step(
        "pallas", g["batch"], g["max_pages"], g["page_size"],
        g["heads"], g["head_dim"], num_kv_heads=g["kv_heads"],
        q_tokens=SPEC_VERIFY_Q_TOKENS))


def _build_spec_verify() -> Tuple[ProgramArtifacts, float, Dict]:
    art = capture_spec_verify(gather=False)
    cfg = dict(GQA_DECODE_GEOM, q_tokens=SPEC_VERIFY_Q_TOKENS,
               impl="pallas")
    return art, spec_verify_stream_bytes(), cfg


def _build_sharded_decode() -> Tuple[ProgramArtifacts, float, Dict]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from ..core.aot_tpu import tpu_topology
    from ..kernels.paged_attention import attention_bytes_per_step
    from ..serving.distributed import sharded as _sh
    from ..serving.generate import DecodeConfig

    # the paged_decode attention geometry (H=8, D=128, ps=16), grown to
    # the full decode step and split 4 ways
    n, B, num_pages, maxp, ps = 4, 4, 64, 8, 16
    dcfg = DecodeConfig(vocab_size=256, d_model=1024, n_head=8,
                        n_layer=1, d_inner=2048, max_length=maxp * ps)
    cfg = {"n_shards": n, "batch": B, "heads": dcfg.n_head,
           "head_dim": dcfg.head_dim, "d_model": dcfg.d_model,
           "n_layer": dcfg.n_layer, "vocab": dcfg.vocab_size,
           "num_pages": num_pages, "max_pages": maxp, "page_size": ps,
           "impl": "pallas", "topology": "v5e:2x2"}
    topo = tpu_topology("v5e:2x2", chips_per_host=(2, 2, 1))
    mesh = Mesh(np.array(topo.devices), (_sh.AXIS_TP,))
    kv_spec = PartitionSpec(None, _sh.AXIS_TP, None, None, None)
    body = _sh.decode_step_fn(dcfg, n, impl=cfg["impl"])
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(_sh.param_partition_specs(dcfg),)
        + (PartitionSpec(),) * 6 + (kv_spec, kv_spec),
        out_specs=(PartitionSpec(), kv_spec, kv_spec),
        check_vma=False)  # no replication rule for pallas_call
    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
    kv = jax.ShapeDtypeStruct(
        (dcfg.n_layer, dcfg.n_head, num_pages, ps, dcfg.head_dim),
        jnp.float32)
    rep = NamedSharding(mesh, PartitionSpec())
    # the layout-consumption contract (ISSUE 14): the pool args carry
    # the XLA-preferred {3,0,2,1}-major shard layout the paged kernel's
    # pool_layout="xla" arm consumes — banked relayout-copy-pair count
    # is 0 BY CONSTRUCTION, and the gate holds it there
    kv_io = _sh.kv_pool_layout(NamedSharding(mesh, kv_spec))
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), _sh.param_partition_specs(dcfg),
        is_leaf=lambda x: isinstance(x, PartitionSpec))
    art = capture_fn(
        fn, _sh.param_shape_dtypes(dcfg), i32(B), i32(B), i32(B), i32(B),
        i32(B, maxp), i32(B), kv, kv,
        name="sharded_decode",
        topology=topo,
        # the pool shards alias in->out (the on-chip in-place append)
        donate_argnums=(7, 8),
        in_shardings=(param_sh,) + (rep,) * 6 + (kv_io, kv_io),
        out_shardings=(rep, kv_io, kv_io))
    # per-chip analytic page-stream share: each chip walks its OWN
    # heads' pages (H/n of the batch's KV traffic), invisible to the
    # XLA cost model like the single-device paged_decode entry
    extra = float(attention_bytes_per_step(
        cfg["impl"], B, maxp, ps, dcfg.n_head // n, dcfg.head_dim,
        num_layers=dcfg.n_layer))
    return art, extra, cfg


# the spec_verify_spmd geometry: the sharded_decode step fed Sq = 1+d
# query rows per sequence (ISSUE 16 — mesh speculation), with an
# H_kv=4 GQA pool so each chip holds ONE KV head and the query group
# shares its page stream.  ONE source of truth with the known-bad
# corpus arm (spec_verify_spmd_gather): the same mesh program through
# the reference full-gather tier (which also re-expands K/V over the
# query group) prices far above the banked per-chip page stream and
# must trip the bytes gate.
SPEC_VERIFY_SPMD_GEOM = {
    "n_shards": 4, "batch": 4, "heads": 8, "kv_heads": 4,
    "num_pages": 256, "max_pages": 64, "page_size": 16,
    "d_model": 1024, "n_layer": 1, "vocab": 256,
    "q_tokens": SPEC_VERIFY_Q_TOKENS, "topology": "v5e:2x2",
}


def capture_spec_verify_spmd(gather: bool) -> ProgramArtifacts:
    """Capture the spec_verify_spmd program — ``gather=False`` is the
    zoo entry (per-shard pallas multi-token page walk under shard_map,
    pool args pinned to the XLA-preferred layout like sharded_decode);
    ``gather=True`` is the known-bad arm: the SAME mesh verify contract
    re-materializing each shard's contiguous [B, H, S, D] gather (the
    reference tier) instead of streaming pages.  Both artifacts carry
    the zoo entry's name so they gate against the same banked
    baseline."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from ..core.aot_tpu import tpu_topology
    from ..serving.distributed import sharded as _sh
    from ..serving.generate import DecodeConfig

    g = SPEC_VERIFY_SPMD_GEOM
    n, B = g["n_shards"], g["batch"]
    num_pages, maxp, ps = g["num_pages"], g["max_pages"], g["page_size"]
    Sq = g["q_tokens"]
    dcfg = DecodeConfig(
        vocab_size=g["vocab"], d_model=g["d_model"], n_head=g["heads"],
        n_kv_head=g["kv_heads"], n_layer=g["n_layer"],
        d_inner=2 * g["d_model"], max_length=maxp * ps)
    topo = tpu_topology(g["topology"], chips_per_host=(2, 2, 1))
    mesh = Mesh(np.array(topo.devices), (_sh.AXIS_TP,))
    kv_spec = PartitionSpec(None, _sh.AXIS_TP, None, None, None)
    impl = "reference" if gather else "pallas"
    body = _sh.verify_step_fn(dcfg, n, impl=impl)
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(_sh.param_partition_specs(dcfg),)
        + (PartitionSpec(),) * 9 + (kv_spec, kv_spec),
        out_specs=(PartitionSpec(), kv_spec, kv_spec),
        check_vma=False)
    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
    kv = jax.ShapeDtypeStruct(
        (dcfg.n_layer, dcfg.num_kv_heads, num_pages, ps, dcfg.head_dim),
        jnp.float32)
    rep = NamedSharding(mesh, PartitionSpec())
    # the zoo arm pins the pool layout contract sharded_decode banks
    # (relayout-copy-pair 0 by construction); the gather arm leaves the
    # layout free — the regression it models never made that promise
    kv_sh = NamedSharding(mesh, kv_spec)
    kv_io = kv_sh if gather else _sh.kv_pool_layout(kv_sh)
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), _sh.param_partition_specs(dcfg),
        is_leaf=lambda x: isinstance(x, PartitionSpec))
    return capture_fn(
        fn, _sh.param_shape_dtypes(dcfg),
        i32(B, Sq), i32(B, Sq), i32(B), i32(B, maxp), i32(B),
        i32(B * Sq), i32(B * Sq), i32(B * Sq), i32(B * Sq), kv, kv,
        name="spec_verify_spmd",
        topology=topo,
        donate_argnums=(10, 11),
        in_shardings=(param_sh,) + (rep,) * 9 + (kv_io, kv_io),
        out_shardings=(rep, kv_io, kv_io))


def spec_verify_spmd_stream_bytes() -> float:
    """Per-chip analytic page-stream share for the pallas
    spec_verify_spmd arm: each chip walks its OWN KV head's pages
    (H_kv/n of the batch's KV traffic) plus the q_tokens query/output
    term — the only part that grows with d."""
    from ..kernels.paged_attention import attention_bytes_per_step

    g = SPEC_VERIFY_SPMD_GEOM
    n = g["n_shards"]
    return float(attention_bytes_per_step(
        "pallas", g["batch"], g["max_pages"], g["page_size"],
        g["heads"] // n, g["d_model"] // g["heads"],
        num_layers=g["n_layer"],
        num_kv_heads=g["kv_heads"] // n, q_tokens=g["q_tokens"]))


def _build_spec_verify_spmd() -> Tuple[ProgramArtifacts, float, Dict]:
    art = capture_spec_verify_spmd(gather=False)
    cfg = dict(SPEC_VERIFY_SPMD_GEOM, impl="pallas")
    return art, spec_verify_spmd_stream_bytes(), cfg


# the lora_decode geometry: the batched per-row adapter apply from the
# multi-tenant serving step (serving/adapters.py + generate.py's
# _apply_adapters seam, ISSUE 19) at CI scale — a 4-row batch over an
# 8-slot pool, 2 layers, rank-8 factors.  The program IS the seam's
# math: gather each row's packed A/B by slot index, add the low-rank
# product on top of the dense matmul.  The gather traffic is
# XLA-visible, so no analytic correction — the banked bytes/step is the
# honest per-step adapter cost the gate holds.
LORA_DECODE_GEOM = {"batch": 4, "slots": 8, "n_layer": 2,
                    "d_model": 128, "rank": 8}


def _build_lora_decode() -> Tuple[ProgramArtifacts, float, Dict]:
    import jax
    import jax.numpy as jnp

    g = LORA_DECODE_GEOM
    B, S, L = g["batch"], g["slots"], g["n_layer"]
    d, r = g["d_model"], g["rank"]
    cfg = dict(g)
    # packs carry slots+1 rows: row 0 is the permanent zero identity
    # base-model rows index (AdapterPool.device_arrays layout)
    a_pack = jax.ShapeDtypeStruct((S + 1, L, d, r), jnp.float32)
    b_pack = jax.ShapeDtypeStruct((S + 1, L, r, d), jnp.float32)
    w = jax.ShapeDtypeStruct((L, d, d), jnp.float32)
    x = jax.ShapeDtypeStruct((B, d), jnp.float32)
    idx = jax.ShapeDtypeStruct((B,), jnp.int32)

    def fn(a_pack, b_pack, w, x, idx):
        h = x
        for li in range(L):
            al = a_pack[idx, li]           # [B, d, r] slot gather
            bl = b_pack[idx, li]           # [B, r, d]
            low = jnp.einsum("bd,bdr->br", h, al)
            h = h @ w[li] + jnp.einsum("br,bro->bo", low, bl)
        return h

    art = capture_fn(fn, a_pack, b_pack, w, x, idx, name="lora_decode")
    return art, 0.0, cfg


# the longctx_decode geometry (ISSUE 20): the GQA int8 decode step at
# the 32k-context serving shape — ~1k pages per sequence over a
# 16k-page pool — walked through the TWO-LEVEL page-table view with the
# sliding-window + attention-sink operands the long-context tier
# serves.  The whole point of banking it: at this scale the FLAT table
# contract's scalar-prefetch operands ([B, maxp] table + starts + two
# POOL-sized [P] fp32 scale rows) overflow the ~128 KB SMEM envelope,
# while the two-level view's SMEM rides the walked L2 blocks.  ONE
# source of truth with the known-bad corpus arm (longctx_flat_pool):
# the SAME geometry through the flat contract, flagged by the
# smem-overflow detector and priced against this entry's banked
# baseline — retuning this geometry retunes the regression check.
LONGCTX_DECODE_GEOM = {"batch": 4, "heads": 8, "kv_heads": 2,
                       "head_dim": 128, "page_size": 32,
                       "max_pages": 1024, "pool_pages": 16384,
                       "table_block": 128, "dtype": "int8"}


def capture_longctx_decode(two_level: bool) -> ProgramArtifacts:
    """Capture the longctx_decode program — ``two_level=True`` is the
    zoo entry (L1 directory + L2 block walk, block-gathered scale
    blocks); ``two_level=False`` is the known-bad arm: the SAME
    windowed int8 decode through the flat-table contract, whose
    scalar operands are pool-sized.  Both artifacts carry the zoo
    entry's name so they gate against the same banked baseline."""
    import jax
    import jax.numpy as jnp

    from ..kernels.paged_attention import (
        TwoLevelTables, paged_decode_attention)

    g = LONGCTX_DECODE_GEOM
    B, Hq, Hkv, D = g["batch"], g["heads"], g["kv_heads"], g["head_dim"]
    ps, maxp, P, bs = (g["page_size"], g["max_pages"], g["pool_pages"],
                       g["table_block"])
    q = jax.ShapeDtypeStruct((B, Hq, 1, D), jnp.float32)
    kp = jax.ShapeDtypeStruct((Hkv, P, ps, D), jnp.int8)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32)
    sc = jax.ShapeDtypeStruct((P,), jnp.float32)
    if two_level:
        n_l1 = maxp // bs
        n_blocks = B * n_l1 + 1  # + the shared all-padding block
        l1 = jax.ShapeDtypeStruct((B, n_l1), jnp.int32)
        blk = jax.ShapeDtypeStruct((n_blocks, bs), jnp.int32)
        return capture_fn(
            lambda q, k, v, l1, l2, st, l, w, s, ks, vs:
                paged_decode_attention(
                    q, k, v, TwoLevelTables(l1, l2, st, bs), l,
                    impl="pallas", windows=w, sinks=s,
                    k_scales=ks, v_scales=vs),
            q, kp, kp, l1, blk, blk, ln, ln, ln, sc, sc,
            name="longctx_decode")
    tb = jax.ShapeDtypeStruct((B, maxp), jnp.int32)
    return capture_fn(
        lambda q, k, v, t, st, l, w, s, ks, vs: paged_decode_attention(
            q, k, v, t, l, impl="pallas", page_starts=st,
            windows=w, sinks=s, k_scales=ks, v_scales=vs),
        q, kp, kp, tb, tb, ln, ln, ln, sc, sc,
        name="longctx_decode")


def longctx_decode_stream_bytes() -> float:
    """The analytic page-stream correction for longctx_decode — the
    int8 page walk over the full table width (each walked page also
    reads its two fp32 scales; ``attention_bytes_per_step`` charges
    them under ``dtype=int8``).  Identical for both table contracts:
    the two-level view changes what SMEM holds, never what HBM
    streams."""
    import jax.numpy as jnp

    from ..kernels.paged_attention import attention_bytes_per_step

    g = LONGCTX_DECODE_GEOM
    return float(attention_bytes_per_step(
        "pallas", g["batch"], g["max_pages"], g["page_size"],
        g["heads"], g["head_dim"], num_kv_heads=g["kv_heads"],
        dtype=jnp.int8))


def _build_longctx_decode() -> Tuple[ProgramArtifacts, float, Dict]:
    art = capture_longctx_decode(two_level=True)
    cfg = dict(LONGCTX_DECODE_GEOM, impl="pallas")
    return art, longctx_decode_stream_bytes(), cfg


def _build_prefix_decode() -> Tuple[ProgramArtifacts, float, Dict]:
    import jax
    import jax.numpy as jnp

    from ..kernels.paged_attention import (
        attention_bytes_per_step, paged_decode_attention)

    # the serving decode step under N-WAY PREFIX SHARING (ISSUE 11):
    # 8 sequences whose page tables all walk the SAME refcounted
    # shared-prefix pages (28 of each table's 32 entries) plus a
    # private 4-page tail, so the POOL holds one shared page-set + 8
    # tails (60 pages) instead of 8 x 32 = 256 — the table-indirection
    # property that makes an N-way-shared system prompt cost one
    # page-set.  The kernel is the same pallas page walk as
    # paged_decode (sharing lives entirely in the table CONTENT); the
    # analytic stream still charges each sequence's full walk — shared
    # pages are read once per READER, the honest per-step traffic
    B, H, D, ps = 8, 8, 128, 16
    shared_pages, tail_pages = 28, 4
    maxp = shared_pages + tail_pages
    pool_pages = shared_pages + B * tail_pages
    cfg = {"batch": B, "heads": H, "head_dim": D, "page_size": ps,
           "max_pages": maxp, "shared_pages": shared_pages,
           "tail_pages": tail_pages, "pool_pages": pool_pages,
           "impl": "pallas"}
    q = jax.ShapeDtypeStruct((B, H, 1, D), jnp.float32)
    kp = jax.ShapeDtypeStruct((H, pool_pages, ps, D), jnp.float32)
    tb = jax.ShapeDtypeStruct((B, maxp), jnp.int32)
    ln = jax.ShapeDtypeStruct((B,), jnp.int32)
    art = capture_fn(
        lambda q, k, v, t, l: paged_decode_attention(
            q, k, v, t, l, impl="pallas"),
        q, kp, kp, tb, ln, name="prefix_decode")
    extra = float(attention_bytes_per_step("pallas", B, maxp, ps, H, D))
    return art, extra, cfg


ZOO = {
    "resnet50_train": _build_resnet50,
    "transformer_train": _build_transformer,
    "paged_decode": _build_paged_decode,
    "gqa_decode": _build_gqa_decode,
    "spec_verify": _build_spec_verify,
    "spec_verify_spmd": _build_spec_verify_spmd,
    "lora_decode": _build_lora_decode,
    "longctx_decode": _build_longctx_decode,
    "prefix_decode": _build_prefix_decode,
    "sharded_decode": _build_sharded_decode,
}


def _corpus_builder(name: str):
    def build() -> Tuple[ProgramArtifacts, float, Dict]:
        from .corpus import build_corpus_program, corpus_extra_bytes

        return (build_corpus_program(name), corpus_extra_bytes(name),
                {"corpus": name})
    return build


def run_zoo(programs: Optional[Sequence[str]] = None,
            inject: Sequence[str] = (),
            detectors: Optional[Sequence[str]] = None,
            progress=None) -> List[ZooResult]:
    """Capture + lint every requested zoo program (default: all), plus
    any injected known-bad corpus programs (their results carry the
    corpus program's name, e.g. ``corpus_broadcast_lse``)."""
    from .corpus import CORPUS

    from .detectors import DETECTORS

    names = list(programs) if programs else list(ZOO)
    # validate EVERYTHING before the first expensive capture
    for d in detectors or ():
        if d not in DETECTORS:
            raise KeyError(
                f"unknown detector {d!r}; have {sorted(DETECTORS)}")
    builders = []
    for n in names:
        if n not in ZOO:
            raise KeyError(
                f"unknown zoo program {n!r}; have {sorted(ZOO)}")
        builders.append(ZOO[n])
    for n in inject:
        if n not in CORPUS:
            raise KeyError(
                f"unknown corpus program {n!r}; have {sorted(CORPUS)}")
        builders.append(_corpus_builder(n))
    results: List[ZooResult] = []
    for build in builders:
        art, extra_bytes, cfg = build()
        if progress:
            progress(f"captured {art.name} "
                     f"({art.bytes_per_step / 1e6:.1f} MB/step xla-visible)")
        # severity-then-bytes order everywhere findings surface (report
        # text and --json alike) so gate diffs never churn on detector
        # iteration order
        findings = sort_findings(run_detectors(art, detectors))
        results.append(ZooResult(
            name=art.name,
            artifacts=art,
            findings=findings,
            bytes_per_step=art.bytes_per_step + extra_bytes,
            flops_per_step=art.flops_per_step,
            config=cfg,
        ))
    return results


def default_baseline_path() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "AOT_COST_ZOO.json")


def bank(results: List[ZooResult], path: str,
         tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Write the zoo baseline artifact (the banked counterpart of
    AOT_COST_AB/AOT_COST_PAGED, now one gated table).  Refuses results
    whose AOT compile failed: banking bytes_per_step=0 would make every
    later healthy run look like a regression (and the broken one pass)."""
    broken = [r.name for r in results if r.artifacts.compile_error]
    if broken:
        raise ValueError(
            f"refusing to bank programs whose AOT compile failed: {broken}")
    doc = {
        "what": ("chip-less linter zoo baselines (paddle_tpu.analysis): "
                 "per-program finding counts by detector + the AOT v5e "
                 "cost model's bytes/step and flops/step, captured by "
                 "tools/lint_programs.py --bank on a CPU-only host. "
                 "lint_programs --gate fails PRs on any NEW finding or a "
                 "bytes/step regression past tolerance. paged_decode "
                 "bytes include the analytic page-stream traffic on top "
                 "of the XLA-visible bytes (AOT_COST_PAGED.json method)."),
        "tolerance": tolerance,
        "programs": {
            r.name: {
                "config": r.config,
                "bytes_per_step": r.bytes_per_step,
                "flops_per_step": r.flops_per_step,
                "findings": r.finding_counts(),
                "fingerprint": r.artifacts.fingerprint,
            }
            for r in results
        },
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def gate(results: List[ZooResult], baseline_path: str,
         tolerance: Optional[float] = None,
         require_all: bool = False) -> Tuple[List[dict], bool]:
    """Verdicts vs the banked baseline.  Returns (verdicts, failed).

    Fails on: a program with no banked entry (bank deliberately, don't
    drift), any detector whose finding count EXCEEDS the banked count
    (new finding), and a bytes/step rise past tolerance (the existing
    BENCH_BASELINE verdict machinery prices the regression).  With
    require_all (an unfiltered run), a BANKED program absent from the
    run also fails — deleting or renaming a zoo entry must not silently
    shrink CI coverage."""
    from ..observability import regression_verdict

    with open(baseline_path) as f:
        base = json.load(f)
    tol = tolerance if tolerance is not None else float(
        base.get("tolerance", DEFAULT_TOLERANCE))
    banked = base.get("programs", {})
    verdicts: List[dict] = []
    failed = False
    for r in results:
        # a program the pipeline REJECTED analyzed nothing HLO-side:
        # bytes collapse to 0 (lower-is-better would PASS) and the HLO
        # detectors go blind — that is a gate failure, never a pass
        if r.artifacts.compile_error:
            verdicts.append({
                "metric": f"{r.name}_compile", "verdict": "fail",
                "reason": ("AOT compile failed — nothing was analyzed: "
                           + r.artifacts.compile_error[:200]),
            })
            failed = True
            continue
        entry = banked.get(r.name)
        if entry is None:
            verdicts.append({
                "metric": f"{r.name}_findings", "verdict": "fail",
                "reason": "program has no banked baseline "
                          "(run --bank to add it deliberately)",
            })
            failed = True
            continue
        base_counts = entry.get("findings", {}) or {}
        cur_counts = r.finding_counts()
        for det in sorted(set(base_counts) | set(cur_counts)):
            cur, prev = cur_counts.get(det, 0), base_counts.get(det, 0)
            if cur > prev:
                verdicts.append({
                    "metric": f"{r.name}_findings[{det}]",
                    "baseline": prev, "current": cur, "verdict": "fail",
                    "reason": f"{cur - prev} new {det} finding(s)",
                })
                failed = True
            elif cur < prev:
                # strictly better — report so the baseline gets re-banked
                verdicts.append({
                    "metric": f"{r.name}_findings[{det}]",
                    "baseline": prev, "current": cur, "verdict": "pass",
                    "reason": "fewer findings than banked — re-bank",
                })
        bv = regression_verdict(
            f"{r.name}_aot_bytes_per_step",
            float(entry.get("bytes_per_step", 0.0)),
            r.bytes_per_step, tolerance=tol, higher_is_better=False)
        verdicts.append(bv)
        failed = failed or bv["verdict"] == "fail"
    if require_all:
        ran = {r.name for r in results}
        for name in sorted(set(banked) - ran):
            verdicts.append({
                "metric": f"{name}_coverage", "verdict": "fail",
                "reason": ("banked program missing from the run — "
                           "coverage shrank (re-bank deliberately if the "
                           "zoo entry was removed)"),
            })
            failed = True
    return verdicts, failed
