"""Known-bad regression corpus: each builder re-creates one hazard class
this repo actually shipped (or nearly shipped) and returns the captured
ProgramArtifacts.  tests/test_analysis.py asserts the linter flags each
with the right detector id, and ``lint_programs.py --inject <name>``
splices them into a zoo run so the CI gate's nonzero exit is provable
end-to-end.

These are small on purpose — every builder AOT-compiles chip-less in
seconds, so the corpus runs in tier-1.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .capture import capture_fn, ProgramArtifacts

__all__ = ["CORPUS", "build_corpus_program", "corpus_extra_bytes"]


def _broadcast_lse_operand() -> ProgramArtifacts:
    """The pre-PR-1 flash-attention residual bug: an lse-shaped [N]
    vector broadcast-materialized to [N, 128] as a pallas custom-call
    operand.  'XLA fuses it' was false — custom-call operands materialize
    at full size (67 MB/tensor at longcontext)."""
    import jax.experimental.pallas as pl

    def _add_kernel(x_ref, b_ref, o_ref):
        o_ref[...] = x_ref[...] + b_ref[...]

    def fn(x, lse):
        # the bug shape: per-row scalar state padded to the 128-lane width
        b = jnp.broadcast_to(lse[:, None], (x.shape[0], 128))
        return pl.pallas_call(
            _add_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x, b)

    return capture_fn(
        fn,
        jax.ShapeDtypeStruct((512, 128), jnp.float32),
        jax.ShapeDtypeStruct((512,), jnp.float32),
        name="corpus_broadcast_lse")


def _conv_relayout_sandwich() -> ProgramArtifacts:
    """The ROADMAP 'layout tax': an unfused conv feeding the pallas
    conv-epilogue custom call and another conv consuming it.  XLA prefers
    {3,0,2,1} for conv activations while the custom call pins row-major,
    so the compiled module brackets the call with relayout copies."""
    from ..kernels.conv_epilogue import conv_bn_act

    N, H, C = 2, 56, 64

    def fn(x, w0, w, g, b, w2):
        h = jax.lax.conv_general_dilated(
            x, w0, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        h, _, _ = conv_bn_act(h, w, g, b)
        return jax.lax.conv_general_dilated(
            h, w2, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    wsd = jax.ShapeDtypeStruct((3, 3, C, C), jnp.float32)
    gsd = jax.ShapeDtypeStruct((C,), jnp.float32)
    return capture_fn(
        fn, jax.ShapeDtypeStruct((N, H, H, C), jnp.float32),
        wsd, wsd, gsd, gsd, wsd,
        name="corpus_relayout_sandwich")


def _missed_donation() -> ProgramArtifacts:
    """A train-step-shaped fn whose state is eligible for aliasing but
    never donated: the executable keeps input AND output buffers
    resident — at real model scale, double the param memory."""
    def fn(state, x):
        return [s + x for s in state], jnp.sum(x)

    a = jax.ShapeDtypeStruct((256, 256), jnp.float32)
    return capture_fn(
        fn, [a, a, a], a,
        donate_argnums=(), donatable_argnums=(0,),
        name="corpus_missed_donation")


def _weak_type_scalar() -> ProgramArtifacts:
    """A python scalar leaked into the trace: the lr rides as a
    weak-typed f32 scalar, so the same step called with a numpy/jax
    array lr silently lands on a different trace key and recompiles."""
    def fn(x, lr):
        return x - lr * x

    return capture_fn(
        fn, jax.ShapeDtypeStruct((128, 128), jnp.float32), 0.1,
        name="corpus_weak_type")


def _bf16_promotion_escape() -> ProgramArtifacts:
    """A silent bf16->fp32 promotion whose full-width result escapes to
    the program output: keep-tier bf16 is defeated — the activation hits
    HBM at 2x the bytes."""
    def fn(x):
        # the hazard: a strongly-typed fp32 constant promotes the whole
        # activation, and nothing narrows it back before the HBM write
        return x.astype(jnp.float32) * 2.0 + 1.0

    return capture_fn(
        fn, jax.ShapeDtypeStruct((1024, 512), jnp.bfloat16),
        name="corpus_bf16_escape")


def _all_gather_replicated() -> ProgramArtifacts:
    """The SPMD placement hazard (ISSUE 10): a shard_map body
    all-gathers a >=1MB sharded activation onto EVERY chip and then
    consumes it with a plain reduction — the gather moves and
    materializes n_shards x the bytes a psum/psum_scatter placement
    would have (each chip only needed its shard's contribution)."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..core.aot_tpu import tpu_topology

    topo = tpu_topology("v5e:2x2", chips_per_host=(2, 2, 1))
    mesh = Mesh(np.array(topo.devices), ("tp",))

    def body(xl):
        g = jax.lax.all_gather(xl, "tp", axis=0, tiled=True)  # full [S, D]
        return jnp.sum(g * g, axis=0)

    def fn(x):
        # check_vma off: the checker cannot infer that a gathered-then-
        # reduced value is replicated — which is part of the smell
        return jax.shard_map(body, mesh=mesh, in_specs=P("tp", None),
                             out_specs=P(), check_vma=False)(x)

    return capture_fn(
        fn, jax.ShapeDtypeStruct((4096, 128), jnp.float32),
        name="corpus_all_gather", topology=topo,
        in_shardings=(NamedSharding(mesh, P("tp", None)),),
        out_shardings=NamedSharding(mesh, P()))


def _host_callback() -> ProgramArtifacts:
    """A host callback inside the step body: every execution round-trips
    the host, draining the device pipeline."""
    import numpy as np

    def fn(x):
        s = jax.pure_callback(
            lambda v: np.asarray(v).sum(),
            jax.ShapeDtypeStruct((), jnp.float32), x)
        return x * s

    return capture_fn(
        fn, jax.ShapeDtypeStruct((64, 128), jnp.float32),
        name="corpus_host_callback")


def _vmem_overflow() -> ProgramArtifacts:
    """The kernel-interior hazard class (ISSUE 14): a BlockSpec working
    set no v5e core can hold — here a whole-array 64 MB block, double-
    buffered to 256 MB against a 16 MB VMEM.  Today this class either
    silently falls back off the fast path or dies in a chip-only Mosaic
    RESOURCE_EXHAUSTED; the vmem-overflow detector prices it from the
    traced jaxpr before any compile (the AOT pipeline may well reject
    the program too — the gate fails either way, which is the point)."""
    import jax.experimental.pallas as pl

    def _scale_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    N = 4096  # one f32 [N, N] block = 64 MB

    def fn(x):
        return pl.pallas_call(
            _scale_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((1, N, N), lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec((1, N, N), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((2, N, N), jnp.float32))(x)

    return capture_fn(
        fn, jax.ShapeDtypeStruct((2, N, N), jnp.float32),
        name="corpus_vmem_overflow")


def _scan_widened_carry() -> ProgramArtifacts:
    """The scan-carry widening class the ROADMAP names for new hot
    paths: bf16 rows accumulated into a carry whose init silently
    traced fp32 (a forgotten dtype= in zeros), so jax forces the whole
    loop wide — every iteration rewrites the loop-resident buffer at 2x
    the bytes and the stacked fp32 history escapes to the program
    output unnarrowed."""
    def fn(x):  # x: [T, N] bf16 activations
        def body(c, row):
            c = c + row  # bf16 row joins the f32 carry -> widens
            return c, c

        c0 = jnp.zeros((x.shape[1],))  # the bug: traced fp32, not bf16
        _, history = jax.lax.scan(body, c0, x)
        return history  # [T, N] fp32 — 2x the bf16 bytes, every step

    return capture_fn(
        fn, jax.ShapeDtypeStruct((512, 1024), jnp.bfloat16),
        name="corpus_scan_widening")


def _spec_verify_gather() -> ProgramArtifacts:
    """The speculative-verify regression the spec_verify zoo entry
    gates on: a multi-token verify step that re-materializes the full
    contiguous [B, H, S, D] KV gather (reference tier — gather + group
    broadcast + dense attention) instead of streaming pages through
    the q_lengths kernel.  Structurally healthy, so no detector flags
    it — it must trip the BYTES tolerance: the artifact shares the zoo
    entry's capture (and name) via ``zoo.capture_spec_verify``, so
    ``lint_programs --inject spec_verify_gather --gate`` prices it
    against the banked page-stream baseline and exits 3.  Its traffic
    is fully XLA-visible (that IS the hazard), so it carries no
    analytic correction."""
    from .zoo import capture_spec_verify

    return capture_spec_verify(gather=True)


def _spec_verify_spmd_gather() -> ProgramArtifacts:
    """The mesh twin of spec_verify_gather (ISSUE 16): the shard-mapped
    Sq=1+d verify step whose per-shard attention re-materializes the
    contiguous [B, H_local, S, D] gather (reference tier — gather +
    group broadcast + dense attention) instead of walking pages.  On a
    GQA pool the gather also re-expands K/V over the query group, so
    the per-chip traffic prices far above the banked stream.  The
    artifact shares the zoo entry's capture (and name) via
    ``zoo.capture_spec_verify_spmd``, so ``lint_programs --inject
    spec_verify_spmd_gather --gate`` prices it against the banked
    per-chip page-stream baseline and exits 3 on the BYTES tolerance
    (at this scale the group-broadcast re-expansion is also big enough
    for the broadcast-operand detector to flag — belt and braces, the
    gate fails either way).  Its traffic is fully XLA-visible (that IS
    the hazard), so it carries no analytic correction."""
    from .zoo import capture_spec_verify_spmd

    return capture_spec_verify_spmd(gather=True)


def _longctx_flat_pool() -> ProgramArtifacts:
    """The long-context SMEM regression the longctx_decode zoo entry
    gates on (ISSUE 20): the SAME windowed GQA int8 decode geometry
    (~1k pages/seq, 16k-page pool) walked through the FLAT page-table
    contract — the scalar-prefetch operands ([B, max_pages] table +
    starts rows plus two POOL-sized [P] fp32 scale rows) total ~160 KB
    against the ~128 KB v5e SMEM envelope.  The smem-overflow detector
    prices it straight from the traced jaxpr (the AOT pipeline may
    reject the kernel too — the gate fails either way), so
    ``lint_programs --inject longctx_flat_pool --gate`` exits 3 against
    the banked two-level baseline.  The artifact shares the zoo entry's
    capture (and name) via ``zoo.capture_longctx_decode``, so retuning
    the zoo geometry retunes this check with it."""
    from .zoo import capture_longctx_decode

    return capture_longctx_decode(two_level=False)


def _longctx_flat_pool_extra_bytes() -> float:
    """The flat arm streams the same analytic int8 page walk as the
    banked two-level entry — the hazard is SMEM, not HBM, and charging
    the honest stream keeps the bytes verdict quiet so the gate failure
    is unambiguously the detector's."""
    from .zoo import longctx_decode_stream_bytes

    return longctx_decode_stream_bytes()


def _gqa_full_pool() -> ProgramArtifacts:
    """The GQA regression the gqa_decode zoo entry gates on: a model
    configured for grouped KV heads served from a FULL H_q pool (the
    grouping dropped somewhere between config and pool construction, so
    every page stores and streams H_q/H_kv x the bytes).  No detector
    flags it — the program is structurally healthy — which is exactly
    why it must trip the BYTES tolerance instead: the artifact shares
    the zoo entry's capture (and name) via ``zoo.capture_gqa_decode``,
    just with H_q pool heads, so ``lint_programs --inject gqa_full_pool
    --gate`` prices it against the banked grouped baseline and exits 3
    rather than silently passing — and retuning the zoo geometry
    retunes this check with it."""
    from .zoo import GQA_DECODE_GEOM, capture_gqa_decode

    return capture_gqa_decode(GQA_DECODE_GEOM["heads"])  # full H_q!


def _gqa_full_pool_extra_bytes() -> float:
    """The full-H_q analytic page stream the known-bad pool pays —
    without it the corpus program's XLA-visible bytes alone would gate
    BELOW the banked grouped baseline and pass."""
    from .zoo import GQA_DECODE_GEOM, gqa_decode_stream_bytes

    return gqa_decode_stream_bytes(GQA_DECODE_GEOM["heads"])


# name -> (builder, detector id the linter must flag it with; None for
# programs that trip the zoo BYTES gate instead of a detector)
CORPUS = {
    "broadcast_lse": (_broadcast_lse_operand, "broadcast-operand"),
    "relayout_sandwich": (_conv_relayout_sandwich, "relayout-copy-pair"),
    "missed_donation": (_missed_donation, "missed-donation"),
    "weak_type": (_weak_type_scalar, "recompile-hazard"),
    "bf16_escape": (_bf16_promotion_escape, "dtype-promotion"),
    "host_callback": (_host_callback, "host-sync"),
    "vmem_overflow": (_vmem_overflow, "vmem-overflow"),
    "scan_widening": (_scan_widened_carry, "scan-widening"),
    "all_gather_replicated": (_all_gather_replicated,
                              "collective-placement"),
    "gqa_full_pool": (_gqa_full_pool, None),
    "longctx_flat_pool": (_longctx_flat_pool, "smem-overflow"),
    "spec_verify_gather": (_spec_verify_gather, None),
    "spec_verify_spmd_gather": (_spec_verify_spmd_gather, None),
}

# corpus programs whose hazard prices in the analytic page-stream
# correction (zoo._corpus_builder adds it to the XLA-visible bytes,
# mirroring the real zoo entries' methodology); default 0
_EXTRA_BYTES = {
    "gqa_full_pool": _gqa_full_pool_extra_bytes,
    "longctx_flat_pool": _longctx_flat_pool_extra_bytes,
}


def corpus_extra_bytes(name: str) -> float:
    """Analytic bytes/step correction for one corpus program (0 for
    programs whose hazard is fully XLA-visible)."""
    fn = _EXTRA_BYTES.get(name)
    return float(fn()) if fn else 0.0


@functools.lru_cache(maxsize=None)
def build_corpus_program(name: str) -> ProgramArtifacts:
    """Build (and memoize — corpus programs are immutable) one known-bad
    program by name."""
    builder, _expected = CORPUS[name]
    return builder()
