"""Kernel-interior static analysis: price a Pallas kernel's on-chip
working set from its TRACED parameters — no Mosaic compile, no chip.

The HLO-level detectors stop at the custom-call boundary: a
``pallas_call`` is one opaque instruction to them, so the bug classes
that live INSIDE the kernel — a BlockSpec working set that cannot fit
v5e VMEM (today it silently falls back, or dies in a chip-only Mosaic
RESOURCE_EXHAUSTED) — were invisible until hardware.  Everything the
estimator needs is already in the traced jaxpr: the ``pallas_call``
equation's ``grid_mapping`` carries every operand's block shape and
memory space, the kernel jaxpr's invars carry the scalar-prefetch SMEM
operands and the scratch shapes.  ``kernel_vmem_bytes()`` prices them
the way the chip allocates them:

- each in/out block is padded to whole (sublane, lane) tiles — (8, 128)
  fp32, (16, 128) bf16, (32, 128) int8 — because Mosaic stores partial
  tiles at full tile footprint;
- blocks of a gridded kernel are DOUBLE-buffered (the pipeline DMAs the
  next block while the current one computes), so they charge 2x;
- VMEM scratch charges once (it persists across grid steps, that is its
  point); SMEM operands/scratch price separately (scalars, page tables
  — a different, much smaller budget).

``detect_vmem_overflow`` flags any program whose kernel invocation
exceeds the configurable v5e budget (``FLAGS_analysis_vmem_budget``,
default the full 16 MiB/core — kernels/conv_epilogue.py plans its own
tiles against the stricter 3/4 share to leave the compiler headroom).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, Tuple

from .findings import Finding

__all__ = [
    "KernelCost",
    "V5E_SMEM_BYTES",
    "V5E_VMEM_BYTES",
    "default_smem_budget",
    "default_vmem_budget",
    "detect_smem_overflow",
    "detect_vmem_overflow",
    "iter_pallas_calls",
    "iter_subjaxprs",
    "kernel_cost",
    "kernel_smem_bytes",
    "kernel_vmem_bytes",
    "tile_padded_bytes",
]

# one v5e core's vector memory — the hard envelope every kernel
# invocation's blocks + scratch must fit inside (with the compiler's
# own spills); the authoritative constant the kernel tile planners
# derive their headroomed budgets from
V5E_VMEM_BYTES = 16 * 1024 * 1024

# the modeled scalar-memory envelope per core: where scalar-prefetch
# operands live — grid indices, the paged-attention page tables, the
# per-page int8 scales.  Orders of magnitude smaller than VMEM, which
# is exactly why long contexts hit it FIRST: a flat [B, ~1k] page
# table plus two pool-sized [P] fp32 scale rows is already past this
# at 128k, while the two-level view (L1 directory + walked L2 blocks,
# kernels/paged_attention.TwoLevelTables) stays bounded by live blocks
V5E_SMEM_BYTES = 128 * 1024

_LANE = 128


def default_vmem_budget() -> int:
    """The detector's budget: FLAGS_analysis_vmem_budget (default the
    full v5e VMEM)."""
    from .. import flags

    return int(flags.flag("analysis_vmem_budget"))


def default_smem_budget() -> int:
    """The smem-overflow detector's budget: FLAGS_analysis_smem_budget
    (default the modeled V5E_SMEM_BYTES envelope)."""
    from .. import flags

    return int(flags.flag("analysis_smem_budget"))


def tile_padded_bytes(shape, dtype) -> int:
    """Bytes one buffer occupies in VMEM: the last two dims padded to a
    whole (sublane, lane) tile — sublane 32/itemsize (8 fp32, 16 bf16,
    32 int8), lane 128 — leading dims multiplying.  Rank-0/1 buffers
    price as one (1, n) plane; squeezed/None block dims count as 1."""
    import numpy as np

    dt = np.dtype(dtype)
    sub = max(1, 32 // max(dt.itemsize, 1))
    dims = [int(d) if isinstance(d, int) else 1 for d in (shape or (1,))]
    if len(dims) < 2:
        dims = [1] + dims
    lane = -(-dims[-1] // _LANE) * _LANE
    sublane = -(-dims[-2] // sub) * sub
    n = lane * sublane * dt.itemsize
    for d in dims[:-2]:
        n *= d
    return n


@dataclass
class KernelCost:
    """The statically-priced on-chip working set of ONE pallas_call.

    buffers: (role, shape, dtype, charged_bytes) per operand — role is
    'in'/'out' (block, charged 2x when double-buffered), 'scratch'
    (VMEM, charged once) or 'smem' (scalar-prefetch operand / SMEM
    scratch, outside the VMEM sum)."""

    name: str
    grid: Tuple[int, ...]
    vmem_bytes: int
    smem_bytes: int
    double_buffered: bool
    buffers: List[Tuple[str, Tuple[int, ...], str, int]] = field(
        default_factory=list)


def iter_subjaxprs(jaxpr) -> Iterator[Tuple[object, int]]:
    """(jaxpr, depth) over an open jaxpr and everything nested in eqn
    params (pjit bodies, cond branches, scan/while bodies, remat...)."""
    stack = [(jaxpr, 0)]
    while stack:
        j, d = stack.pop()
        yield j, d
        for eqn in j.eqns:
            for v in eqn.params.values():
                vals = v if isinstance(v, (list, tuple)) else [v]
                for item in vals:
                    inner = getattr(item, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        stack.append((inner, d + 1))
                    elif hasattr(item, "eqns"):
                        stack.append((item, d + 1))


def iter_pallas_calls(jaxpr) -> Iterator[object]:
    """Every pallas_call equation anywhere in the (closed or open)
    jaxpr, nested bodies included."""
    open_jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    if open_jaxpr is None:
        return
    for sub, _ in iter_subjaxprs(open_jaxpr):
        for eqn in sub.eqns:
            if eqn.primitive.name == "pallas_call":
                yield eqn


def _is_smem(aval) -> bool:
    return "smem" in str(getattr(aval, "memory_space", "") or "").lower()


def _is_semaphore(aval) -> bool:
    space = str(getattr(aval, "memory_space", "") or "").lower()
    return "sem" in space and "smem" not in space


def kernel_cost(eqn) -> KernelCost:
    """Price one pallas_call equation's working set from its
    grid_mapping (block shapes + memory spaces) and its kernel jaxpr's
    invars (scalar-prefetch SMEM operands, scratch shapes)."""
    gm = eqn.params["grid_mapping"]
    kernel_jaxpr = eqn.params["jaxpr"]
    name = str(eqn.params.get("name_and_src_info", "pallas_call"))
    name = name.split(" at ")[0] or "pallas_call"
    grid = tuple(int(g) for g in gm.grid if isinstance(g, int))
    grid_size = 1
    for g in grid:
        grid_size *= g
    double = grid_size > 1
    mult = 2 if double else 1
    vmem = smem = 0
    buffers: List[Tuple[str, Tuple[int, ...], str, int]] = []
    n_in = int(getattr(gm, "num_inputs", len(gm.block_mappings)))
    for i, bm in enumerate(gm.block_mappings):
        aval = bm.transformed_block_aval
        role = "in" if i < n_in else "out"
        shape = tuple(getattr(aval, "shape", bm.block_shape))
        dtype = str(getattr(aval, "dtype", "float32"))
        if _is_smem(aval):
            b = _flat_bytes(shape, dtype)
            smem += b
            buffers.append(("smem", shape, dtype, b))
            continue
        b = mult * tile_padded_bytes(shape, dtype)
        vmem += b
        buffers.append((role, shape, dtype, b))
    invars = list(kernel_jaxpr.invars)
    n_idx = int(getattr(gm, "num_index_operands", 0))
    n_scratch = int(getattr(gm, "num_scratch_operands", 0))
    for v in invars[:n_idx]:
        aval = v.aval
        b = _flat_bytes(getattr(aval, "shape", ()), str(aval.dtype))
        smem += b
        buffers.append(("smem", tuple(aval.shape), str(aval.dtype), b))
    for v in invars[len(invars) - n_scratch:] if n_scratch else []:
        aval = v.aval
        shape = tuple(getattr(aval, "shape", ()))
        dtype = str(getattr(aval, "dtype", "float32"))
        if _is_semaphore(aval):
            continue
        if _is_smem(aval):
            b = _flat_bytes(shape, dtype)
            smem += b
            buffers.append(("smem", shape, dtype, b))
        else:
            b = tile_padded_bytes(shape, dtype)
            vmem += b
            buffers.append(("scratch", shape, dtype, b))
    return KernelCost(name=name, grid=grid, vmem_bytes=vmem,
                      smem_bytes=smem, double_buffered=double,
                      buffers=buffers)


def _flat_bytes(shape, dtype) -> int:
    import numpy as np

    n = np.dtype(dtype).itemsize
    for d in shape or ():
        if isinstance(d, int):
            n *= d
    return n


def kernel_vmem_bytes(eqn) -> int:
    """The VMEM working set of one pallas_call equation: double-buffered
    padded in/out blocks + VMEM scratch (SMEM operands excluded — see
    kernel_cost for the breakdown)."""
    return kernel_cost(eqn).vmem_bytes


def kernel_smem_bytes(eqn) -> int:
    """The SMEM working set of one pallas_call equation: every
    scalar-prefetch operand + SMEM-space blocks/scratch, flat bytes
    (scalars are not tiled)."""
    return kernel_cost(eqn).smem_bytes


def detect_vmem_overflow(art) -> List[Finding]:
    """Flag every pallas_call whose statically-priced VMEM working set
    exceeds the v5e budget.  Today such a kernel either falls back off
    the fast path or dies with a chip-only Mosaic RESOURCE_EXHAUSTED —
    the linter sees it from the traced jaxpr before any compile."""
    budget = default_vmem_budget()
    findings: List[Finding] = []
    for eqn in iter_pallas_calls(art.jaxpr):
        cost = kernel_cost(eqn)
        if cost.vmem_bytes <= budget:
            continue
        top = sorted(cost.buffers, key=lambda b: -b[3])[:2]
        worst = ", ".join(
            f"{role} {dtype}{list(shape)}={b} B" for role, shape, dtype, b
            in top)
        findings.append(Finding(
            detector="vmem-overflow", severity="error",
            program=art.name, fingerprint=art.fingerprint,
            where=f"pallas_call:{cost.name}",
            vmem_bytes=cost.vmem_bytes, budget=budget,
            message=(f"kernel {cost.name} needs {cost.vmem_bytes} bytes "
                     f"of VMEM (budget {budget}): grid {cost.grid} "
                     f"{'double-buffers' if cost.double_buffered else 'holds'}"
                     f" its blocks — biggest: {worst}; this shape "
                     "compiles nowhere on a v5e core — shrink the "
                     "BlockSpecs or tile the grid finer"),
        ))
    return findings


def detect_smem_overflow(art) -> List[Finding]:
    """Flag every pallas_call whose scalar-prefetch operands + SMEM
    scratch exceed the scalar-memory budget — the LONG-CONTEXT failure
    class (ISSUE 20): a flat [B, max_pages] page table plus two
    pool-sized [P] int8 scale rows grows with total pages and blows
    SMEM near ~1k pages/seq, where the two-level table view's L1
    directory + walked L2 blocks (with block-gathered scales) stays
    bounded by live blocks.  Like vmem-overflow, the linter prices it
    from the traced jaxpr — no Mosaic compile, no chip."""
    budget = default_smem_budget()
    findings: List[Finding] = []
    for eqn in iter_pallas_calls(art.jaxpr):
        cost = kernel_cost(eqn)
        if cost.smem_bytes <= budget:
            continue
        smem_bufs = [b for b in cost.buffers if b[0] == "smem"]
        top = sorted(smem_bufs, key=lambda b: -b[3])[:3]
        worst = ", ".join(
            f"{dtype}{list(shape)}={b} B" for _, shape, dtype, b in top)
        findings.append(Finding(
            detector="smem-overflow", severity="error",
            program=art.name, fingerprint=art.fingerprint,
            where=f"pallas_call:{cost.name}",
            vmem_bytes=cost.smem_bytes, budget=budget,
            message=(f"kernel {cost.name} prefetches {cost.smem_bytes} "
                     f"bytes of scalars into SMEM (budget {budget}) — "
                     f"biggest: {worst}; scalar operands growing with "
                     "total pool pages (flat page tables, [P] scale "
                     "rows) are the long-context killer — use the "
                     "two-level table view so SMEM rides the walked "
                     "blocks"),
        ))
    return findings
