"""Chip-less program linter: static analysis over jaxprs, TPU-lowered
StableHLO, and AOT-compiled v5e HLO — no execution, no chip.

Three of this repo's worst bug classes were invisible until a chip (or
the AOT tier) caught them late: broadcast-materialized custom-call
operands (the PR-1 lse/dvec 67 MB residuals), the relayout copy-pairs
XLA inserts around pallas custom calls (the ROADMAP "layout tax"), and
silent recompiles from weak types / python scalars leaking into trace
keys.  All are statically detectable from the compiled chip program,
which core/aot_tpu.py produces on any CPU host.

    from paddle_tpu import analysis

    art = analysis.capture_executor(exe, feed=..., fetch_list=[loss])
    for f in analysis.run_detectors(art):
        print(f.format())

``tools/lint_programs.py`` runs the detectors over the model zoo
(analysis.zoo), banks per-program baselines in AOT_COST_ZOO.json, and
``--gate`` exits 3 on any new finding or bytes/step regression — the
per-PR perf gate that runs with no chip attached.

The KERNEL-INTERIOR tier (analysis.pallas) looks inside pallas_call:
``kernel_vmem_bytes()`` statically prices a kernel invocation's VMEM
working set from its BlockSpecs, scratch shapes and scalar-prefetch
SMEM operands, and the ``vmem-overflow`` / ``scan-widening`` detectors
catch the chip-only failure classes (out-of-envelope block specs,
loop carries that silently run wide) before any compile.
"""

from .findings import Finding, SEVERITIES, sort_findings  # noqa: F401
from .capture import (  # noqa: F401
    ProgramArtifacts,
    capture_executor,
    capture_fn,
)
from .detectors import DETECTORS, run_detectors  # noqa: F401
from .pallas import (  # noqa: F401
    V5E_VMEM_BYTES,
    kernel_cost,
    kernel_vmem_bytes,
)
from . import pallas  # noqa: F401
from .zoo import (  # noqa: F401
    ZOO,
    ZooResult,
    bank,
    default_baseline_path,
    gate,
    run_zoo,
)

__all__ = [
    "DETECTORS",
    "Finding",
    "ProgramArtifacts",
    "SEVERITIES",
    "V5E_VMEM_BYTES",
    "ZOO",
    "ZooResult",
    "bank",
    "capture_executor",
    "capture_fn",
    "default_baseline_path",
    "gate",
    "kernel_cost",
    "kernel_vmem_bytes",
    "run_detectors",
    "run_zoo",
    "sort_findings",
]
