"""Typed findings for the chip-less program linter.

A Finding is one statically-detected hazard in one compiled program:
which detector fired, how bad it is, where, and how many HBM bytes the
hazard costs per step (0 when the cost is a recompile/stall rather than
traffic).  Findings are JSON-stable so lint_programs.py can bank counts
into AOT_COST_ZOO.json and diff them in CI.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

__all__ = ["Finding", "SEVERITIES", "sort_findings"]

# ordered weakest -> strongest; gate policy treats every severity as
# gating (a new `info` finding is still a new hazard), severity exists
# for human triage
SEVERITIES = ("info", "warning", "error")


@dataclass
class Finding:
    """One statically-detected hazard.

    detector : stable detector id (``relayout-copy-pair``, ...) — the
               corpus tests assert on these, so they are API
    severity : one of SEVERITIES
    program  : zoo/program name the finding was raised against
    message  : human-readable one-liner
    bytes    : HBM bytes per step this hazard costs (0 = non-traffic
               hazard, e.g. a recompile trigger)
    where    : instruction / variable the finding anchors to ("" when
               the hazard is program-wide)
    fingerprint : program fingerprint (sha1 of the TPU StableHLO, or the
               ProgramDesc fingerprint for executor programs)
    vmem_bytes / budget : kernel-interior findings only (vmem-overflow):
               the statically-priced VMEM working set and the budget it
               busted — on-chip residency, not HBM traffic, hence
               separate from ``bytes``
    """

    detector: str
    severity: str
    program: str
    message: str
    bytes: int = 0
    where: str = ""
    fingerprint: str = ""
    vmem_bytes: Optional[int] = None
    budget: Optional[int] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got {self.severity!r}")

    def as_dict(self) -> Dict[str, Any]:
        d = {
            "detector": self.detector,
            "severity": self.severity,
            "program": self.program,
            "message": self.message,
            "bytes": int(self.bytes),
            "where": self.where,
            "fingerprint": self.fingerprint,
        }
        if self.vmem_bytes is not None:
            d["vmem_bytes"] = int(self.vmem_bytes)
        if self.budget is not None:
            d["budget"] = int(self.budget)
        if self.extra:
            d["extra"] = self.extra
        return d

    def format(self) -> str:
        cost = f" [{_fmt_bytes(self.bytes)}]" if self.bytes else ""
        if self.vmem_bytes is not None:
            cost += (f" [vmem {_fmt_bytes(self.vmem_bytes)}"
                     + (f" / budget {_fmt_bytes(self.budget)}"
                        if self.budget is not None else "") + "]")
        loc = f" @ {self.where}" if self.where else ""
        return (f"{self.severity.upper():7} {self.detector:24} "
                f"{self.program}{loc}{cost}: {self.message}")


def sort_findings(findings):
    """Severity-then-bytes ordering (strongest severity first, biggest
    cost first, then stable lexical keys) — the one order every report
    and banked JSON uses, so gate diffs never churn on dict/detector
    iteration order."""
    return sorted(findings, key=lambda f: (
        -SEVERITIES.index(f.severity),
        -max(int(f.bytes), int(f.vmem_bytes or 0)),
        f.detector, f.where, f.message))


def _fmt_bytes(n: int) -> str:
    for unit, div in (("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
        if n >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"
