"""Capture one program's analyzable artifacts — no execution, no chip.

A ProgramArtifacts bundles the three views every detector family needs,
all produced from ONE trace against the chip-less v5e topology
(core/aot_tpu.py):

  jaxpr      jax-level dataflow (recompile hazards, dtype promotions,
             host callbacks)
  stablehlo  the TPU-lowered module BEFORE the XLA pipeline (custom-call
             operands still show their defining broadcast/convert ops)
  hlo        the optimized chip executable's text (relayout copies,
             input/output aliasing — what actually hits HBM)
  cost       the TPU compiler's own cost model for the executable
             ({'bytes accessed', 'flops', ...} per step)

Entry points: ``capture_fn`` for a bare jax callable, and
``capture_executor`` for the exact program an Executor would run
(resolved through the executor's own compiled-program cache under the
TPU trace scope, so keep-bf16/NHWC auto-resolution is included and the
analyzed program IS the chip program).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import jax

from .findings import Finding

__all__ = ["ProgramArtifacts", "capture_fn", "capture_executor"]


@dataclass
class ProgramArtifacts:
    name: str
    jaxpr: Any                      # jax.core.ClosedJaxpr
    stablehlo: str
    hlo: str
    cost: dict
    fingerprint: str = ""
    # flat parameter indices the caller marked donatable (the
    # missed-donation detector only audits these — feeds/keys are not
    # donatable by the executor contract)
    donatable: frozenset = frozenset()
    num_flat_args: int = 0
    # capture-time hazards that are not visible in any IR (python-scalar
    # feeds, non-hashable statics); the recompile-hazard detector merges
    # them into its findings
    extra_hazards: List[Finding] = field(default_factory=list)
    # non-empty when the XLA TPU pipeline refused the program (e.g. host
    # callbacks with a compile-only client); jaxpr/stablehlo detectors
    # still run, hlo/cost views are empty
    compile_error: str = ""

    @property
    def bytes_per_step(self) -> float:
        return float(self.cost.get("bytes accessed", 0.0))

    @property
    def flops_per_step(self) -> float:
        return float(self.cost.get("flops", 0.0))


def _normalize_cost(ca) -> dict:
    return ca if isinstance(ca, dict) else (ca[0] if ca else {})


def _flat_donatable(args: Tuple, donate_argnums) -> frozenset:
    """Flat parameter indices covered by the donated argnums — jax
    flattens jit arguments in order, so each top-level arg owns one
    contiguous run of entry parameters."""
    donate = set(donate_argnums or ())
    idx = 0
    out = set()
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        if i in donate:
            out.update(range(idx, idx + n))
        idx += n
    return frozenset(out)


def capture_fn(fn, *args, name: str = "fn", donate_argnums=(),
               donatable_argnums=None, topology=None, fingerprint: str = "",
               extra_hazards: Optional[List[Finding]] = None,
               in_shardings=None, out_shardings=None,
               ) -> ProgramArtifacts:
    """Trace/lower/AOT-compile ``fn(*args)`` for the v5e topology and
    return its artifact bundle.  Args may be concrete values or
    ShapeDtypeStructs — only shapes/dtypes are consumed.

    donate_argnums is what the executable ACTUALLY donates;
    donatable_argnums (default: same) is what is ELIGIBLE for donation —
    the missed-donation detector flags eligible-but-unaliased buffers, so
    passing donatable_argnums without donate_argnums models a caller that
    forgot to donate.

    in_shardings/out_shardings capture SPMD programs (shard_map over a
    mesh of the topology's devices): the analyzed HLO is then the
    per-chip partitioned module — its cost model prices per-chip
    bytes/step, and collectives (all-gather/all-reduce) are visible to
    the collective-placement detector."""
    from .. import flags
    from ..core.aot_tpu import trace_tpu

    if donatable_argnums is None:
        donatable_argnums = donate_argnums
    # trace with the TPU trace scope ACTIVE: op lowering reads it lazily
    # at trace time (keep-bf16, NHWC, pallas-vs-interpret selection), so
    # without it an executor raw_fn would trace its CPU reference-parity
    # program and the linter would analyze the wrong executable — same
    # forcing cost_analysis(platform="tpu") does
    with flags.tpu_trace_scope(True):
        traced = trace_tpu(fn, *args, topology=topology,
                           donate_argnums=tuple(donate_argnums),
                           in_shardings=in_shardings,
                           out_shardings=out_shardings)
        jaxpr = traced.jaxpr
        lowered = traced.lower()
        stablehlo = lowered.as_text()
        hlo, cost, compile_error = "", {}, ""
        try:
            compiled = lowered.compile()
            hlo = compiled.as_text()
            cost = _normalize_cost(compiled.cost_analysis())
        except Exception as e:
            # a program the chip pipeline REJECTS (host callbacks under
            # the compile-only client, Mosaic envelope violations) still
            # gets its jaxpr/StableHLO detectors — and the rejection
            # itself is worth surfacing to the caller
            compile_error = str(e)
    fp = fingerprint or hashlib.sha1(stablehlo.encode()).hexdigest()[:12]
    return ProgramArtifacts(
        name=name,
        jaxpr=jaxpr,
        stablehlo=stablehlo,
        hlo=hlo,
        cost=cost,
        fingerprint=fp,
        donatable=_flat_donatable(args, donatable_argnums),
        num_flat_args=sum(
            len(jax.tree_util.tree_leaves(a)) for a in args),
        extra_hazards=list(extra_hazards or []),
        compile_error=compile_error,
    )


def _capture_time_hazards(name: str, feed: dict, fingerprint: str
                          ) -> List[Finding]:
    """Hazards only visible at the call boundary: python scalars in the
    feed (weak-typed trace entries — the same feed with a numpy array
    silently recompiles) and non-hashable statics reaching the
    compiled-program cache key (every run would miss the cache)."""
    from .. import flags
    from ..core import amp

    hazards: List[Finding] = []
    for fname, v in sorted((feed or {}).items()):
        if isinstance(v, (bool, int, float)) and not hasattr(v, "dtype"):
            hazards.append(Finding(
                detector="recompile-hazard", severity="warning",
                program=name, fingerprint=fingerprint,
                where=f"feed:{fname}",
                message=(f"feed '{fname}' is a python scalar "
                         f"({type(v).__name__}): it traces weak-typed, so "
                         "feeding an array later recompiles silently"),
            ))
    for label, key in (("flags.trace_key", flags.trace_key()),
                       ("amp.state_key", amp.state_key())):
        try:
            hash(key)
        except TypeError:
            hazards.append(Finding(
                detector="recompile-hazard", severity="error",
                program=name, fingerprint=fingerprint, where=label,
                message=(f"{label}() is not hashable — every executor run "
                         "misses the compiled-program cache and recompiles"),
            ))
    return hazards


def capture_executor(exe, program=None, feed=None, fetch_list=None,
                     scope=None, name: str = "program",
                     ) -> ProgramArtifacts:
    """Capture the CHIP program this executor would run for (program,
    feed, fetch_list) — same cache entry, same state donation, TPU trace
    scope forced (keep-bf16 / NHWC auto-resolution included)."""
    from ..core.framework import default_main_program

    prog = program or default_main_program()
    fp = prog.desc.fingerprint().hex()[:12]
    hazards = _capture_time_hazards(name, feed, fp)
    try:
        compiled, feed_vals, state_vals, rng = exe.capture_program(
            program, feed, fetch_list, scope)
    except TypeError:
        # the executor's own cache-key hash dies on the exact hazard the
        # non-hashable-statics check exists to report — surface the
        # finding rather than crashing the linter
        if any(h.where in ("flags.trace_key", "amp.state_key")
               for h in hazards):
            return ProgramArtifacts(
                name=name, jaxpr=None, stablehlo="", hlo="", cost={},
                fingerprint=fp, extra_hazards=hazards,
                compile_error="compiled-program cache key not hashable")
        raise
    donate = compiled.donates_states
    args = (tuple(feed_vals), tuple(state_vals), rng)
    # the state tuple is ALWAYS donation-eligible (run() aliases it unless
    # the numerics sentinel turned donation off) — so an executor whose
    # donation is off shows up as missed-donation findings, by design
    return capture_fn(
        compiled.raw_fn, *args, name=name,
        donate_argnums=(1,) if donate else (),
        donatable_argnums=(1,),
        fingerprint=fp,
        extra_hazards=hazards,
    )
