"""The detectors of the chip-less program linter.

Each detector is ``fn(ProgramArtifacts) -> List[Finding]`` over the
captured jaxpr / TPU StableHLO / optimized chip HLO — no execution.  The
detector ids are stable API (the known-bad corpus tests and banked
AOT_COST_ZOO.json baselines key on them):

  relayout-copy-pair   layout-changing copies XLA inserted to feed or
                       drain a custom call (the ROADMAP "layout tax":
                       custom calls pin row-major while XLA prefers e.g.
                       {3,0,2,1} for conv tensors) — quantified in bytes
  broadcast-operand    a custom-call operand materialized by
                       broadcast_in_dim (the PR-1 lse/dvec bug class:
                       "XLA fuses it" is false for custom-call operands)
  missed-donation      a donatable input buffer with a shape/dtype-
                       matching output that the compiled executable did
                       NOT alias — one resident copy of the buffer wasted
  recompile-hazard     weak types / python scalars / non-hashable statics
                       reaching trace or cache keys — silent recompiles
  dtype-promotion      silent widening (fp32->fp64 anywhere; bf16/fp16->
                       fp32 whose result ESCAPES to HBM — program output
                       or custom-call operand — above a size floor;
                       fusion-internal fp32 math that narrows back before
                       the HBM write is the intended stats idiom, not a
                       finding)
  host-sync            host callbacks / infeed / outfeed inside the
                       program body — every step round-trips the host
  collective-placement all-gather / all-reduce collectives in the SPMD
                       module materializing a full-replicated tensor
                       >= 1MB on every chip — where a psum_scatter /
                       reduce-scatter would keep shards, the collective
                       moves (and each device then holds) n_shards x
                       the bytes the consumer needed
  vmem-overflow        a pallas_call whose statically-priced VMEM
                       working set (double-buffered padded blocks +
                       scratch — analysis/pallas.py kernel_vmem_bytes)
                       exceeds the v5e budget: the kernel compiles
                       nowhere on chip, a failure class that used to be
                       chip-only
  scan-widening        a scan/while carry or stacked output that runs
                       WIDER than the narrow (bf16/fp16) data feeding
                       it — the init silently traced wide, every
                       iteration rewrites the loop-resident HBM buffer
                       at 2x the bytes — where the widened result then
                       escapes to HBM unnarrowed
  smem-overflow        a pallas_call whose scalar-prefetch operands +
                       SMEM scratch exceed the scalar-memory budget
                       (analysis/pallas.py kernel_smem_bytes) — the
                       long-context class: flat page tables and
                       pool-sized [P] scale rows grow with total
                       pages; the two-level table view keeps SMEM on
                       the walked blocks
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from .capture import ProgramArtifacts
from .findings import Finding
from . import hlo as H
from .pallas import (detect_smem_overflow, detect_vmem_overflow,
                     iter_subjaxprs as _iter_subjaxprs)

__all__ = ["DETECTORS", "run_detectors"]


def _aval_bytes(aval) -> int:
    try:
        return int(aval.size) * aval.dtype.itemsize
    except Exception:
        return 0


# ---------------------------------------------------------------------------
# relayout-copy-pair


def _resolve(name: str, by_name: dict, depth: int = 4) -> Optional[object]:
    """Follow bitcast / get-tuple-element / copy-done indirections to the
    instruction that actually produced a value."""
    while depth:
        instr = by_name.get(name)
        if instr is None:
            return None
        if instr.opcode in ("bitcast", "get-tuple-element", "copy-done"):
            if not instr.operand_names:
                return instr
            name = instr.operand_names[0]
            if instr.opcode == "copy-done":
                src = by_name.get(name)
                if src is not None and src.opcode == "copy-start" \
                        and src.operand_names:
                    name = src.operand_names[0]
            depth -= 1
            continue
        return instr
    return by_name.get(name)


def _is_relayout_copy(instr) -> bool:
    if instr.opcode != "copy" or not instr.shapes or not instr.operands:
        return False
    res = instr.shapes[0]
    op = instr.operands[0][0]
    if op is None or not res.perm or not op.perm:
        return False
    return res.perm != op.perm


def _pins_layout(instr) -> bool:
    """Only custom calls that PIN operand/result layouts levy the
    relayout tax.  The TPU backend also emits internal custom calls
    (ConcatBitcast, GatherScatterIndicesBitpacked, ...) as part of its
    own lowering — copies around those are XLA's choice, not a kernel
    forcing a layout on XLA."""
    return ('custom_call_target="tpu_custom_call"' in instr.line
            or "operand_layout_constraints=" in instr.line)


def detect_relayout_copies(art: ProgramArtifacts) -> List[Finding]:
    instrs = H.entry_instructions(art.hlo)
    by_name = {i.name: i for i in instrs}
    findings: List[Finding] = []
    custom_calls = [i for i in instrs
                    if i.opcode == "custom-call" and _pins_layout(i)]
    cc_names = {i.name for i in custom_calls}
    # copies INTO a custom call: an operand (through bitcast/gte/async
    # copy indirections) produced by a layout-changing copy
    for cc in custom_calls:
        for opname in cc.operand_names:
            producer = _resolve(opname, by_name)
            if producer is not None and _is_relayout_copy(producer):
                b = producer.shapes[0].bytes
                findings.append(Finding(
                    detector="relayout-copy-pair", severity="warning",
                    program=art.name, fingerprint=art.fingerprint,
                    bytes=b, where=f"{producer.name}->{cc.name}",
                    message=(f"relayout copy {{{producer.operands[0][0].perm}}}"
                             f"->{{{producer.shapes[0].perm}}} feeds custom "
                             f"call {cc.name} ({b} bytes): the custom call "
                             "pins a layout XLA does not prefer here"),
                ))
    # copies OUT of a custom call: a layout-changing copy whose operand
    # resolves back to a custom-call result
    for instr in instrs:
        if not _is_relayout_copy(instr) or not instr.operand_names:
            continue
        producer = _resolve(instr.operand_names[0], by_name)
        if producer is not None and producer.name in cc_names:
            b = instr.shapes[0].bytes
            findings.append(Finding(
                detector="relayout-copy-pair", severity="warning",
                program=art.name, fingerprint=art.fingerprint,
                bytes=b, where=f"{producer.name}->{instr.name}",
                message=(f"relayout copy {{{instr.operands[0][0].perm}}}"
                         f"->{{{instr.shapes[0].perm}}} drains custom call "
                         f"{producer.name} ({b} bytes)"),
            ))
    return findings


# ---------------------------------------------------------------------------
# broadcast-operand

_BROADCAST_MIN_BYTES = 64 * 1024


def detect_broadcast_operands(art: ProgramArtifacts) -> List[Finding]:
    findings = []
    for target, ssa, dst_b, src_b in H.stablehlo_broadcast_operands(
            art.stablehlo):
        if dst_b < _BROADCAST_MIN_BYTES:
            continue  # scalar scales etc. — not the materialization class
        findings.append(Finding(
            detector="broadcast-operand", severity="error",
            program=art.name, fingerprint=art.fingerprint,
            bytes=dst_b, where=f"%{ssa}->@{target or 'custom_call'}",
            message=(f"custom-call operand %{ssa} is a materialized "
                     f"broadcast ({src_b} -> {dst_b} bytes): custom-call "
                     "operands are NOT fused away — this buffer hits HBM "
                     "at full size every step (the PR-1 lse/dvec class)"),
        ))
    return findings


# ---------------------------------------------------------------------------
# missed-donation


def detect_missed_donation(art: ProgramArtifacts) -> List[Finding]:
    if not art.donatable:
        return []
    params, outs = H.parse_entry_layout(art.hlo)
    alias = H.parse_input_output_alias(art.hlo)
    aliased_params = set(alias.values())
    aliased_outs = set(alias.keys())
    findings: List[Finding] = []
    free_outs = [
        (i, o) for i, o in enumerate(outs) if i not in aliased_outs]
    for p_idx in sorted(art.donatable):
        if p_idx in aliased_params or p_idx >= len(params):
            continue
        p = params[p_idx]
        match = next(
            ((i, o) for i, o in free_outs
             if o.dtype == p.dtype and o.dims == p.dims), None)
        if match is None:
            continue
        free_outs.remove(match)
        findings.append(Finding(
            detector="missed-donation", severity="warning",
            program=art.name, fingerprint=art.fingerprint,
            bytes=p.bytes, where=f"param {p_idx} -> output {match[0]}",
            message=(f"donatable input {p_idx} "
                     f"({p.dtype}{list(p.dims)}, {p.bytes} bytes) has a "
                     f"shape-matched unaliased output {match[0]} but the "
                     "executable holds both buffers — donation was "
                     "requested but not realized (layout/sharding "
                     "mismatch) or never requested"),
        ))
    return findings


# ---------------------------------------------------------------------------
# recompile-hazard


def detect_recompile_hazards(art: ProgramArtifacts) -> List[Finding]:
    findings = list(art.extra_hazards)
    jaxpr = getattr(art.jaxpr, "jaxpr", art.jaxpr)
    if jaxpr is None:
        return findings
    for i, var in enumerate(jaxpr.invars):
        aval = getattr(var, "aval", None)
        if aval is not None and getattr(aval, "weak_type", False):
            findings.append(Finding(
                detector="recompile-hazard", severity="warning",
                program=art.name, fingerprint=art.fingerprint,
                bytes=_aval_bytes(aval), where=f"arg {i}",
                message=(f"argument {i} traces WEAK-typed ({aval.dtype}): a "
                         "python scalar reached the trace — calling with a "
                         "strongly-typed array later lands on a different "
                         "trace key and silently recompiles"),
            ))
    return findings


# ---------------------------------------------------------------------------
# dtype-promotion

_PROMOTION_MIN_BYTES = 1 << 20
_WIDENING = {
    ("bfloat16", "float32"), ("float16", "float32"),
    ("float32", "float64"), ("bfloat16", "float64"),
    ("float16", "float64"),
}
# ops a widened value flows THROUGH at full size; anything not listed is
# an accumulate/shrink sink (reductions, dots, convs, scatters) or an
# unknown op, both of which stop propagation
_TRANSPARENT_PRIMS = {
    "add", "sub", "mul", "div", "max", "min", "neg", "abs", "exp", "log",
    "tanh", "logistic", "sqrt", "rsqrt", "pow", "integer_pow", "select_n",
    "reshape", "transpose", "broadcast_in_dim", "slice", "dynamic_slice",
    "concatenate", "pad", "rev", "squeeze", "copy", "expand_dims",
    "where", "clamp", "sign",
}
_CUSTOM_CALL_PRIMS = {"pallas_call", "custom_call", "tpu_custom_call"}


_MIXING_PRIMS = {"add", "sub", "mul", "div", "max", "min", "select_n",
                 "where", "clamp"}


def _absorbed_by_wide_sibling(var, user) -> bool:
    """A widened value merging into an equally-large tensor that is
    ALREADY the wide dtype is a deliberate precision join (the AMP
    master-weight / fp32-stats idiom: bf16 grads cast up to update f32
    params) — the f32 HBM write is attributable to that tensor, not to
    the promotion.  Scalar/broadcast siblings (a f32 constant promoting
    a whole activation) do not absorb."""
    va = getattr(var, "aval", None)
    if va is None:
        return False
    for sib in user.invars:
        if sib is var:
            continue
        sa = getattr(sib, "aval", None)
        if sa is not None and sa.dtype == va.dtype \
                and getattr(sa, "size", 0) >= va.size:
            return True
    return False


def _escapes(start_vars, jaxpr, top_level: bool) -> Optional[str]:
    """Does a widened value (any of `start_vars`) reach HBM at full
    width — a program output (top level only) or a custom-call operand?
    Walks forward through transparent elementwise/movement ops;
    reductions, contractions, unknown ops, and full-width joins with
    already-wide tensors absorb it (the accumulate-in-fp32 /
    master-weight idioms)."""
    outvars = {id(v) for v in jaxpr.outvars}
    uses: Dict[int, list] = {}
    for e in jaxpr.eqns:
        for v in e.invars:
            uses.setdefault(id(v), []).append(e)
    frontier = list(start_vars)
    seen = set()
    while frontier:
        var = frontier.pop()
        if id(var) in seen:
            continue
        seen.add(id(var))
        if top_level and id(var) in outvars:
            return "program output"
        for user in uses.get(id(var), []):
            prim = user.primitive.name
            if prim in _CUSTOM_CALL_PRIMS:
                return f"custom call ({prim})"
            if prim == "convert_element_type":
                # narrowing back down ends the hazard on that path
                continue
            if prim in _MIXING_PRIMS \
                    and _absorbed_by_wide_sibling(var, user):
                continue
            if prim in _TRANSPARENT_PRIMS:
                frontier.extend(user.outvars)
    return None


def detect_dtype_promotions(art: ProgramArtifacts) -> List[Finding]:
    closed = art.jaxpr
    jaxpr = getattr(closed, "jaxpr", closed)
    if jaxpr is None:
        return []
    findings: List[Finding] = []
    for sub, depth in _iter_subjaxprs(jaxpr):
        for eqn in sub.eqns:
            if eqn.primitive.name != "convert_element_type":
                continue
            src = getattr(eqn.invars[0], "aval", None)
            dst = getattr(eqn.outvars[0], "aval", None)
            if src is None or dst is None:
                continue
            pair = (str(src.dtype), str(dst.dtype))
            if pair not in _WIDENING:
                continue
            b = _aval_bytes(dst)
            if pair[1] == "float64":
                findings.append(Finding(
                    detector="dtype-promotion", severity="error",
                    program=art.name, fingerprint=art.fingerprint,
                    bytes=b, where=f"{pair[0]}->{pair[1]}",
                    message=(f"silent {pair[0]}->float64 promotion "
                             f"({b} bytes): an x64 leak — TPUs have no "
                             "f64 units, this deoptimizes the whole "
                             "fusion it lands in"),
                ))
                continue
            if b < _PROMOTION_MIN_BYTES:
                continue
            sink = _escapes(eqn.outvars, sub, top_level=(depth == 0))
            if sink is None:
                continue
            findings.append(Finding(
                detector="dtype-promotion", severity="warning",
                program=art.name, fingerprint=art.fingerprint,
                bytes=b, where=f"{pair[0]}->{pair[1]} -> {sink}",
                message=(f"{pair[0]}->{pair[1]} promotion escapes to "
                         f"{sink} at full width ({b} bytes): the widened "
                         "activation hits HBM — keep-tier bf16 is "
                         "defeated on this path"),
            ))
    return findings


# ---------------------------------------------------------------------------
# scan-widening


def _loop_body_and_carries(eqn):
    """(body_jaxpr, num_carry_outvars, label) for a scan/while equation,
    else None.  A scan body's outvars are [carries..., ys...]; a while
    body's outvars are all carries."""
    name = eqn.primitive.name
    if name == "scan":
        body = eqn.params["jaxpr"].jaxpr
        return body, int(eqn.params["num_carry"]), "scan"
    if name == "while":
        body = eqn.params["body_jaxpr"].jaxpr
        return body, len(body.outvars), "while"
    return None


def _body_outvars_reached(conv_eqn, body):
    """Outvar slots of `body` the widening convert's result reaches
    through transparent ops (the same propagation rules as _escapes,
    minus the custom-call/output sinks — here the loop boundary IS the
    sink)."""
    uses: Dict[int, list] = {}
    for e in body.eqns:
        for v in e.invars:
            uses.setdefault(id(v), []).append(e)
    # one var may fill SEVERAL outvar slots (`return c, c` — the carry
    # also emitted as a stacked output), so every slot must be kept: a
    # last-wins dict would hide the carry behind a possibly-dead ys
    out_slots: Dict[int, list] = {}
    for i, v in enumerate(body.outvars):
        out_slots.setdefault(id(v), []).append(i)
    reached = set()
    frontier = list(conv_eqn.outvars)
    seen = set()
    while frontier:
        var = frontier.pop()
        if id(var) in seen:
            continue
        seen.add(id(var))
        reached.update(out_slots.get(id(var), ()))
        for user in uses.get(id(var), []):
            prim = user.primitive.name
            if prim == "convert_element_type":
                continue  # narrowed (or re-widened) — a different value
            if prim in _TRANSPARENT_PRIMS or prim in _MIXING_PRIMS:
                frontier.extend(user.outvars)
    return reached


def detect_scan_widening(art: ProgramArtifacts) -> List[Finding]:
    """Scan/while carries (and scan's stacked ys) that run WIDER than
    the narrow data feeding them: a bf16/fp16 value widened inside the
    loop body reaches the body's outvars, so every iteration rewrites
    the loop-resident HBM buffer — and the stacked history — at the
    wide dtype (an init that silently traced fp32 is how the carry got
    wide in the first place; jax then forces the whole loop to follow).
    Flagged only when the loop's widened RESULT also escapes to HBM
    unnarrowed (program output / custom-call operand) above the size
    floor — a deliberate fp32 accumulator that narrows or reduces
    before the write stays clean, the dtype-promotion contract."""
    closed = art.jaxpr
    jaxpr = getattr(closed, "jaxpr", closed)
    if jaxpr is None:
        return []
    findings: List[Finding] = []
    for sub, depth in _iter_subjaxprs(jaxpr):
        for eqn in sub.eqns:
            parts = _loop_body_and_carries(eqn)
            if parts is None:
                continue
            body, num_carry, label = parts
            flagged = set()
            for beqn in body.eqns:
                if beqn.primitive.name != "convert_element_type":
                    continue
                src = getattr(beqn.invars[0], "aval", None)
                dst = getattr(beqn.outvars[0], "aval", None)
                if src is None or dst is None:
                    continue
                if (str(src.dtype), str(dst.dtype)) not in _WIDENING:
                    continue
                for slot in sorted(_body_outvars_reached(beqn, body)):
                    if slot in flagged or slot >= len(eqn.outvars):
                        continue
                    out = eqn.outvars[slot]
                    aval = getattr(out, "aval", None)
                    if aval is None or str(aval.dtype) != str(dst.dtype):
                        continue
                    b = _aval_bytes(aval)
                    if b < _PROMOTION_MIN_BYTES:
                        continue
                    sink = _escapes([out], sub, top_level=(depth == 0))
                    if sink is None:
                        continue
                    flagged.add(slot)
                    kind = ("carry" if slot < num_carry
                            else "stacked output")
                    findings.append(Finding(
                        detector="scan-widening", severity="warning",
                        program=art.name, fingerprint=art.fingerprint,
                        bytes=b, where=f"{label} {kind} {slot}",
                        message=(f"{label} {kind} {slot} runs "
                                 f"{dst.dtype} over {src.dtype} data "
                                 f"joined inside the body: the loop "
                                 f"rewrites it wide every iteration and "
                                 f"the widened result escapes to {sink} "
                                 f"({b} bytes) — the carry's init traced "
                                 "wide (a forgotten dtype=), defeating "
                                 "the keep-narrow tier on the whole "
                                 "loop"),
                    ))
    return findings


# ---------------------------------------------------------------------------
# host-sync

_HOST_SYNC_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "outside_call",
    "host_callback_call", "infeed", "outfeed",
}
_HOST_SYNC_CC_MARKERS = ("xla_python_cpu_callback", "xla_ffi_python",
                         "callback")


def detect_host_sync(art: ProgramArtifacts) -> List[Finding]:
    closed = art.jaxpr
    jaxpr = getattr(closed, "jaxpr", closed)
    findings: List[Finding] = []
    if jaxpr is not None:
        for sub, _ in _iter_subjaxprs(jaxpr):
            for eqn in sub.eqns:
                if eqn.primitive.name in _HOST_SYNC_PRIMS:
                    b = sum(_aval_bytes(getattr(v, "aval", None)) or 0
                            for v in eqn.invars
                            if getattr(v, "aval", None) is not None)
                    findings.append(Finding(
                        detector="host-sync", severity="error",
                        program=art.name, fingerprint=art.fingerprint,
                        bytes=b, where=eqn.primitive.name,
                        message=(f"{eqn.primitive.name} inside the program "
                                 "body: every step synchronizes with the "
                                 "host — the device pipeline drains and "
                                 "serving latency inherits host jitter"),
                    ))
    # callbacks that arrived pre-packaged as custom calls (libraries):
    # each jaxpr-level CALLBACK lowers to one such custom call, so only
    # marker lines BEYOND the callback-prim findings are additional
    # hazards — without this a single pure_callback would bank a count
    # of 2.  infeed/outfeed prims lower to stablehlo.infeed/outfeed,
    # never to callback custom calls, so they must not offset the slice
    n_from_jaxpr = sum(
        1 for f in findings if f.where not in ("infeed", "outfeed"))
    cc_lines = []
    for line in art.stablehlo.splitlines():
        if "custom_call" not in line:
            continue
        low = line.lower()
        if any(m in low for m in _HOST_SYNC_CC_MARKERS) \
                and "tpu_custom_call" not in low:
            cc_lines.append(line)
    for line in cc_lines[n_from_jaxpr:]:
        findings.append(Finding(
            detector="host-sync", severity="error",
            program=art.name, fingerprint=art.fingerprint,
            where="custom_call",
            message=("host-callback custom call in lowered module: "
                     + line.strip()[:120]),
        ))
    return findings


# ---------------------------------------------------------------------------
# collective-placement

_COLLECTIVE_MIN_BYTES = 1 << 20
# opcodes that MATERIALIZE a replicated/enlarged result on every
# participating chip; reduce-scatter/psum_scatter (results stay
# shard-sized) are the fix, not a finding
_MATERIALIZING_COLLECTIVES = ("all-gather", "all-reduce")
_SH_COLLECTIVE_RE = None  # compiled lazily below


def detect_collective_placement(art: ProgramArtifacts) -> List[Finding]:
    """All-gather / all-reduce in the SPMD module whose result is a
    >=1MB tensor: every chip receives (and holds) the FULL tensor even
    though a shard-local consumer only needed 1/n of it — the
    psum_scatter / reduce-scatter placement keeps shards instead.
    Inspected on the optimized per-chip HLO (what actually ships);
    falls back to the lowered StableHLO when the chip compile was
    rejected, so the detector never goes blind on a broken program."""
    findings: List[Finding] = []

    def note(opcode: str, where: str, b: int) -> None:
        findings.append(Finding(
            detector="collective-placement", severity="warning",
            program=art.name, fingerprint=art.fingerprint,
            bytes=b, where=where,
            message=(f"{opcode} materializes a full-replicated "
                     f"{b}-byte tensor on every chip: if the consumer "
                     "is shard-local (elementwise, a reduction, the "
                     "next row-parallel matmul), a psum_scatter/"
                     "reduce-scatter keeps per-chip traffic and "
                     "residency at 1/n_shards"),
        ))

    if art.hlo:
        for instr in H.entry_instructions(art.hlo):
            if instr.opcode not in _MATERIALIZING_COLLECTIVES:
                continue
            b = sum(s.bytes for s in instr.shapes)
            if b >= _COLLECTIVE_MIN_BYTES:
                note(instr.opcode, instr.name, b)
        return findings
    # StableHLO fallback (compile_error path): same opcode family in
    # the lowered module's text, result type last on the line
    import re

    global _SH_COLLECTIVE_RE
    if _SH_COLLECTIVE_RE is None:
        _SH_COLLECTIVE_RE = re.compile(
            r"stablehlo\.(all_gather|all_reduce)\b")
    for line in art.stablehlo.splitlines():
        m = _SH_COLLECTIVE_RE.search(line)
        if not m:
            continue
        types = H._SH_TENSOR_RE.findall(line)
        if not types:
            continue
        b = H._tensor_elems_bytes(types[-1])
        if b >= _COLLECTIVE_MIN_BYTES:
            note(m.group(1).replace("_", "-"), m.group(1), b)
    return findings


# ---------------------------------------------------------------------------

DETECTORS: Dict[str, Callable[[ProgramArtifacts], List[Finding]]] = {
    "relayout-copy-pair": detect_relayout_copies,
    "broadcast-operand": detect_broadcast_operands,
    "missed-donation": detect_missed_donation,
    "recompile-hazard": detect_recompile_hazards,
    "dtype-promotion": detect_dtype_promotions,
    "scan-widening": detect_scan_widening,
    "host-sync": detect_host_sync,
    "collective-placement": detect_collective_placement,
    # kernel-interior tier (analysis/pallas.py): inside the custom call
    "vmem-overflow": detect_vmem_overflow,
    "smem-overflow": detect_smem_overflow,
}


def run_detectors(art: ProgramArtifacts,
                  detectors: Optional[Sequence[str]] = None
                  ) -> List[Finding]:
    """Run the named detectors (default: all, in registry order) over one
    captured program."""
    names = list(detectors) if detectors else list(DETECTORS)
    out: List[Finding] = []
    for n in names:
        out.extend(DETECTORS[n](art))
    return out
