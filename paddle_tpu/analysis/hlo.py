"""Minimal text parsers for the two program dumps the linter inspects.

No HLO python bindings exist for the AOT TPU pipeline's output, but the
two facts the detectors need — instruction-level def/use in the ENTRY
computation of optimized HLO, and SSA def/use in lowered StableHLO — are
regular enough to parse from `Compiled.as_text()` / `Lowered.as_text()`.
Kept deliberately narrow: shapes, layout *permutations* (tiling and
memory-space suffixes like ``T(8,128)S(1)`` are ignored — a
same-permutation copy is a memory-space move, not a relayout), operand
name lists, and the module-header ``input_output_alias`` /
``entry_computation_layout`` blocks.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = [
    "HloInstr",
    "Shape",
    "entry_instructions",
    "parse_entry_layout",
    "parse_input_output_alias",
    "parse_shape",
    "shape_bytes",
    "stablehlo_broadcast_operands",
]

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "f8e4m3fn": 1, "f8e5m2": 1, "c64": 8, "c128": 16,
    # StableHLO spellings
    "i1": 1, "i8": 1, "i16": 2, "i32": 4, "i64": 8,
}


@dataclass
class Shape:
    dtype: str
    dims: Tuple[int, ...]
    perm: str = ""  # layout permutation, "" when unspecified/scalar

    @property
    def bytes(self) -> int:
        n = _DTYPE_BYTES.get(self.dtype, 4)
        for d in self.dims:
            n *= d
        return n


@dataclass
class HloInstr:
    name: str
    opcode: str
    shapes: List[Shape]               # result shapes (tuple flattened)
    operands: List[Tuple[Shape, str]]  # shaped operand refs, in order
    operand_names: List[str]          # every %ref on the line, in order
    is_root: bool = False
    line: str = ""


# f32[2,56,56,64]{3,2,1,0:T(8,128)S(1)}  /  f32[]{:T(128)}  /  s32[4,32]
_SHAPE_RE = re.compile(
    r"([a-z][a-z0-9]*)\[([\d,]*)\](?:\{([^}]*)\})?")
_DEF_RE = re.compile(r"^\s*(ROOT\s+)?%([\w.\-]+)\s*=\s*")


def parse_shape(text: str) -> Optional[Shape]:
    m = _SHAPE_RE.search(text)
    if not m:
        return None
    dims = tuple(int(d) for d in m.group(2).split(",") if d)
    layout = m.group(3) or ""
    perm = layout.split(":", 1)[0]
    return Shape(m.group(1), dims, perm)


def shape_bytes(text: str) -> int:
    s = parse_shape(text)
    return s.bytes if s else 0


def _result_shapes(text: str) -> List[Shape]:
    return [Shape(m.group(1),
                  tuple(int(d) for d in m.group(2).split(",") if d),
                  (m.group(3) or "").split(":", 1)[0])
            for m in _SHAPE_RE.finditer(text)]


_OPERAND_RE = re.compile(
    r"([a-z][a-z0-9]*\[[\d,]*\](?:\{[^}]*\})?)\s+%([\w.\-]+)")
_REF_RE = re.compile(r"%([\w.\-]+)")


def _scan_result_shape(text: str):
    """Parse the result-shape prefix of an instruction body (single shape
    or tuple; layouts nest () and {} — e.g. T(8,128) — so this scans by
    depth).  Returns (shape_text, rest) or None."""
    text = text.lstrip()
    if text.startswith("("):
        depth, i = 1, 1
        while depth and i < len(text):
            depth += {"(": 1, ")": -1}.get(text[i], 0)
            i += 1
        return text[:i], text[i:]
    m = _SHAPE_RE.match(text)
    if not m:
        return None
    i = m.end()
    if i < len(text) and text[i] == "{":
        depth = 1
        i += 1
        while depth and i < len(text):
            depth += {"{": 1, "}": -1}.get(text[i], 0)
            i += 1
    return text[:i], text[i:]


_OPCODE_RE = re.compile(r"\s*([\w\-]+)\(")


def entry_instructions(hlo_text: str) -> List[HloInstr]:
    """Instructions of the ENTRY computation only — fusion-internal ops
    never touch HBM on their own, so relayout/copy accounting over them
    would double-count."""
    out: List[HloInstr] = []
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry and line.startswith("}"):
            break
        if not in_entry:
            continue
        m = _DEF_RE.match(line)
        if not m:
            continue
        scanned = _scan_result_shape(line[m.end():])
        if not scanned:
            continue
        shape_txt, rest = scanned
        om = _OPCODE_RE.match(rest)
        if not om:
            continue
        rest = rest[om.end():]
        # operands end at the opcode's matching close paren; trailing
        # attrs (metadata/backend_config) must not contribute refs
        depth, j = 1, 0
        while depth and j < len(rest):
            depth += {"(": 1, ")": -1}.get(rest[j], 0)
            j += 1
        rest = rest[:max(j - 1, 0)]
        out.append(HloInstr(
            name=m.group(2),
            opcode=om.group(1),
            shapes=_result_shapes(shape_txt),
            operands=[(parse_shape(s.group(1)), s.group(2))
                      for s in _OPERAND_RE.finditer(rest)],
            operand_names=_REF_RE.findall(rest),
            is_root=bool(m.group(1)),
            line=line.strip(),
        ))
    return out


def parse_entry_layout(hlo_text: str):
    """(param_shapes, output_shapes) from the module header's
    entry_computation_layout={(p0, p1, ...)->(o0, ...)}."""
    m = re.search(r"entry_computation_layout=\{", hlo_text)
    if not m:
        return [], []
    # shape layouts contain nested {}: scan to the matching close brace
    depth, i = 1, m.end()
    while depth and i < len(hlo_text):
        depth += {"{": 1, "}": -1}.get(hlo_text[i], 0)
        i += 1
    body = hlo_text[m.end():i - 1]
    if "->" not in body:
        return [], []
    params_txt, out_txt = body.split("->", 1)
    params = [parse_shape(p) for p in _split_shapes(params_txt)]
    outs = [parse_shape(o) for o in _split_shapes(out_txt)]
    return [p for p in params if p], [o for o in outs if o]


def _split_shapes(text: str) -> List[str]:
    """Split '(f32[2]{1,0:T(8,128)}, f32[]{:T(128)})' on top-level commas
    (commas also appear inside [] and {})."""
    text = text.strip()
    if text.startswith("("):
        text = text[1:]
    if text.endswith(")"):
        text = text[:-1]
    parts, depth, cur = [], 0, []
    for ch in text:
        if ch in "[{(":
            depth += 1
        elif ch in "]})":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p for p in (s.strip() for s in parts) if p]


_ALIAS_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{[\d,\s]*\}(?:,\s*([a-z\-]+))?\)")


def parse_input_output_alias(hlo_text: str) -> Dict[int, int]:
    """{flat output index: parameter number} from the module header's
    input_output_alias block (empty dict when nothing is aliased).  Only
    flat (non-nested) output tuples are produced by our step functions."""
    m = re.search(r"input_output_alias=\{", hlo_text)
    if not m:
        return {}
    depth, i = 1, m.end()
    while depth and i < len(hlo_text):
        depth += {"{": 1, "}": -1}.get(hlo_text[i], 0)
        i += 1
    out: Dict[int, int] = {}
    for am in _ALIAS_RE.finditer(hlo_text[m.end():i - 1]):
        idx_txt = am.group(1).strip()
        out_idx = int(idx_txt.split(",")[0]) if idx_txt else 0
        out[out_idx] = int(am.group(2))
    return out


# ---------------------------------------------------------------------------
# StableHLO (lowered, pre-XLA-pipeline) — SSA def/use for the broadcast
# detector.

_SH_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_SH_BCAST_RE = re.compile(
    r"%([\w#]+)\s*=\s*(?:\"stablehlo\.broadcast_in_dim\"|"
    r"stablehlo\.broadcast_in_dim)\s*[\(]?%([\w#]+)")
_SH_CC_RE = re.compile(
    r"(?:\"stablehlo\.custom_call\"|stablehlo\.custom_call)\s*"
    r"(?:@([\w.]+)\s*)?\(([^)]*)\)")


def _tensor_elems_bytes(type_txt: str) -> int:
    parts = type_txt.split("x")
    dtype = parts[-1]
    n = _DTYPE_BYTES.get(dtype, 4)
    for d in parts[:-1]:
        if d.isdigit():
            n *= int(d)
    return n


def stablehlo_broadcast_operands(sh_text: str):
    """Yield (cc_target, operand_ssa_name, materialized_bytes,
    source_bytes) for every custom-call operand whose defining op is a
    materializing stablehlo.broadcast_in_dim (result strictly larger than
    its source)."""
    bcasts = {}
    for line in sh_text.splitlines():
        bm = _SH_BCAST_RE.search(line)
        if bm:
            types = _SH_TENSOR_RE.findall(line)
            if len(types) >= 2:
                src_b = _tensor_elems_bytes(types[-2])
                dst_b = _tensor_elems_bytes(types[-1])
                bcasts[bm.group(1)] = (dst_b, src_b, line.strip())
            continue
    results = []
    for line in sh_text.splitlines():
        cm = _SH_CC_RE.search(line)
        if not cm:
            continue
        target = cm.group(1) or ""
        for ref in _REF_RE.findall(cm.group(2)):
            if ref in bcasts:
                dst_b, src_b, _ = bcasts[ref]
                if dst_b > src_b:
                    results.append((target, ref, dst_b, src_b))
    return results
