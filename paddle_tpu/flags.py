"""Global flags tier (reference: python/paddle/fluid/__init__.py:125
__bootstrap__ reading gflags from the environment, e.g. FLAGS_check_nan_inf,
FLAGS_cpu_deterministic, FLAGS_benchmark; framework/operator.cc:777 consumes
check_nan_inf after every op run).

TPU-native shape: flags are plain Python state seeded from `FLAGS_*` env
vars at import, mutable via set_flags()/get_flags() (the modern public
spelling).  check_nan_inf is consumed by the executors as a post-step scan
of fetches and persistable state (the per-op granularity of the reference
would force a host sync between ops — against the one-XLA-program design;
the post-step scan still names the first offending variable).
cpu_deterministic is satisfied by construction — lowerings use counter-based
jax PRNG keys and XLA reductions are run-to-run deterministic on TPU — so
setting it only pins the default program seed.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Any, Dict

__all__ = ["get_flags", "set_flags", "flag"]

_DEFS: Dict[str, Any] = {
    # debugging
    "FLAGS_check_nan_inf": False,
    "FLAGS_benchmark": False,
    # resilience: NaN/Inf step sentinel (resilience/sentinel.py).  Where
    # FLAGS_check_nan_inf raises the moment a non-finite value appears
    # (post-write-back, debugging), check_numerics implements the
    # AMP-loss-scaler recovery contract in Executor.run: the offending
    # step is SKIPPED (persistable state is not written back — previous
    # params stay live), consecutive trips are counted, and after
    # check_numerics_max_consecutive trips the executor raises
    # NonFiniteStepError naming the first offending fetch/var of the
    # streak.  ElasticTrainer lets that raise report the task failed, so
    # the lease machinery re-dispatches it instead of publishing poisoned
    # params.  Turning it on disables state-buffer donation for affected
    # programs (a skipped step must keep the pre-step params alive) and
    # costs one scalar device sync per step for the jitted finite scan.
    "FLAGS_check_numerics": False,
    "FLAGS_check_numerics_max_consecutive": 3,
    # observability (paddle_tpu/observability/): master switch for the
    # unified telemetry spine — per-step executor metrics (wall-time
    # histogram, compile-cache hit/miss, donation status, sentinel
    # skips), trace spans (compile/step/ckpt, exported as one merged
    # Chrome/Perfetto trace), resilience/elastic counters, and the
    # StepStats p50/p99 ring buffer.  Off (default): every instrument
    # returns after a single dict lookup — no locks, allocations, or
    # clock reads on the hot path (tier-1 asserts this).
    "FLAGS_observability": False,
    # per-program bytes/step cost attribution, recorded once per fresh
    # compiled entry when observability is on: "native" prices the
    # executable the host actually runs (cheap — the re-lower hits jax's
    # compile cache), "tpu" prices the CHIP program via the chip-less
    # AOT topology tier (core/aot_tpu.py — minutes for big models, the
    # relay-free conv-epilogue measurement loop), "off" skips costing
    "FLAGS_observability_cost": "off",
    # request-scoped tracing (observability/requesttrace.py): hard
    # per-run cap on how many requests keep FULL span detail in the
    # merged trace.  Tail-based sampling keeps slow (>= rolling p99),
    # errored, shed, timed-out, and quarantined requests; everything
    # else contributes only to metrics.  Once the budget is spent even
    # keep-worthy requests are dropped (counted on
    # paddle_tpu_request_traces{decision="budget_dropped"}) — a
    # long-lived server must not grow host memory one span tree per
    # slow request forever
    "FLAGS_request_trace_budget": 256,
    # flight-recorder dump directory (observability/flight.py): where
    # the black-box JSONL lands when the serving circuit breaker trips
    # or engine.health() enters BROKEN.  "" (default) resolves to
    # <tempdir>/paddle_tpu_flight
    "FLAGS_flight_dir": "",
    # determinism
    "FLAGS_cpu_deterministic": False,
    # accepted for reference-script compatibility; memory/threads are
    # XLA/jax concerns here (documented no-ops)
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_eager_delete_tensor_gb": -1.0,
    "FLAGS_init_allocated_mem": False,
    "FLAGS_paddle_num_threads": 1,
    "FLAGS_use_pinned_memory": True,
    # internal conv compute layout: "NCHW" (reference parity) or "NHWC"
    # (TPU-preferred — convs lower with NHWC dimension_numbers behind
    # boundary transposes that XLA cancels between chained convs).
    # "auto" (default) resolves per compiled program: NHWC when tracing
    # for a TPU device, NCHW otherwise — NHWC measured +8% on-chip and
    # won every round-3 tuner probe, so TPUPlace gets it with no env vars
    # (VERDICT r3 item 5) while CPU keeps bit-parity with the reference
    "FLAGS_conv_layout": "auto",
    # flash-attention backward implementation: "jax" (recompute the
    # reference formulation under jax.vjp — XLA fuses it well),
    # "pallas" (this repo's FlashAttention-2 dq/dkv kernels; O(S*D) HBM
    # in backward), or "jaxlib" (the jax-shipped TPU kernel pair, fwd AND
    # bwd — independent compile behavior, tools/flash_bwd_probe.py
    # compares).  Default jax: the axon relay's remote-compile service has
    # failed on full-model pallas-backward compiles (round 3); on a
    # directly attached TPU host flip to "pallas"/"jaxlib" for long
    # sequences
    "FLAGS_flash_bwd": "jax",
    # conv_bn_add_act implementation: "reference" (XLA conv + BN chain —
    # one op, XLA fuses the epilogue; the parity-safe default) or
    # "pallas" (kernels/conv_epilogue.py: BN stats accumulate inside the
    # conv pass, normalize/residual/act in one epilogue pass — ~4-5
    # activation passes down to 3).  Pallas stays opt-in until the
    # staged probe (tools/conv_epilogue_probe.py) banks a winning
    # on-chip A/B: defaults follow measurements
    "FLAGS_conv_epilogue": "reference",
    # compile-time fusion pass (core/fusion.py): pattern-match
    # conv2d -> batch_norm [-> elementwise_add] -> relu chains in block 0
    # and route them through the one-op conv_bn_add_act tier at lowering
    # time — the program desc itself is untouched.  The op that runs is
    # then picked by FLAGS_conv_epilogue (reference composition vs the
    # pallas conv-epilogue kernel pair).  Default off until a chip A/B
    # banks a win (defaults follow measurements); the bytes/step win is
    # CPU-verifiable via Executor.cost_analysis (tests/test_conv_fusion_pass.py)
    "FLAGS_fuse_conv_epilogue": False,
    # serving (paddle_tpu/serving/): the dynamic batcher's batch-size
    # bucket ladder.  Queued requests coalesce into micro-batches padded
    # UP to the smallest bucket that fits, so a polymorphic-batch AOT
    # artifact (or an executor program) compiles at most once per bucket
    # and never again — arbitrary-size batching would compile every
    # batch size traffic ever produces.  Engine-level knobs (max wait,
    # queue depth, deadlines) live on serving.EngineConfig; this flag
    # only sets the process default ladder
    "FLAGS_serving_buckets": "1,2,4,8,16",
    # paged-attention decode implementation (kernels/paged_attention.py):
    # "auto" (default) streams pages through the pallas ragged
    # paged-attention kernel on TPU whenever pallas_paged_viable accepts
    # the pool geometry (head_dim%128==0, page_size sublane-aligned) and
    # takes the reference gather everywhere else; "reference" forces the
    # gather + flash ragged k_lengths tier; "pallas" forces the kernel
    # (falling back to reference OUTSIDE the envelope, with a one-time
    # log — never a Mosaic compile failure); "interpret" runs the pallas
    # kernel under the interpreter (CPU parity testing)
    "FLAGS_serving_paged_impl": "auto",
    # chip-less linter (paddle_tpu/analysis/pallas.py): the v5e VMEM
    # budget the vmem-overflow detector prices every pallas_call's
    # statically-estimated working set (double-buffered padded blocks +
    # scratch) against.  Default: the full 16 MiB/core
    # (analysis.pallas.V5E_VMEM_BYTES); lower it to lint with headroom
    # for compiler spills, raise it only for a different chip
    "FLAGS_analysis_vmem_budget": 16 * 1024 * 1024,
    # chip-less linter (paddle_tpu/analysis/pallas.py): the scalar-
    # memory budget the smem-overflow detector prices every
    # pallas_call's scalar-prefetch operands + SMEM scratch against.
    # SMEM is where the paged-attention page tables and per-page int8
    # scales live — at 128k contexts (~1k pages/seq) FLAT tables and
    # pool-sized scale rows blow through it, the failure the two-level
    # table view (kernels/paged_attention.TwoLevelTables) exists to
    # avoid.  Default: the modeled 128 KiB/core envelope
    # (analysis.pallas.V5E_SMEM_BYTES)
    "FLAGS_analysis_smem_budget": 128 * 1024,
    # chunked prefill (serving/generate.py): cap on PREFILL tokens one
    # engine step may process across the batch.  0 (default) is
    # uncapped — whole prompts prefill in one pass.  With a cap, long
    # prompts split into <=N-token chunks and the scheduler interleaves
    # decode steps between chunks, bounding how long an in-flight
    # sequence's next token can stall behind someone else's prefill
    # (the TTFT/inter-token-jitter knob for bursty shared-prefix load)
    "FLAGS_serving_prefill_chunk": 0,
    # speculative decoding (serving/generate.py + serving/speculative.py):
    # draft tokens per generating sequence per decode step, proposed by
    # the prompt-lookup drafter (n-gram match against prompt +
    # generation history — no draft model) and verified in ONE
    # multi-token model step through the paged kernel; rejected tokens
    # roll back via KVCachePool.truncate_seq.  0 (default) disables.
    # Greedy output stays token-identical to full_decode; sequences
    # with non-greedy SamplingParams degrade to 0 per-sequence
    "FLAGS_serving_speculate": 0,
    # serving circuit breaker (serving/engine.py): after
    # serving_breaker_threshold CONSECUTIVE batch-dispatch failures the
    # engine opens its breaker — submit() fails fast with
    # EngineUnhealthyError for serving_breaker_cooldown_s seconds, then
    # half-opens (requests probe the backend; one successful dispatch
    # closes it).  Process defaults only; per-engine overrides live on
    # serving.EngineConfig(breaker_threshold=, breaker_cooldown_s=)
    "FLAGS_serving_breaker_threshold": 3,
    "FLAGS_serving_breaker_cooldown_s": 5.0,
    # persistent XLA executable cache directory ("" = disabled): repeated
    # runs of the same program skip compilation entirely — first compiles
    # through the TPU relay cost minutes, so benches/drivers set this.
    # Applied immediately by set_flags (and re-checked at each fresh block
    # compile, core/compiler.py); setting "" disables the cache again.  A
    # backend whose PJRT plugin can't serialize executables logs and
    # continues uncached
    "FLAGS_compile_cache_dir": "",
}

_VALUES: Dict[str, Any] = {}


def _coerce(default: Any, raw: str) -> Any:
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def _bootstrap() -> None:
    for name, default in _DEFS.items():
        raw = os.environ.get(name)
        _VALUES[name] = default if raw is None else _coerce(default, raw)


_bootstrap()


def _canon(name: str) -> str:
    return name if name.startswith("FLAGS_") else "FLAGS_" + name


def flag(name: str) -> Any:
    """Read one flag (accepts 'check_nan_inf' or 'FLAGS_check_nan_inf')."""
    return _VALUES[_canon(name)]


def get_flags(names=None) -> Dict[str, Any]:
    """reference parity: paddle.get_flags."""
    if names is None:
        return dict(_VALUES)
    if isinstance(names, str):
        names = [names]
    return {_canon(n): _VALUES[_canon(n)] for n in names}


# flags restricted to an exact value set (a typo'd value would otherwise
# silently select the default branch at the use site)
_CHOICES: Dict[str, tuple] = {
    "FLAGS_conv_layout": ("auto", "NCHW", "NHWC"),
    "FLAGS_flash_bwd": ("jax", "pallas", "jaxlib"),
    "FLAGS_conv_epilogue": ("reference", "pallas"),
    "FLAGS_observability_cost": ("off", "native", "tpu"),
    "FLAGS_serving_paged_impl": ("auto", "reference", "pallas", "interpret"),
}


# -- trace-time device scope -------------------------------------------------
# Executors enter this scope (keyed off the ACTUAL jax device platform, not
# the Place class) around cache-key computation, compilation, and execution,
# so "auto" flags and the un-set AMP policy resolve to the chip-measured
# winners exactly when the program targets a TPU.  Thread-local: hogwild
# AsyncExecutor threads each carry their own scope.
_tls = threading.local()


def tpu_trace_active() -> bool:
    return getattr(_tls, "tpu_active", False)


@contextlib.contextmanager
def tpu_trace_scope(active: bool):
    prev = getattr(_tls, "tpu_active", False)
    _tls.tpu_active = bool(active)
    try:
        yield
    finally:
        _tls.tpu_active = prev


# one-time notices when an "auto" flag / un-set policy silently resolves to
# the TPU-tuned value (ADVICE r4: there was no runtime signal that a
# TPU-traced program picked bf16/NHWC while paths compiling OUTSIDE the
# trace scope — inference/aot.py export, the py_reader preprocessor —
# resolve to fp32/NCHW reference parity; AOT-exported artifacts therefore
# use reference-parity defaults regardless of target device unless the
# policy is set explicitly)
_auto_noted: set = set()
_auto_noted_lock = threading.Lock()


def note_auto_resolution(kind: str, resolved: str) -> None:
    """Log once per process the first time an auto default engages."""
    with _auto_noted_lock:
        if kind in _auto_noted:
            return
        _auto_noted.add(kind)
    import logging

    logging.getLogger("paddle_tpu").info(
        "auto-resolved %s -> %s for a TPU-traced program (explicit "
        "enable_amp()/FLAGS_conv_layout overrides; programs compiled "
        "outside the TPU trace scope, e.g. AOT export, keep "
        "reference-parity fp32/NCHW)", kind, resolved)


def conv_layout() -> str:
    """FLAGS_conv_layout with "auto" resolved for the active device."""
    v = _VALUES["FLAGS_conv_layout"]
    if v == "auto":
        if tpu_trace_active():
            note_auto_resolution("conv_layout", "NHWC")
            return "NHWC"
        return "NCHW"
    return v


def trace_key() -> tuple:
    """Resolved values of every flag that changes the traced program —
    executors include this (plus amp.state_key()) in compiled-program
    cache keys so a flag flip between runs recompiles instead of reusing
    a stale executable."""
    return (conv_layout(), _VALUES["FLAGS_flash_bwd"],
            _VALUES["FLAGS_conv_epilogue"],
            _VALUES["FLAGS_fuse_conv_epilogue"],
            # not trace-affecting, but executable-affecting: the sentinel
            # turns state-buffer donation off, so a flag flip must land on
            # a different compiled entry instead of reusing one whose
            # donated inputs a skipped step would have to keep alive
            _VALUES["FLAGS_check_numerics"])


def set_flags(flags: Dict[str, Any]) -> None:
    """reference parity: paddle.set_flags({'FLAGS_check_nan_inf': True}).

    Validates the WHOLE dict before committing any value or side effect:
    a typo in one flag must not leave a partial update (or an already-
    redirected compile cache) behind the raised error."""
    staged: Dict[str, Any] = {}
    for name, value in flags.items():
        cname = _canon(name)
        if cname not in _DEFS:
            raise KeyError(f"unknown flag {name!r}")
        default = _DEFS[cname]
        coerced = (
            _coerce(default, value) if isinstance(value, str)
            else type(default)(value)
        )
        if cname in _CHOICES and coerced not in _CHOICES[cname]:
            raise ValueError(
                f"{cname} must be one of {_CHOICES[cname]}, got {coerced!r}")
        staged[cname] = coerced
    _VALUES.update(staged)
    if "FLAGS_compile_cache_dir" in staged:
        # apply immediately: the compile-path hook only fires on cache
        # misses, so a redirect between two cached runs would otherwise
        # be ignored until the next fresh compile (ADVICE r3)
        from .core import compiler

        compiler._maybe_enable_compile_cache()
