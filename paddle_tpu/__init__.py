"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid (reference snapshot: MrGo2008/Paddle @ Fluid 1.2/1.3-dev).

Programs are Block/Op descriptions built from a fluid-style Python API
(layers, append_backward autodiff, in-graph optimizers), lowered wholesale to
XLA via JAX — `TPUPlace` is the first-class device, collectives ride ICI via
jax.sharding instead of NCCL/gRPC.  See SURVEY.md at the repo root for the
structural map to the reference.

Typical use mirrors fluid:

    import paddle_tpu as fluid
    img = fluid.layers.data("img", [1, 28, 28])
    ...
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(fluid.default_startup_program())
    loss_v, = exe.run(feed={...}, fetch_list=[loss])
"""

__version__ = "0.1.0"

from . import jax_compat as _jax_compat  # older-jax aliases first  # noqa: F401
from . import ops as _ops  # registers all op lowerings  # noqa: F401

from .core.framework import (  # noqa: F401
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
    recompute_scope,
    reset_default_env,
)
from .core.place import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    is_compiled_with_cuda,
)
from .core.scope import Scope, global_scope, scope_guard  # noqa: F401
from .core.lod import LoDValue, create_lod_tensor  # noqa: F401
from .core.executor import Executor  # noqa: F401
from .core.amp import enable_amp, disable_amp, amp_dtype  # noqa: F401
from .core.dtypes import enable_x64, x64_enabled, x64_scope  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .core.backward import append_backward, calc_gradient  # noqa: F401
from .core import proto as core  # noqa: F401  (fluid.core-ish alias)

from . import average  # noqa: F401
from . import debugger  # noqa: F401
from . import evaluator  # noqa: F401
from . import clip  # noqa: F401
from . import contrib  # noqa: F401
from . import imperative  # noqa: F401
from . import inference  # noqa: F401
from . import transpiler  # noqa: F401
from . import nets  # noqa: F401
from . import learning_rate_decay  # noqa: F401
from . import unique_name  # noqa: F401
from . import recordio as recordio_writer  # noqa: F401
from .core import backward  # noqa: F401
from .tensor_shim import LoDTensor, LoDTensorArray, Tensor  # noqa: F401
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from .transpiler import InferenceTranspiler  # noqa: F401
from .transpiler import memory_optimize, release_memory  # noqa: F401
from .async_executor import AsyncExecutor  # noqa: F401
from . import distributed  # noqa: F401
from . import elastic  # noqa: F401
from . import net_drawer  # noqa: F401
from .core import enforce  # noqa: F401
from .core.enforce import EnforceNotMet  # noqa: F401
from . import distribute_lookup_table  # noqa: F401
from .data_feed_desc import DataFeedDesc  # noqa: F401
from . import dataset  # noqa: F401
from . import executor  # noqa: F401
from . import io  # noqa: F401
from . import reader  # noqa: F401
from . import recordio  # noqa: F401
from . import resilience  # noqa: F401
from . import serving  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .reader import batch  # noqa: F401
from . import metrics  # noqa: F401
from . import observability  # noqa: F401
from . import profiler  # noqa: F401
from . import parallel  # noqa: F401
from .parallel import BuildStrategy, ExecutionStrategy, ParallelExecutor  # noqa: F401
from .parallel.executor import CompiledProgram  # noqa: F401
from . import initializer  # noqa: F401
from . import layers  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401

# fluid-style direct names
from .initializer import Constant, MSRA, Normal, TruncatedNormal, Uniform, Xavier  # noqa: F401
