"""ResNet (reference configs: benchmark/fluid/models/resnet.py for
cifar10-scale, benchmark/fluid/models/se_resnext.py's imagenet layout).

ResNet-50 is the framework's flagship conv model and the north-star
benchmark (images/sec/chip).  TPU notes: NCHW layouts feed XLA's conv
lowering directly; batch_norm fuses into the conv epilogue; all FLOPs land
on the MXU."""

from __future__ import annotations

import functools

from .. import layers
from .common import ModelSpec, class_batch


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  fuse_bn=False):
    """conv -> BN(+act).  fuse_bn=True emits the recompute-tagged
    fused_bn_add_act op: same numbers, but backward rebuilds the normalize/
    act chain instead of storing it — the HBM-traffic fix for the profile's
    72% elementwise share (CHANGES_r03).  The DEFAULT is False — the
    defaults-follow-measurements rule (VERDICT r4 weak #1): the only
    chip-measured ResNet trajectory (r3, 2225 img/s) ran the unfused
    chain, and the r4 instruction-count watch-item flags ~3x transposes
    on the fused path; the default flips to True the day the chip A/B
    (chip_session fuse_bn_ab) measures the fused op faster.  fuse_bn=False
    also keeps the separate reference-shaped batch_norm op (transpilers
    that pattern-match conv+BN, e.g. the inference fold, want that
    shape)."""
    if fuse_bn == "conv":
        # whole-block one-op tier: the conv itself joins the fusion so
        # FLAGS_conv_epilogue=pallas can accumulate BN stats inside the
        # conv pass (kernels/conv_epilogue.py)
        return layers.conv_bn_add_act(
            input, ch_out, filter_size, stride=stride, padding=padding,
            act=act)
    conv = layers.conv2d(
        input=input, num_filters=ch_out, filter_size=filter_size,
        stride=stride, padding=padding, act=None, bias_attr=False,
    )
    if fuse_bn:
        return layers.fused_bn_add_act(conv, act=act)
    return layers.batch_norm(input=conv, act=act)


def _shortcut(input, ch_out, stride, fuse_bn=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             fuse_bn=fuse_bn)
    return input


def basicblock(input, ch_out, stride, fuse_bn=False):
    s = _shortcut(input, ch_out, stride, fuse_bn=fuse_bn)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, fuse_bn=fuse_bn)
    if fuse_bn == "conv":
        return layers.conv_bn_add_act(conv1, ch_out, 3, residual=s,
                                      stride=1, padding=1, act="relu")
    conv2 = layers.conv2d(conv1, num_filters=ch_out, filter_size=3,
                          stride=1, padding=1, act=None, bias_attr=False)
    if fuse_bn:
        # BN + residual + relu in ONE recompute-tagged op
        return layers.fused_bn_add_act(conv2, s, act="relu")
    bn2 = layers.batch_norm(input=conv2, act=None)
    return layers.elementwise_add(s, bn2, act="relu")


def bottleneck(input, ch_out, stride, fuse_bn=False):
    s = _shortcut(input, ch_out * 4, stride, fuse_bn=fuse_bn)
    conv1 = conv_bn_layer(input, ch_out, 1, 1, 0, fuse_bn=fuse_bn)
    conv2 = conv_bn_layer(conv1, ch_out, 3, stride, 1, fuse_bn=fuse_bn)
    if fuse_bn == "conv":
        return layers.conv_bn_add_act(conv2, ch_out * 4, 1, residual=s,
                                      stride=1, padding=0, act="relu")
    conv3 = layers.conv2d(conv2, num_filters=ch_out * 4, filter_size=1,
                          stride=1, padding=0, act=None, bias_attr=False)
    if fuse_bn:
        return layers.fused_bn_add_act(conv3, s, act="relu")
    bn3 = layers.batch_norm(input=conv3, act=None)
    return layers.elementwise_add(s, bn3, act="relu")


def _layer_warp(block_func, input, ch_out, count, stride, fuse_bn=False):
    res = block_func(input, ch_out, stride, fuse_bn=fuse_bn)
    for _ in range(1, count):
        res = block_func(res, ch_out, 1, fuse_bn=fuse_bn)
    return res


def resnet_imagenet(
    img=None, label=None, depth: int = 50, class_num: int = 1000,
    img_shape=(3, 224, 224), fuse_bn: bool = False,
) -> ModelSpec:
    """ImageNet-scale ResNet: 7x7/2 stem + maxpool + 4 bottleneck stages +
    global average pool + FC."""
    if img is None:
        img = layers.data("image", list(img_shape), dtype="float32")
    if label is None:
        label = layers.data("label", [1], dtype="int64")

    cfg = {
        18: ([2, 2, 2, 2], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    stages, block_func = cfg[depth]

    conv1 = conv_bn_layer(img, ch_out=64, filter_size=7, stride=2, padding=3,
                          fuse_bn=fuse_bn)
    pool1 = layers.pool2d(
        input=conv1, pool_type="max", pool_size=3, pool_stride=2, pool_padding=1
    )
    res1 = _layer_warp(block_func, pool1, 64, stages[0], 1, fuse_bn=fuse_bn)
    res2 = _layer_warp(block_func, res1, 128, stages[1], 2, fuse_bn=fuse_bn)
    res3 = _layer_warp(block_func, res2, 256, stages[2], 2, fuse_bn=fuse_bn)
    res4 = _layer_warp(block_func, res3, 512, stages[3], 2, fuse_bn=fuse_bn)
    pool2 = layers.pool2d(
        input=res4, pool_size=7, pool_type="avg", pool_stride=1, global_pooling=True
    )
    out = layers.fc(input=pool2, size=class_num, act="softmax")

    cost = layers.cross_entropy(input=out, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=out, label=label)
    acc5 = layers.accuracy(input=out, label=label, k=5)

    return ModelSpec(
        name=f"resnet{depth}_imagenet",
        feed_names=[img.name, label.name],
        loss=avg_cost,
        metrics={"acc1": acc, "acc5": acc5},
        synthetic_batch=functools.partial(
            class_batch, img_shape=tuple(img_shape), num_classes=class_num,
            img_name=img.name, label_name=label.name,
        ),
        extras={"predict": out},
    )


def resnet_cifar10(
    img=None, label=None, depth: int = 32, class_num: int = 10,
    fuse_bn: bool = False,
) -> ModelSpec:
    """CIFAR-scale ResNet (6n+2 basicblock layout)."""
    if img is None:
        img = layers.data("image", [3, 32, 32], dtype="float32")
    if label is None:
        label = layers.data("label", [1], dtype="int64")
    assert (depth - 2) % 6 == 0, "depth must be 6n+2"
    n = (depth - 2) // 6

    conv1 = conv_bn_layer(img, ch_out=16, filter_size=3, stride=1, padding=1,
                          fuse_bn=fuse_bn)
    res1 = _layer_warp(basicblock, conv1, 16, n, 1, fuse_bn=fuse_bn)
    res2 = _layer_warp(basicblock, res1, 32, n, 2, fuse_bn=fuse_bn)
    res3 = _layer_warp(basicblock, res2, 64, n, 2, fuse_bn=fuse_bn)
    pool = layers.pool2d(
        input=res3, pool_size=8, pool_type="avg", pool_stride=1, global_pooling=True
    )
    out = layers.fc(input=pool, size=class_num, act="softmax")

    cost = layers.cross_entropy(input=out, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=out, label=label)

    return ModelSpec(
        name=f"resnet{depth}_cifar10",
        feed_names=[img.name, label.name],
        loss=avg_cost,
        metrics={"acc": acc},
        synthetic_batch=functools.partial(
            class_batch, img_shape=(3, 32, 32), num_classes=class_num,
            img_name=img.name, label_name=label.name,
        ),
        extras={"predict": out},
    )
