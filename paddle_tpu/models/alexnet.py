"""AlexNet — the benchmark/paddle/image/alexnet.py config (conv11s4-96 +
LRN, conv5-256 + LRN, conv3-384 x2, conv3-256, three maxpools, fc4096 x2
with dropout, softmax-1000; published baseline: 399 img/s train bs=64 on
2x Xeon 6148, benchmark/IntelOptimizedPaddle.md:61-66)."""

from __future__ import annotations

import functools

from .. import layers
from .common import ModelSpec, class_batch


def alexnet(
    img=None, label=None, class_num: int = 1000, img_shape=(3, 227, 227)
) -> ModelSpec:
    if img is None:
        img = layers.data("image", list(img_shape), dtype="float32")
    if label is None:
        label = layers.data("label", [1], dtype="int64")

    c1 = layers.conv2d(img, num_filters=96, filter_size=11, stride=4,
                       padding=1, act="relu")
    c1 = layers.lrn(c1, n=5, alpha=1e-4, beta=0.75)
    p1 = layers.pool2d(c1, pool_size=3, pool_stride=2, pool_type="max")

    c2 = layers.conv2d(p1, num_filters=256, filter_size=5, padding=2,
                       act="relu")
    c2 = layers.lrn(c2, n=5, alpha=1e-4, beta=0.75)
    p2 = layers.pool2d(c2, pool_size=3, pool_stride=2, pool_type="max")

    c3 = layers.conv2d(p2, num_filters=384, filter_size=3, padding=1,
                       act="relu")
    c4 = layers.conv2d(c3, num_filters=384, filter_size=3, padding=1,
                       act="relu")
    c5 = layers.conv2d(c4, num_filters=256, filter_size=3, padding=1,
                       act="relu")
    p5 = layers.pool2d(c5, pool_size=3, pool_stride=2, pool_type="max")

    fc6 = layers.fc(p5, size=4096, act="relu")
    fc6 = layers.dropout(fc6, dropout_prob=0.5)
    fc7 = layers.fc(fc6, size=4096, act="relu")
    fc7 = layers.dropout(fc7, dropout_prob=0.5)
    predict = layers.fc(fc7, size=class_num, act="softmax")

    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)

    return ModelSpec(
        name="alexnet",
        feed_names=[img.name, label.name],
        loss=avg_cost,
        metrics={"acc": acc},
        synthetic_batch=functools.partial(
            class_batch, img_shape=tuple(img_shape), num_classes=class_num,
            img_name=img.name, label_name=label.name,
        ),
        extras={"predict": predict},
    )
