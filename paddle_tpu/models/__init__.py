"""Model zoo mirroring the reference's benchmark configurations
(reference: benchmark/fluid/models/ — mnist, resnet, vgg, machine
translation / transformer, stacked_dynamic_lstm, se_resnext).

Each builder constructs its graph into the CURRENT default main/startup
programs (use fluid.program_guard to redirect) and returns a ModelSpec with
the feed names, loss/metric variables, and a synthetic-batch generator for
benchmarking without datasets.
"""

from .common import ModelSpec  # noqa: F401
from .mnist import lenet5  # noqa: F401
from .resnet import resnet_cifar10, resnet_imagenet  # noqa: F401
from .alexnet import alexnet  # noqa: F401
from .googlenet import googlenet  # noqa: F401
from .vgg import vgg16, vgg19  # noqa: F401
from .transformer import transformer, TransformerConfig  # noqa: F401
from .stacked_lstm import stacked_dynamic_lstm  # noqa: F401
from .machine_translation import machine_translation  # noqa: F401
from .se_resnext import se_resnext  # noqa: F401
from .deepfm import deepfm  # noqa: F401
