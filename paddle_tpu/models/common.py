"""ModelSpec: what a model builder hands back to benches/tests."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class ModelSpec:
    name: str
    feed_names: List[str]
    loss: Any  # Variable
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # batch_size -> {feed_name: np.ndarray}; deterministic synthetic data
    synthetic_batch: Optional[Callable[[int], Dict[str, np.ndarray]]] = None
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)


def class_batch(
    batch_size: int,
    img_shape,
    num_classes: int,
    img_name: str = "image",
    label_name: str = "label",
    seed: int = 0,
) -> Dict[str, np.ndarray]:
    rng = np.random.RandomState(seed)
    return {
        img_name: rng.rand(batch_size, *img_shape).astype(np.float32),
        label_name: rng.randint(0, num_classes, size=(batch_size, 1)).astype(np.int64),
    }
