"""DeepFM CTR model over high-dimensional sparse features.

The BASELINE north-star CTR config ("DeepFM CTR: high-dim sparse embedding;
pserver -> ICI allreduce").  Reference harness shape:
python/paddle/fluid/tests/unittests/dist_ctr.py:1 (embedding-DNN CTR) and
the pserver sparse path it exercises (distributed lookup_table,
distribute_transpiler.py:1119).  DeepFM = first-order linear term +
FM second-order pairwise term + DNN, all over shared sparse embeddings
(Guo et al., 2017).

TPU-native: the embedding tables emit SelectedRows sparse grads
(is_sparse=True -> ops/tensor_ops.py lookup_table_grad), so a step's
gradient traffic is O(batch * fields * dim), never O(vocab); sparse
optimizer kernels update only touched rows.  Sharding the table over an mp
axis (var.sharding) replaces the pserver row-slicing.
"""

from __future__ import annotations

import functools
from typing import Dict, Sequence

import numpy as np

from .. import layers
from .common import ModelSpec

__all__ = ["deepfm"]


def deepfm(
    num_fields: int = 26,
    vocab_size: int = 1000 * 1000,
    embed_dim: int = 10,
    hidden_sizes: Sequence[int] = (400, 400, 400),
    is_sparse: bool = True,
) -> ModelSpec:
    feat_ids = layers.data("feat_ids", [num_fields], dtype="int64")
    feat_vals = layers.data("feat_vals", [num_fields], dtype="float32")
    label = layers.data("label", [1], dtype="float32")

    vals = layers.reshape(feat_vals, [-1, num_fields, 1])

    # first-order term: sum_f w1[id_f] * val_f           [B, 1]
    w1 = layers.embedding(
        feat_ids, size=[vocab_size, 1], is_sparse=is_sparse, param_attr="deepfm_w1",
    )
    first = layers.reduce_sum(layers.elementwise_mul(w1, vals), dim=[1, 2])
    first = layers.reshape(first, [-1, 1])

    # shared embeddings: e_f = E[id_f] * val_f           [B, F, K]
    emb = layers.embedding(
        feat_ids, size=[vocab_size, embed_dim], is_sparse=is_sparse,
        param_attr="deepfm_emb",
    )
    emb = layers.elementwise_mul(emb, vals)

    # FM second-order: 0.5 * sum_k ((sum_f e)^2 - sum_f e^2)    [B, 1]
    sum_f = layers.reduce_sum(emb, dim=[1])                 # [B, K]
    sum_sq = layers.square(sum_f)
    sq_sum = layers.reduce_sum(layers.square(emb), dim=[1])  # [B, K]
    second = layers.reduce_sum(
        layers.elementwise_sub(sum_sq, sq_sum), dim=[1])
    second = layers.scale(layers.reshape(second, [-1, 1]), scale=0.5)

    # deep component over the flattened field embeddings
    deep = layers.reshape(emb, [-1, num_fields * embed_dim])
    for i, h in enumerate(hidden_sizes):
        deep = layers.fc(deep, size=h, act="relu", name=f"deepfm_fc{i}")
    deep = layers.fc(deep, size=1, name="deepfm_out")

    logit = layers.elementwise_add(layers.elementwise_add(first, second), deep)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label)
    )
    predict = layers.sigmoid(logit)

    return ModelSpec(
        name="deepfm_ctr",
        feed_names=[feat_ids.name, feat_vals.name, label.name],
        loss=loss,
        metrics={},
        synthetic_batch=functools.partial(
            _ctr_batch, num_fields=num_fields, vocab_size=vocab_size,
        ),
        extras={"predict": predict},
    )


def _ctr_batch(
    batch_size: int, num_fields: int, vocab_size: int, seed: int = 0
) -> Dict[str, np.ndarray]:
    rng = np.random.RandomState(seed)
    return {
        "feat_ids": rng.randint(
            0, vocab_size, size=(batch_size, num_fields)
        ).astype(np.int64),
        "feat_vals": rng.rand(batch_size, num_fields).astype(np.float32),
        "label": rng.randint(0, 2, size=(batch_size, 1)).astype(np.float32),
    }
