"""GoogLeNet (Inception v1) — the benchmark/paddle/image/googlenet.py
config: stem conv7s2 + two convs, 9 inception modules, global 7x7 avg
pool, softmax-1000.  Aux towers are dropped exactly as the reference
benchmark drops them ("We remove loss1 and loss2 ... when testing
benchmark", googlenet.py:221).  Published baseline: 250.46 img/s train
bs=64 on 2x Xeon 6148 (benchmark/IntelOptimizedPaddle.md:52-56)."""

from __future__ import annotations

import functools

from .. import layers
from .common import ModelSpec, class_batch


def _inception(x, f1, f3r, f3, f5r, f5, proj):
    b1 = layers.conv2d(x, num_filters=f1, filter_size=1, act="relu")
    b3 = layers.conv2d(x, num_filters=f3r, filter_size=1, act="relu")
    b3 = layers.conv2d(b3, num_filters=f3, filter_size=3, padding=1,
                       act="relu")
    b5 = layers.conv2d(x, num_filters=f5r, filter_size=1, act="relu")
    b5 = layers.conv2d(b5, num_filters=f5, filter_size=5, padding=2,
                       act="relu")
    bp = layers.pool2d(x, pool_size=3, pool_stride=1, pool_padding=1,
                       pool_type="max")
    bp = layers.conv2d(bp, num_filters=proj, filter_size=1, act="relu")
    return layers.concat([b1, b3, b5, bp], axis=1)


def googlenet(
    img=None, label=None, class_num: int = 1000, img_shape=(3, 224, 224)
) -> ModelSpec:
    if img is None:
        img = layers.data("image", list(img_shape), dtype="float32")
    if label is None:
        label = layers.data("label", [1], dtype="int64")

    x = layers.conv2d(img, num_filters=64, filter_size=7, stride=2,
                      padding=3, act="relu")
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_type="max")
    x = layers.conv2d(x, num_filters=64, filter_size=1, act="relu")
    x = layers.conv2d(x, num_filters=192, filter_size=3, padding=1,
                      act="relu")
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_type="max")

    x = _inception(x, 64, 96, 128, 16, 32, 32)      # 3a -> 256
    x = _inception(x, 128, 128, 192, 32, 96, 64)    # 3b -> 480
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_type="max")

    x = _inception(x, 192, 96, 208, 16, 48, 64)     # 4a -> 512
    x = _inception(x, 160, 112, 224, 24, 64, 64)    # 4b
    x = _inception(x, 128, 128, 256, 24, 64, 64)    # 4c
    x = _inception(x, 112, 144, 288, 32, 64, 64)    # 4d -> 528
    x = _inception(x, 256, 160, 320, 32, 128, 128)  # 4e -> 832
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_type="max")

    x = _inception(x, 256, 160, 320, 32, 128, 128)  # 5a
    x = _inception(x, 384, 192, 384, 48, 128, 128)  # 5b -> 1024
    x = layers.pool2d(x, pool_size=7, pool_stride=7, pool_type="avg")
    x = layers.dropout(x, dropout_prob=0.4)

    predict = layers.fc(x, size=class_num, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)

    return ModelSpec(
        name="googlenet",
        feed_names=[img.name, label.name],
        loss=avg_cost,
        metrics={"acc": acc},
        synthetic_batch=functools.partial(
            class_batch, img_shape=tuple(img_shape), num_classes=class_num,
            img_name=img.name, label_name=label.name,
        ),
        extras={"predict": predict},
    )
