"""VGG-16 with batch norm (reference config: benchmark/fluid/models/vgg.py,
tests/book image classification VGG)."""

from __future__ import annotations

import functools

from .. import layers, nets
from .common import ModelSpec, class_batch


def vgg16(
    img=None, label=None, class_num: int = 10, img_shape=(3, 32, 32),
    depth: int = 16,
) -> ModelSpec:
    if img is None:
        img = layers.data("image", list(img_shape), dtype="float32")
    if label is None:
        label = layers.data("label", [1], dtype="int64")

    def conv_block(input, num_filter, groups, dropouts):
        return nets.img_conv_group(
            input=input,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act="relu",
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type="max",
        )

    # VGG-19 has 4 convs in blocks 3-5 where VGG-16 has 3
    # (the IntelOptimizedPaddle.md benchmark model)
    g = 4 if depth == 19 else 3
    conv1 = conv_block(img, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, g, [0.4] * (g - 1) + [0])
    conv4 = conv_block(conv3, 512, g, [0.4] * (g - 1) + [0])
    conv5 = conv_block(conv4, 512, g, [0.4] * (g - 1) + [0])

    drop = layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = layers.fc(input=drop, size=512, act=None)
    bn = layers.batch_norm(input=fc1, act="relu")
    drop2 = layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = layers.fc(input=drop2, size=512, act=None)
    predict = layers.fc(input=fc2, size=class_num, act="softmax")

    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)

    return ModelSpec(
        name="vgg16",
        feed_names=[img.name, label.name],
        loss=avg_cost,
        metrics={"acc": acc},
        synthetic_batch=functools.partial(
            class_batch, img_shape=tuple(img_shape), num_classes=class_num,
            img_name=img.name, label_name=label.name,
        ),
        extras={"predict": predict},
    )


def vgg19(img=None, label=None, class_num: int = 1000,
          img_shape=(3, 224, 224)) -> ModelSpec:
    """The IntelOptimizedPaddle.md VGG-19 benchmark config (ImageNet
    shapes; train bs=64 28.46 img/s, infer bs=1 75.07 img/s on 2x Xeon
    6148 are the published baselines)."""
    import dataclasses

    spec = vgg16(img, label, class_num=class_num, img_shape=img_shape,
                 depth=19)
    return dataclasses.replace(spec, name="vgg19")
