"""Stacked dynamic-LSTM sentiment model (reference config:
benchmark/fluid/models/stacked_dynamic_lstm.py — IMDB sentiment: embedding
-> LSTM over a variable-length sequence batch -> last-step pool -> softmax).

The reference hand-rolls its LSTM inside a DynamicRNN block; the framework's
`dynamic_lstm` layer (one fused lax.scan) expresses the same recurrence
TPU-natively, and `stacked_layers > 1` stacks them the way the
understand_sentiment book test's stacked_lstm_net does
(tests/book/test_understand_sentiment.py:64)."""

from __future__ import annotations

import functools
from typing import Dict

import numpy as np

from .. import layers
from .common import ModelSpec


def seq_class_batch(
    batch_size: int,
    vocab_size: int,
    max_len: int,
    num_classes: int = 2,
    seed: int = 0,
) -> Dict[str, np.ndarray]:
    from ..core.lod import create_lod_tensor

    rng = np.random.RandomState(seed)
    lens = rng.randint(max(1, max_len // 2), max_len + 1, size=(batch_size,))
    words = create_lod_tensor(
        [rng.randint(0, vocab_size, size=(l, 1)).astype(np.int64) for l in lens]
    )
    labels = rng.randint(0, num_classes, size=(batch_size, 1)).astype(np.int64)
    return {"words": words, "label": labels}


def stacked_dynamic_lstm(
    vocab_size: int = 5149,  # imdb.word_dict() size in the reference
    emb_dim: int = 512,
    lstm_size: int = 512,
    stacked_layers: int = 1,
    class_num: int = 2,
    max_len: int = 100,
) -> ModelSpec:
    words = layers.data("words", shape=[1], dtype="int64", lod_level=1)
    label = layers.data("label", shape=[1], dtype="int64")

    sentence = layers.embedding(input=words, size=[vocab_size, emb_dim])
    inp = layers.fc(input=sentence, size=lstm_size, act="tanh")
    for _ in range(stacked_layers):
        proj = layers.fc(input=inp, size=lstm_size * 4)
        hidden, _cell = layers.dynamic_lstm(input=proj, size=lstm_size * 4)
        inp = hidden

    last = layers.sequence_pool(inp, "last")
    logit = layers.fc(input=last, size=class_num, act="softmax")
    cost = layers.cross_entropy(input=logit, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=logit, label=label)

    return ModelSpec(
        name="stacked_dynamic_lstm",
        feed_names=[words.name, label.name],
        loss=avg_cost,
        metrics={"acc": acc},
        synthetic_batch=functools.partial(
            seq_class_batch, vocab_size=vocab_size, max_len=max_len,
            num_classes=class_num,
        ),
    )
