"""Transformer NMT — the framework's flagship model and north-star benchmark
(tokens/sec/chip).  Reference configs: benchmark/fluid dist_transformer /
machine-translation family; architecture is the standard base Transformer
(6+6 layers, d_model 512, 8 heads, ffn 2048, sinusoid positions, label
smoothing), built entirely from framework layers so the whole training step
lowers to one XLA computation.

TPU-first design points:
- static [batch, max_len] shapes; padding masks built in-graph from pad_idx
  (equal -> cast -> -1e9 bias), causal mask from a range/compare triangle —
  no ragged LoD on the hot path.
- Megatron-style tensor parallelism is expressed as sharding annotations on
  the weights (qkv/ffn-in column-split -> 'tp', out-proj/ffn-out row-split),
  applied when the caller trains under a mesh with a 'tp' axis; XLA inserts
  the all-reduces.
- sequence axis annotated 'sp' on the activations via feed sharding for
  context parallelism (ring collectives over ICI).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, Optional

import numpy as np

from .. import layers
from ..core.framework import recompute_scope
from ..param_attr import ParamAttr
from ..initializer import NumpyArrayInitializer, XavierInitializer
from .common import ModelSpec

__all__ = ["TransformerConfig", "transformer"]


@dataclasses.dataclass
class TransformerConfig:
    src_vocab_size: int = 10000
    trg_vocab_size: int = 10000
    max_length: int = 256
    n_layer: int = 6
    n_head: int = 8
    d_model: int = 512
    d_inner: int = 2048
    dropout: float = 0.1
    label_smooth_eps: float = 0.1
    pad_idx: int = 0
    # parallelism: mesh axes the weights/activations are annotated for
    tp_axis: str = "tp"
    shard_weights: bool = True
    # fuse attention into one flash-kernel op (pallas on TPU); key padding
    # rides as lengths, no [Sq, Sk] bias tensor is materialized
    use_flash_attention: bool = False
    # project q/k/v with ONE [d, 3d] matmul (k/v fused to [d, 2d] for
    # cross-attention) instead of three [d, d] ones: fewer, larger MXU
    # calls and one pass over the activations.  Fused weights keep the
    # same column-parallel 'tp' annotation; numerically identical to the
    # unfused projections (test_transformer_fuse_qkv_parity stitches the
    # weights and compares logits).  Default OFF: fusing renames the
    # attention parameters (*_q_w/_k_w/_v_w -> *_qkv_w), which would break
    # loading checkpoints saved from the unfused layout.
    fuse_qkv: bool = False
    # rematerialize the ops of each encoder/decoder layer in backward
    # (fluid.recompute_scope; per-op jax.checkpoint boundaries).  Matters
    # for the fused_attention composite op — its internal [B, H, Sq, Sk]
    # probability matrix is recomputed instead of stored — so pair it
    # with use_flash_attention; a chain of primitive ops keeps its
    # op-boundary activations resident either way.
    use_recompute: bool = False
    # fold label smoothing into softmax_with_cross_entropy (smooth_eps):
    # identical numbers, no [B, S, V] label tensors.  False restores the
    # reference-shaped one_hot -> label_smooth -> soft-label chain
    fuse_smooth_ce: bool = True


def _sinusoid_table(max_len: int, d_model: int) -> np.ndarray:
    pos = np.arange(max_len, dtype=np.float64)[:, None]
    dim = np.arange(d_model // 2, dtype=np.float64)[None, :]
    angle = pos / np.power(10000.0, 2.0 * dim / d_model)
    table = np.zeros((max_len, d_model), dtype=np.float32)
    table[:, 0::2] = np.sin(angle)
    table[:, 1::2] = np.cos(angle)
    return table


class _Builder:
    def __init__(self, cfg: TransformerConfig):
        self.cfg = cfg

    def linear(self, x, d_in, d_out, name, shard=None, act=None, bias=True,
               initializer=None):
        cfg = self.cfg
        w = layers.create_parameter(
            [d_in, d_out], "float32",
            attr=ParamAttr(name=f"{name}_w", initializer=initializer),
        )
        if cfg.shard_weights and shard is not None:
            w.sharding = shard
        out = layers.matmul(x, w)
        if bias:
            b = layers.create_parameter(
                [d_out], "float32", attr=ParamAttr(name=f"{name}_b"), is_bias=True,
            )
            out = layers.elementwise_add(out, b)
        if act == "relu":
            out = layers.relu(out)
        return out

    def mha(self, q_in, kv_in, bias, name, k_lengths=None, causal=False):
        """Multi-head attention.  q_in/kv_in: [B, S, D]; bias: additive
        attention bias broadcastable to [B, H, Sq, Sk].  With
        cfg.use_flash_attention and k_lengths given, the bias tensor is
        bypassed: one fused_attention op (pallas flash kernel) gets the
        causal flag + per-row key counts instead."""
        cfg = self.cfg
        d, h = cfg.d_model, cfg.n_head
        dh = d // h
        tp = cfg.tp_axis

        # fused projections keep the UNFUSED per-projection Xavier scale
        # (fan_in=d, fan_out=d): the default would read fan_out=3d/2d off
        # the fused shape and shrink init ~1.4x, changing from-scratch
        # training vs the separate projections.  The fused weight carries
        # NO tp annotation: a [None, tp] column split of the block-wise
        # q|k|v concat puts shard cuts mid-projection (tp=2 cuts k at
        # 1.5d), so the logical split(3) would cross shard boundaries and
        # force per-layer resharding — under tensor parallelism prefer
        # fuse_qkv=False, whose per-projection column splits stay local.
        proj_init = XavierInitializer(fan_in=d, fan_out=d)
        if cfg.fuse_qkv and q_in is kv_in:
            qkv = self.linear(q_in, d, 3 * d, f"{name}_qkv",
                              initializer=proj_init)
            q, k, v = layers.split(qkv, num_or_sections=3, dim=-1)
        elif cfg.fuse_qkv:
            q = self.linear(q_in, d, d, f"{name}_q", shard=[None, tp])
            kv = self.linear(kv_in, d, 2 * d, f"{name}_kv",
                             initializer=proj_init)
            k, v = layers.split(kv, num_or_sections=2, dim=-1)
        else:
            q = self.linear(q_in, d, d, f"{name}_q", shard=[None, tp])
            k = self.linear(kv_in, d, d, f"{name}_k", shard=[None, tp])
            v = self.linear(kv_in, d, d, f"{name}_v", shard=[None, tp])

        def split_heads(x):
            x = layers.reshape(x, shape=[0, 0, h, dh])
            return layers.transpose(x, perm=[0, 2, 1, 3])  # [B, H, S, dh]

        q, k, v = split_heads(q), split_heads(k), split_heads(v)
        if cfg.use_flash_attention and k_lengths is not None:
            ctx = layers.fused_attention(
                q, k, v, causal=causal, k_lengths=k_lengths
            )
            if cfg.dropout:
                # the flash kernel does not expose attention weights, so
                # regularization moves to the attention output (the common
                # flash-attention approximation of weight dropout)
                ctx = layers.dropout(ctx, dropout_prob=cfg.dropout)
        else:
            q = layers.scale(q, scale=dh ** -0.5)
            scores = layers.matmul(q, k, transpose_y=True)  # [B, H, Sq, Sk]
            scores = layers.elementwise_add(scores, bias)
            weights = layers.softmax(scores)
            if cfg.dropout:
                weights = layers.dropout(weights, dropout_prob=cfg.dropout)
            ctx = layers.matmul(weights, v)  # [B, H, Sq, dh]
        ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
        ctx = layers.reshape(ctx, shape=[0, 0, d])
        return self.linear(ctx, d, d, f"{name}_o", shard=[tp, None])

    def ffn(self, x, name):
        cfg = self.cfg
        tp = cfg.tp_axis
        hidden = self.linear(x, cfg.d_model, cfg.d_inner, f"{name}_in",
                             shard=[None, tp], act="relu")
        if cfg.dropout:
            hidden = layers.dropout(hidden, dropout_prob=cfg.dropout)
        return self.linear(hidden, cfg.d_inner, cfg.d_model, f"{name}_out",
                           shard=[tp, None])

    def sublayer(self, x, out, name):
        """post-norm residual connection: LayerNorm(x + dropout(out))."""
        cfg = self.cfg
        if cfg.dropout:
            out = layers.dropout(out, dropout_prob=cfg.dropout)
        return layers.layer_norm(
            layers.elementwise_add(x, out),
            begin_norm_axis=2,
            param_attr=ParamAttr(name=f"{name}_ln_scale"),
            bias_attr=ParamAttr(name=f"{name}_ln_bias"),
        )

    def embed(self, words, vocab_size, name):
        """token embedding * sqrt(d) + sinusoid positions, then dropout."""
        cfg = self.cfg
        emb = layers.embedding(
            words,
            size=[vocab_size, cfg.d_model],
            padding_idx=cfg.pad_idx,
            param_attr=ParamAttr(name=f"{name}_emb"),
        )
        emb = layers.scale(emb, scale=cfg.d_model ** 0.5)
        seq_len = words.shape[1]
        pos_table = layers.create_parameter(
            [seq_len, cfg.d_model], "float32",
            attr=ParamAttr(
                name=f"{name}_pos_enc", trainable=False,
                initializer=NumpyArrayInitializer(
                    _sinusoid_table(cfg.max_length, cfg.d_model)[:seq_len]
                ),
            ),
        )
        out = layers.elementwise_add(emb, pos_table, axis=1)
        if cfg.dropout:
            out = layers.dropout(out, dropout_prob=cfg.dropout)
        return out

    # -- masks (in-graph, static shapes) --------------------------------
    def pad_bias(self, words):
        """[B, 1, 1, S] additive bias: -1e9 at pad positions."""
        pad = layers.fill_constant_batch_size_like(
            words, shape=[-1, words.shape[1]], dtype="int64", value=self.cfg.pad_idx
        )
        is_pad = layers.cast(layers.equal(words, pad), "float32")
        bias = layers.scale(is_pad, scale=-1e9)
        return layers.unsqueeze(layers.unsqueeze(bias, axes=[1]), axes=[1])

    def seq_lengths(self, words):
        """[B] count of non-pad tokens (key-padding lengths for flash)."""
        pad = layers.fill_constant_batch_size_like(
            words, shape=[-1, words.shape[1]], dtype="int64",
            value=self.cfg.pad_idx,
        )
        not_pad = layers.cast(layers.not_equal(words, pad), "int32")
        return layers.reduce_sum(not_pad, dim=1)

    def causal_bias(self, seq_len):
        """[1, 1, S, S] additive bias: -1e9 above the diagonal."""
        r = layers.range(0, seq_len, 1, "float32")
        rows = layers.unsqueeze(r, axes=[1])  # [S, 1]
        cols = layers.unsqueeze(r, axes=[0])  # [1, S]
        future = layers.cast(layers.greater_than(cols, rows), "float32")
        bias = layers.scale(future, scale=-1e9)
        return layers.unsqueeze(bias, axes=[0, 1])


def transformer(
    cfg: Optional[TransformerConfig] = None,
    src_word=None,
    trg_word=None,
    lbl_word=None,
) -> ModelSpec:
    cfg = cfg or TransformerConfig()
    S = cfg.max_length
    if src_word is None:
        src_word = layers.data("src_word", [S], dtype="int64")
    if trg_word is None:
        trg_word = layers.data("trg_word", [S], dtype="int64")
    if lbl_word is None:
        lbl_word = layers.data("lbl_word", [S], dtype="int64")

    b = _Builder(cfg)

    flash = cfg.use_flash_attention
    src_bias = None if flash else b.pad_bias(src_word)    # enc self-attn
    trg_bias = None if flash else layers.elementwise_add(  # dec self-attn
        b.pad_bias(trg_word), b.causal_bias(S)
    )
    src_len = b.seq_lengths(src_word) if flash else None
    trg_len = b.seq_lengths(trg_word) if flash else None

    layer_scope = (recompute_scope if cfg.use_recompute
                   else contextlib.nullcontext)

    # encoder.  enc_boundaries = [embed out, layer1 out, ...] — the
    # stage cut points parallel.ProgramPipeline uses to pipeline the
    # encoder stack over a pp mesh axis (the embedding + bias ops form
    # the pipeline prefix)
    enc = b.embed(src_word, cfg.src_vocab_size, "src")
    enc_boundaries = [enc]
    for i in range(cfg.n_layer):
        with layer_scope():
            attn = b.mha(enc, enc, src_bias, f"enc_l{i}_attn",
                         k_lengths=src_len)
            enc = b.sublayer(enc, attn, f"enc_l{i}_attn")
            ff = b.ffn(enc, f"enc_l{i}_ffn")
            enc = b.sublayer(enc, ff, f"enc_l{i}_ffn")
            enc_boundaries.append(enc)

    # decoder.  dec_boundaries: ProgramPipeline cut points — the whole
    # encoder lands in the pipeline PREFIX and `enc` rides as a carried
    # side input to every decoder stage (cross-attention)
    dec = b.embed(trg_word, cfg.trg_vocab_size, "trg")
    dec_boundaries = [dec]
    for i in range(cfg.n_layer):
        with layer_scope():
            self_attn = b.mha(dec, dec, trg_bias, f"dec_l{i}_self",
                              k_lengths=trg_len, causal=True)
            dec = b.sublayer(dec, self_attn, f"dec_l{i}_self")
            cross = b.mha(dec, enc, src_bias, f"dec_l{i}_cross",
                          k_lengths=src_len)
            dec = b.sublayer(dec, cross, f"dec_l{i}_cross")
            ff = b.ffn(dec, f"dec_l{i}_ffn")
            dec = b.sublayer(dec, ff, f"dec_l{i}_ffn")
            dec_boundaries.append(dec)

    logits = b.linear(dec, cfg.d_model, cfg.trg_vocab_size, "project",
                      shard=[None, cfg.tp_axis], bias=False)

    # label-smoothed CE, masked to non-pad target positions.  The fused
    # path folds the smoothing into softmax_with_cross_entropy analytically
    # (smooth_eps attr, ops/loss_ops.py): no [B, S, V] one_hot/smooth
    # tensors are ever materialized — at V=32k, bs=32 that chain moved
    # ~1 GB/step of HBM.  fuse_smooth_ce=False keeps the reference-shaped
    # one_hot -> label_smooth -> soft-label CE ops (parity-tested equal).
    if cfg.fuse_smooth_ce:
        cost = layers.softmax_with_cross_entropy(
            logits=logits, label=lbl_word,
            smooth_eps=cfg.label_smooth_eps,
        )  # [B, S, 1]
    else:
        one_hot = layers.one_hot(lbl_word, depth=cfg.trg_vocab_size)
        if cfg.label_smooth_eps:
            smooth = layers.label_smooth(one_hot, epsilon=cfg.label_smooth_eps)
        else:
            smooth = one_hot
        cost = layers.softmax_with_cross_entropy(
            logits=logits, label=smooth, soft_label=True
        )  # [B, S, 1]
    cost = layers.squeeze(cost, axes=[2])
    pad = layers.fill_constant_batch_size_like(
        lbl_word, shape=[-1, S], dtype="int64", value=cfg.pad_idx
    )
    non_pad = layers.cast(layers.not_equal(lbl_word, pad), "float32")
    token_count = layers.reduce_sum(non_pad)
    sum_cost = layers.reduce_sum(layers.elementwise_mul(cost, non_pad))
    avg_cost = layers.elementwise_div(sum_cost, token_count)

    def synthetic_batch(batch_size: int, seed: int = 0) -> Dict[str, np.ndarray]:
        rng = np.random.RandomState(seed)
        # avoid pad_idx in real positions; ragged tails padded with pad_idx
        def seqs():
            w = rng.randint(1, cfg.src_vocab_size, size=(batch_size, S))
            lens = rng.randint(S // 2, S + 1, size=(batch_size,))
            for r, l in zip(w, lens):
                r[l:] = cfg.pad_idx
            return w.astype(np.int64)

        return {
            src_word.name: seqs(),
            trg_word.name: seqs(),
            lbl_word.name: seqs(),
        }

    return ModelSpec(
        name="transformer_base",
        feed_names=[src_word.name, trg_word.name, lbl_word.name],
        loss=avg_cost,
        metrics={"token_count": token_count, "sum_cost": sum_cost},
        synthetic_batch=synthetic_batch,
        extras={"logits": logits, "config": cfg,
                "enc_boundaries": enc_boundaries,
                "dec_boundaries": dec_boundaries},
    )
