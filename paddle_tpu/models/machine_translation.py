"""Seq2seq attention NMT (reference: benchmark/fluid/models/
machine_translation.py — GRU encoder-decoder with Bahdanau-style attention
over WMT data, trained with DynamicRNN; decode via beam search).

TPU-native: the encoder uses the fused `gru` sequence op; the decoder is a
DynamicRNN whose per-step attention runs over the padded encoder states
with length masks (same math, static shapes).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import layers
from ..param_attr import ParamAttr
from .common import ModelSpec

__all__ = ["machine_translation"]


def _attention(dec_state, enc_states, enc_proj, d):
    """Bahdanau concat attention (reference: machine_translation.py
    simple_attention): score = v . tanh(W_enc h_enc + W_dec s)."""
    dec_proj = layers.fc(dec_state, size=d, bias_attr=False)  # [B, d]
    # broadcast the decoder projection over the time axis of the LoD states
    mix = layers.tanh(
        layers.elementwise_add(
            enc_proj, layers.unsqueeze(dec_proj, axes=[1])
        )
    )
    e = layers.fc(mix, size=1, bias_attr=False)  # LoD [B, S, 1]
    w = layers.sequence_softmax(e)  # softmax over time, masked by lengths
    scaled = layers.elementwise_mul(enc_states, w)  # broadcast last dim
    return layers.sequence_pool(scaled, "sum")  # [B, 2E]


def machine_translation(
    dict_size: int = 10000,
    embedding_dim: int = 512,
    encoder_size: int = 512,
    decoder_size: int = 512,
    max_length: int = 50,
    beam_size: int = 3,
) -> ModelSpec:
    src = layers.data("src_word_id", [1], dtype="int64", lod_level=1)
    trg = layers.data("target_sequence", [1], dtype="int64", lod_level=1)
    lbl = layers.data("label_sequence", [1], dtype="int64", lod_level=1)

    # encoder: embed -> fc -> bigru (fwd + reversed)
    src_emb = layers.embedding(
        src, size=[dict_size, embedding_dim],
        param_attr=ParamAttr(name="src_emb"),
    )
    enc_in = layers.fc(src_emb, size=encoder_size * 3, bias_attr=False)
    enc_fwd = layers.dynamic_gru(enc_in, size=encoder_size)
    enc_bwd = layers.dynamic_gru(enc_in, size=encoder_size, is_reverse=True)
    enc_states = layers.concat([enc_fwd, enc_bwd], axis=-1)  # [B, S, 2E]
    enc_last = layers.sequence_last_step(enc_fwd)

    enc_proj = layers.fc(enc_states, size=decoder_size, bias_attr=False)

    # decoder with per-step attention
    trg_emb = layers.embedding(
        trg, size=[dict_size, embedding_dim],
        param_attr=ParamAttr(name="trg_emb"),
    )
    init_state = layers.fc(enc_last, size=decoder_size, act="tanh")

    drnn = layers.DynamicRNN()
    with drnn.block():
        word = drnn.step_input(trg_emb)
        prev = drnn.memory(init=init_state)
        enc_s = drnn.static_input(enc_states)
        enc_p = drnn.static_input(enc_proj)
        ctx = _attention(prev, enc_s, enc_p, decoder_size)
        inp = layers.concat([word, ctx], axis=-1)
        h = layers.fc(input=[inp, prev], size=decoder_size, act="tanh")
        drnn.update_memory(prev, h)
        out = layers.fc(h, size=dict_size, act="softmax")
        drnn.output(out)
    probs = drnn()

    cost = layers.cross_entropy(input=probs, label=lbl)
    loss = layers.mean(layers.sequence_pool(cost, "sum"))

    def synthetic_batch(batch_size: int, seed: int = 0):
        rng = np.random.RandomState(seed)
        from ..core.lod import create_lod_tensor

        lens = rng.randint(4, 12, size=batch_size)
        mk = lambda l: rng.randint(1, dict_size, size=(l, 1)).astype("int64")
        srcs = [mk(l) for l in lens]
        trgs = [mk(l) for l in lens]
        lbls = [np.roll(t, -1, axis=0) for t in trgs]
        return {
            "src_word_id": create_lod_tensor(srcs),
            "target_sequence": create_lod_tensor(trgs),
            "label_sequence": create_lod_tensor(lbls),
        }

    return ModelSpec(
        name="machine_translation",
        feed_names=["src_word_id", "target_sequence", "label_sequence"],
        loss=loss,
        synthetic_batch=synthetic_batch,
        extras={"beam_size": beam_size, "max_length": max_length},
    )
