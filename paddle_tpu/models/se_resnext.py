"""SE-ResNeXt-50 (reference: benchmark/fluid/models/se_resnext.py —
cardinality-32 ResNeXt bottlenecks with squeeze-and-excitation)."""

from __future__ import annotations

import numpy as np

from .. import layers
from .common import ModelSpec, class_batch

__all__ = ["se_resnext"]


def _conv_bn(input, num_filters, filter_size, stride=1, groups=1, act=None,
             fuse_bn=False):
    if fuse_bn == "conv":
        # whole-block one-op tier (models/resnet.py conv_bn_layer); the
        # grouped cardinality convs take the reference composition
        # inside the op until a grouped pallas tier exists
        return layers.conv_bn_add_act(
            input, num_filters, filter_size, stride=stride,
            padding=(filter_size - 1) // 2, groups=groups, act=act)
    conv = layers.conv2d(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        groups=groups,
        bias_attr=False,
    )
    if fuse_bn:
        # recompute-tagged fused BN(+act): numerics identical to
        # batch_norm, backward rebuilds the chain (models/resnet.py)
        return layers.fused_bn_add_act(conv, act=act)
    return layers.batch_norm(input=conv, act=act)


def _squeeze_excitation(input, num_channels, reduction_ratio):
    pool = layers.pool2d(
        input=input, pool_type="avg", global_pooling=True
    )
    squeeze = layers.fc(
        input=pool, size=num_channels // reduction_ratio, act="relu"
    )
    excitation = layers.fc(input=squeeze, size=num_channels, act="sigmoid")
    # scale channels: [N, C] -> [N, C, 1, 1]
    exc = layers.unsqueeze(layers.unsqueeze(excitation, axes=[2]), axes=[3])
    return layers.elementwise_mul(input, exc)


def _shortcut(input, ch_out, stride, fuse_bn=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return _conv_bn(input, ch_out, 1, stride, fuse_bn=fuse_bn)
    return input


def _bottleneck(input, num_filters, stride, cardinality, reduction_ratio,
                fuse_bn=False):
    conv0 = _conv_bn(input, num_filters, 1, act="relu", fuse_bn=fuse_bn)
    conv1 = _conv_bn(
        conv0, num_filters, 3, stride=stride, groups=cardinality, act="relu",
        fuse_bn=fuse_bn
    )
    conv2 = _conv_bn(conv1, num_filters * 2, 1, fuse_bn=fuse_bn)
    scaled = _squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = _shortcut(input, num_filters * 2, stride, fuse_bn=fuse_bn)
    return layers.relu(layers.elementwise_add(short, scaled))


def se_resnext(
    class_num: int = 1000,
    layers_cfg=(3, 4, 6, 3),
    cardinality: int = 32,
    reduction_ratio: int = 16,
    img_shape=(3, 224, 224),
    fuse_bn: bool = False,
) -> ModelSpec:
    img = layers.data("image", list(img_shape), dtype="float32")
    label = layers.data("label", [1], dtype="int64")

    conv = _conv_bn(img, 64, 7, stride=2, act="relu", fuse_bn=fuse_bn)
    conv = layers.pool2d(
        input=conv, pool_size=3, pool_stride=2, pool_padding=1,
        pool_type="max",
    )
    num_filters_list = [128, 256, 512, 1024]
    for block, depth in enumerate(layers_cfg):
        for i in range(depth):
            conv = _bottleneck(
                conv,
                num_filters_list[block],
                stride=2 if i == 0 and block != 0 else 1,
                cardinality=cardinality,
                reduction_ratio=reduction_ratio,
                fuse_bn=fuse_bn,
            )
    pool = layers.pool2d(input=conv, pool_type="avg", global_pooling=True)
    drop = layers.dropout(pool, dropout_prob=0.2)
    out = layers.fc(input=drop, size=class_num, act="softmax")

    cost = layers.cross_entropy(input=out, label=label)
    loss = layers.mean(cost)
    acc = layers.accuracy(input=out, label=label)

    def synthetic_batch(batch_size: int, seed: int = 0):
        return class_batch(batch_size, img_shape, class_num, seed=seed)

    return ModelSpec(
        name="se_resnext",
        feed_names=["image", "label"],
        loss=loss,
        metrics={"acc": acc},
        synthetic_batch=synthetic_batch,
    )
