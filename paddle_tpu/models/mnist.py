"""MNIST LeNet-5 (reference config: benchmark/fluid/models/mnist.py,
tests/book/test_recognize_digits.py): two conv+pool stages, a hidden FC,
softmax classifier."""

from __future__ import annotations

import functools

from .. import layers, nets
from .common import ModelSpec, class_batch


def lenet5(img=None, label=None, class_num: int = 10) -> ModelSpec:
    if img is None:
        img = layers.data("image", [1, 28, 28], dtype="float32")
    if label is None:
        label = layers.data("label", [1], dtype="int64")

    conv1 = nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20,
        pool_size=2, pool_stride=2, act="relu",
    )
    conv2 = nets.simple_img_conv_pool(
        input=conv1, filter_size=5, num_filters=50,
        pool_size=2, pool_stride=2, act="relu",
    )
    hidden = layers.fc(conv2, size=500, act="relu")
    predict = layers.fc(hidden, size=class_num, act="softmax")

    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)

    return ModelSpec(
        name="mnist_lenet5",
        feed_names=[img.name, label.name],
        loss=avg_cost,
        metrics={"acc": acc},
        synthetic_batch=functools.partial(
            class_batch, img_shape=(1, 28, 28), num_classes=class_num,
            img_name=img.name, label_name=label.name,
        ),
        extras={"predict": predict},
    )
