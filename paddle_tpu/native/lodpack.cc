// Native LoD batch packer (reference analogue: the sequence-layout
// shufflers in paddle/fluid/operators/math/sequence_padding.cc — the
// reference packs ragged sequence batches into padded layouts in C++;
// here the host-side pack feeds the padded [N, maxT, F] LoDValue the XLA
// program consumes).
//
// Plain-C ABI for ctypes (pybind11 unavailable in this image):
//   lp_pack_flat(src, elem_size, lens, n, feat, max_len, dst)
//     src: concatenated rows, row i occupying lens[i]*feat elements;
//     dst: pre-allocated n*max_len*feat*elem_size bytes; the function
//     copies each row to its padded slot and zeroes the padding tail.
//   lp_pack_rows(srcs, elem_size, lens, n, feat, max_len, dst)
//     srcs: array of n row pointers (non-contiguous inputs).
// Both return 0 on success, nonzero on bad arguments.

#include <cstdint>
#include <cstring>

extern "C" {

int lp_pack_flat(const char* src, long elem_size, const int* lens, long n,
                 long feat, long max_len, char* dst) {
  if (!src || !dst || !lens || elem_size <= 0 || n < 0 || feat <= 0 ||
      max_len < 0) {
    return 1;
  }
  const long row_bytes = max_len * feat * elem_size;
  long off = 0;
  for (long i = 0; i < n; ++i) {
    const long len = lens[i];
    if (len < 0 || len > max_len) return 2;
    const long used = len * feat * elem_size;
    char* out = dst + i * row_bytes;
    std::memcpy(out, src + off, used);
    std::memset(out + used, 0, row_bytes - used);
    off += used;
  }
  return 0;
}

int lp_pack_rows(const char* const* srcs, long elem_size, const int* lens,
                 long n, long feat, long max_len, char* dst) {
  if (!srcs || !dst || !lens || elem_size <= 0 || n < 0 || feat <= 0 ||
      max_len < 0) {
    return 1;
  }
  const long row_bytes = max_len * feat * elem_size;
  for (long i = 0; i < n; ++i) {
    const long len = lens[i];
    if (len < 0 || len > max_len || !srcs[i]) return 2;
    const long used = len * feat * elem_size;
    char* out = dst + i * row_bytes;
    std::memcpy(out, srcs[i], used);
    std::memset(out + used, 0, row_bytes - used);
  }
  return 0;
}

}  // extern "C"
