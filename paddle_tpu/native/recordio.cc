// RecordIO: chunked record file with per-chunk CRC32
// (reference: recordio/ — header.{h,cc} magic+checksum+compressor+len,
// chunk.{h,cc} record framing, writer.cc / scanner.cc APIs).
//
// TPU-native rebuild notes: this is a NEW on-disk format, deliberately NOT
// wire-compatible with the reference's (magic 0x0CDB0CDB here vs the
// reference's kMagicNumber 0x01020304, and the header carries
// num_records:u32 + payload_len:u64 instead of checksum/compressor/len
// framing) — files written by the upstream framework cannot be read and
// vice versa.  It keeps the reference's *design*: chunked sequential
// layout (so shards stream from disk/NFS at full bandwidth on TPU hosts),
// CRC32 integrity per chunk, a compressor field (0=plain is the only
// value emitted; snappy is a reserved flag).
//
// On-disk format, little-endian:
//   chunk := magic:u32 (0x0CDB0CDB) | crc32:u32 | compressor:u32 (0=plain)
//            | num_records:u32 | payload_len:u64 | payload
//   payload := { rec_len:u32 | rec_bytes } * num_records
//
// Exposed as a C ABI for ctypes (pybind11 is not available in this image).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x0CDB0CDBu;

// CRC32 (IEEE), table-driven.
uint32_t crc32_update(uint32_t crc, const uint8_t* buf, size_t len) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = c & 1 ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  crc = ~crc;
  for (size_t i = 0; i < len; i++) crc = table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

struct Writer {
  FILE* f = nullptr;
  std::vector<uint8_t> payload;
  uint32_t num_records = 0;
  uint32_t max_records = 0;

  int flush_chunk() {
    if (num_records == 0) return 0;
    uint32_t crc = crc32_update(0, payload.data(), payload.size());
    uint32_t compressor = 0;
    uint64_t len = payload.size();
    if (fwrite(&kMagic, 4, 1, f) != 1) return -1;
    if (fwrite(&crc, 4, 1, f) != 1) return -1;
    if (fwrite(&compressor, 4, 1, f) != 1) return -1;
    if (fwrite(&num_records, 4, 1, f) != 1) return -1;
    if (fwrite(&len, 8, 1, f) != 1) return -1;
    if (len && fwrite(payload.data(), 1, len, f) != len) return -1;
    payload.clear();
    num_records = 0;
    return 0;
  }
};

struct Scanner {
  FILE* f = nullptr;
  std::vector<uint8_t> payload;
  size_t pos = 0;
  uint32_t remaining = 0;
  std::vector<uint8_t> record;

  // loads the next chunk; returns 0 ok, 1 eof, -1 corrupt
  int load_chunk() {
    uint32_t magic, crc, compressor, num;
    uint64_t len;
    if (fread(&magic, 4, 1, f) != 1) return 1;
    if (magic != kMagic) return -1;
    if (fread(&crc, 4, 1, f) != 1) return -1;
    if (fread(&compressor, 4, 1, f) != 1) return -1;
    if (fread(&num, 4, 1, f) != 1) return -1;
    if (fread(&len, 8, 1, f) != 1) return -1;
    // validate against the remaining file size so a corrupt length field
    // reports corruption instead of throwing across the C ABI
    long here = ftell(f);
    if (here < 0) return -1;
    if (fseek(f, 0, SEEK_END) != 0) return -1;
    long end_pos = ftell(f);
    if (fseek(f, here, SEEK_SET) != 0) return -1;
    if (end_pos < here || len > static_cast<uint64_t>(end_pos - here)) return -1;
    payload.resize(len);
    if (len && fread(payload.data(), 1, len, f) != len) return -1;
    if (crc32_update(0, payload.data(), payload.size()) != crc) return -1;
    pos = 0;
    remaining = num;
    return 0;
  }
};

}  // namespace

extern "C" {

void* rio_writer_open(const char* path, uint32_t max_chunk_records) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  w->max_records = max_chunk_records ? max_chunk_records : 1000;
  return w;
}

int rio_writer_write(void* handle, const uint8_t* data, uint64_t len) {
  Writer* w = static_cast<Writer*>(handle);
  if (len > UINT32_MAX) return -1;  // rec_len frame is u32; refuse, don't truncate
  uint32_t rec_len = static_cast<uint32_t>(len);
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&rec_len);
  w->payload.insert(w->payload.end(), p, p + 4);
  w->payload.insert(w->payload.end(), data, data + len);
  w->num_records++;
  if (w->num_records >= w->max_records) return w->flush_chunk();
  return 0;
}

int rio_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  int rc = w->flush_chunk();
  fclose(w->f);
  delete w;
  return rc;
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Scanner* s = new Scanner();
  s->f = f;
  return s;
}

// Returns record length and sets *out to an internal buffer valid until the
// next call; -1 on EOF, -2 on corruption.
int64_t rio_scanner_next(void* handle, const uint8_t** out) {
  Scanner* s = static_cast<Scanner*>(handle);
  while (s->remaining == 0) {
    int rc = s->load_chunk();
    if (rc == 1) return -1;
    if (rc == -1) return -2;
  }
  if (s->pos + 4 > s->payload.size()) return -2;
  uint32_t rec_len;
  memcpy(&rec_len, s->payload.data() + s->pos, 4);
  s->pos += 4;
  if (s->pos + rec_len > s->payload.size()) return -2;
  s->record.assign(s->payload.begin() + s->pos,
                   s->payload.begin() + s->pos + rec_len);
  s->pos += rec_len;
  s->remaining--;
  *out = s->record.data();
  return static_cast<int64_t>(rec_len);
}

void rio_scanner_close(void* handle) {
  Scanner* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
