// Native MultiSlot text parser (reference:
// paddle/fluid/framework/data_feed.cc MultiSlotDataFeed::ParseOneInstance —
// the reference parses slot text in C++ on reader threads; the Python
// fallback in async_executor.py is ~30x slower on wide CTR lines).
//
// Plain-C ABI for ctypes (pybind11 unavailable in this image):
//   ms_parse_buffer(data, len, num_slots, slot_types, lineno_base)
//     -> handle; data is a span of whole text lines (the Python side
//     streams the file in line-aligned chunks, bounding worker memory)
//     slot_types[i]: 0 = float slot, 1 = uint64 id slot
//   ms_error(h)        -> 0 ok, else 1-based line number of the parse error
//   ms_num_lines(h)    -> parsed instance count
//   ms_slot_total(h,s) -> total value count of slot s across all lines
//   ms_slot_lens(h,s,out_int32)     per-line value counts
//   ms_slot_values_f / ms_slot_values_i  copy concatenated values out
//   ms_free(h)
//
// Layout is struct-of-arrays per slot so the Python side can wrap the
// copies directly as (values, lengths) LoD pairs without re-walking rows.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cerrno>
#include <vector>

namespace {

struct Slot {
  int type;  // 0 float, 1 int64
  std::vector<float> fvals;
  std::vector<long long> ivals;
  std::vector<int> lens;
};

struct MsFile {
  std::vector<Slot> slots;
  long num_lines = 0;
  long error_line = 0;  // 1-based; 0 = ok
};

inline const char* skip_ws(const char* p) {
  while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
  return p;
}

}  // namespace

namespace {

// parse one NUL-terminated line; returns false on malformed input
bool parse_line(const char* p, MsFile* h, int num_slots) {
  for (int s = 0; s < num_slots; ++s) {
    char* end = nullptr;
    long cnt = std::strtol(p, &end, 10);
    if (end == p || cnt < 0) return false;
    p = end;
    Slot& slot = h->slots[s];
    slot.lens.push_back(static_cast<int>(cnt));
    for (long v = 0; v < cnt; ++v) {
      p = skip_ws(p);
      if (slot.type == 0) {
        float val = std::strtof(p, &end);
        if (end == p) return false;
        slot.fvals.push_back(val);
      } else {
        // uint64 sparse ids (hashed features exceed 2^63): parse unsigned
        // with a range check and store the bit pattern in int64 — numpy
        // views the same 8 bytes, so id identity is preserved
        errno = 0;
        unsigned long long val = std::strtoull(p, &end, 10);
        if (end == p || errno == ERANGE) return false;
        slot.ivals.push_back(static_cast<long long>(val));
      }
      p = end;
    }
    p = skip_ws(p);
  }
  return true;
}

}  // namespace

extern "C" {

// Parse an in-memory span of whole text lines (lines separated by \n; the
// buffer need not end with one).  lineno_base offsets reported error lines
// so chunked callers get file-absolute numbers.
MsFile* ms_parse_buffer(const char* buf, long len, int num_slots,
                        const int* slot_types, long lineno_base) {
  MsFile* h = new MsFile();
  h->slots.resize(num_slots);
  for (int i = 0; i < num_slots; ++i) h->slots[i].type = slot_types[i];
  long lineno = lineno_base;
  const char* cur = buf;
  const char* bufend = buf + len;
  std::vector<char> scratch;
  while (cur < bufend) {
    const char* nl = static_cast<const char*>(
        std::memchr(cur, '\n', bufend - cur));
    const char* stop = nl ? nl : bufend;
    ++lineno;
    scratch.assign(cur, stop);
    scratch.push_back('\0');
    const char* p = skip_ws(scratch.data());
    if (*p != '\0') {
      if (!parse_line(p, h, num_slots)) {
        h->error_line = lineno;
        break;
      }
      ++h->num_lines;
    }
    cur = nl ? nl + 1 : bufend;
  }
  return h;
}

long ms_error(MsFile* h) { return h ? h->error_line : -1; }

long ms_num_lines(MsFile* h) { return h->num_lines; }

long ms_slot_total(MsFile* h, int s) {
  const Slot& slot = h->slots[s];
  return slot.type == 0 ? static_cast<long>(slot.fvals.size())
                        : static_cast<long>(slot.ivals.size());
}

void ms_slot_lens(MsFile* h, int s, int* out) {
  const Slot& slot = h->slots[s];
  std::memcpy(out, slot.lens.data(), slot.lens.size() * sizeof(int));
}

void ms_slot_values_f(MsFile* h, int s, float* out) {
  const Slot& slot = h->slots[s];
  std::memcpy(out, slot.fvals.data(), slot.fvals.size() * sizeof(float));
}

void ms_slot_values_i(MsFile* h, int s, long long* out) {
  const Slot& slot = h->slots[s];
  std::memcpy(out, slot.ivals.data(),
              slot.ivals.size() * sizeof(long long));
}

void ms_free(MsFile* h) { delete h; }

}  // extern "C"
