"""Native (C++) runtime components, built on demand with g++.

The reference ships its runtime as C++ (paddle/fluid/...); here the compute
path is XLA, and the native layer covers host-side IO: recordio serde (and,
as it grows, the host data pipeline).  Libraries build once into this
directory; callers must handle `load() is None` with a Python fallback
(pybind11 is unavailable in this image, so the ABI is plain C via ctypes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE = {}


def load(name: str) -> "ctypes.CDLL | None":
    """Build (if needed) and dlopen native/<name>.cc -> lib<name>.so."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        src = os.path.join(_DIR, f"{name}.cc")
        lib = os.path.join(_DIR, f"lib{name}.so")
        try:
            needs_build = os.path.exists(src) and (
                not os.path.exists(lib)
                or os.path.getmtime(lib) < os.path.getmtime(src)
            )
            if needs_build:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", lib],
                    check=True, capture_output=True, timeout=120,
                )
            handle = ctypes.CDLL(lib)
        except Exception:
            handle = None
        _CACHE[name] = handle
        return handle
