"""Program rewriters (reference: python/paddle/fluid/transpiler/).

* DistributeTranspiler — maps the reference's pserver/nccl2 modes onto SPMD
  mesh execution (see distribute_transpiler.py docstring).
* memory_optimize / release_memory — the reference's liveness-based var
  reuse (memory_optimization_transpiler.py).  XLA's buffer assignment owns
  memory reuse end-to-end, so these validate args and return unchanged
  programs (kept for API parity).
* InferenceTranspiler — the reference folds BN/scale into conv weights
  (inference_transpiler.py); XLA's fusion subsumes it, identity here.
"""

from __future__ import annotations

from .distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
    slice_variable,
)
from .ps_dispatcher import HashName, PSDispatcher, RoundRobin  # noqa: F401

__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "InferenceTranspiler",
    "memory_optimize",
    "release_memory",
    "HashName",
    "RoundRobin",
]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """reference: memory_optimization_transpiler.py memory_optimize.
    XLA buffer assignment + donation already reuse buffers; no-op."""
    return None


def release_memory(input_program, skip_opt_set=None):
    """reference: memory_optimization_transpiler.py release_memory; XLA
    frees dead buffers itself."""
    return None


class InferenceTranspiler:
    """reference: inference_transpiler.py InferenceTranspiler."""

    def transpile(self, program, place, scope=None):
        # conv+bn folding, relu fusion etc. are XLA fusions; the program is
        # already inference-shaped after Program.clone(for_test=True)
        return None
