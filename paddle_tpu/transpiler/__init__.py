"""Program rewriters (reference: python/paddle/fluid/transpiler/).

* DistributeTranspiler — maps the reference's pserver/nccl2 modes onto SPMD
  mesh execution (see distribute_transpiler.py docstring).
* memory_optimize / release_memory — the reference's liveness-based var
  reuse (memory_optimization_transpiler.py).  XLA's buffer assignment owns
  memory reuse end-to-end, so these validate args and return unchanged
  programs (kept for API parity).
* InferenceTranspiler — real conv+batch_norm fold (see
  inference_transpiler.py in this package); the reference's MKLDNN-only
  relu/eltwise fusion passes stay absent because XLA fuses those epilogues
  itself.
"""

from __future__ import annotations

from .distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
    slice_variable,
)
from .inference_transpiler import InferenceTranspiler  # noqa: F401
from .ps_dispatcher import HashName, PSDispatcher, RoundRobin  # noqa: F401

__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "InferenceTranspiler",
    "memory_optimize",
    "release_memory",
    "HashName",
    "RoundRobin",
]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """reference: memory_optimization_transpiler.py memory_optimize.
    XLA buffer assignment + donation already reuse buffers; no-op."""
    return None


def release_memory(input_program, skip_opt_set=None):
    """reference: memory_optimization_transpiler.py release_memory; XLA
    frees dead buffers itself."""
    return None


