"""DistributeTranspiler
(reference: python/paddle/fluid/transpiler/distribute_transpiler.py:148).

The reference rewrites one Program into trainer programs (grads -> send +
barriers, params <- recv) and pserver programs (listen_and_serv running
sliced optimizer blocks) over gRPC, or appends gen_nccl_id for collective
("nccl2") mode.

TPU-native mapping — the whole RPC/NCCL plane collapses into SPMD:

* collective ("nccl2") mode IS the native path: the trainer program is the
  original program; data parallelism happens through mesh shardings
  (ParallelExecutor) and gradient psum over ICI.  Multi-host wiring uses
  jax.distributed (paddle_tpu.parallel.env.init_distributed) instead of
  broadcasting an ncclUniqueId.
* pserver mode maps onto the SAME collective execution: there are no
  parameter-server processes on a TPU pod.  transpile() still performs the
  reference's bookkeeping — parameter slicing across the virtual pserver
  endpoints (slice_variable), per-endpoint optimize-block programs — so
  code and tests that inspect get_pserver_program()/get_trainer_program()
  keep working, and sliced optimizer state maps onto ZeRO-style sharded
  optimizer state (BuildStrategy.ReduceStrategy.Reduce).
* the distributed (sharded) embedding path of the reference
  (split_ids/prefetch over pservers) maps to vocab-sharded embedding
  tables: annotate the table with a mesh axis (ParamAttr sharding) and the
  XLA SPMD partitioner inserts the all-to-all the pserver RPC used to do.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..core.framework import Program, default_main_program
from .ps_dispatcher import PSDispatcher, RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig", "slice_variable"]


@dataclass
class DistributeTranspilerConfig:
    """reference: distribute_transpiler.py:126."""

    slice_var_up: bool = True
    split_method: type = RoundRobin
    min_block_size: int = 8192
    # DC-ASGD (reference: distribute_transpiler.py:141 enable_dc_asgd —
    # delay-compensated async SGD on the pserver optimize block)
    enable_dc_asgd: bool = False
    dc_asgd_lambda: float = 0.04
    # TPU-native extras
    mode: str = "pserver"  # "pserver" | "nccl2" | "collective"


def slice_variable(var_list, slice_count: int, min_block_size: int = 8192):
    """Split vars into ~even blocks of >= min_block_size elements
    (reference: distribute_transpiler.py:80 slice_variable)."""
    blocks = []
    for var in var_list:
        split_count = slice_count
        numel = 1
        for d in var.shape:
            numel *= max(int(d), 1)
        max_pserver_count = int(numel / float(min_block_size))
        if max_pserver_count == 0:
            max_pserver_count = 1
        if max_pserver_count < slice_count:
            split_count = max_pserver_count
        block_size = int((numel + split_count - 1) / split_count)
        if len(var.shape) >= 2:
            dim1 = 1
            for d in var.shape[1:]:
                dim1 *= int(d)
            remains = block_size % dim1
            if remains != 0:
                block_size += dim1 - remains
        split_count = int((numel + block_size - 1) / block_size)
        for i in range(split_count):
            curr = min(block_size, numel - i * block_size)
            blocks.append((var.name, i, curr))
    return blocks


class DistributeTranspiler:
    """reference: distribute_transpiler.py DistributeTranspiler."""

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(
        self,
        trainer_id: int,
        program: Optional[Program] = None,
        pservers: str = "127.0.0.1:6174",
        trainers: int = 1,
        sync_mode: bool = True,
        startup_program: Optional[Program] = None,
        current_endpoint: str = "127.0.0.1:6174",
    ) -> None:
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program or default_main_program()
        self.pserver_endpoints = [
            ep for ep in pservers.split(",") if ep.strip()
        ]

        # parameter slicing bookkeeping (PS-mode program inspection parity)
        params_grads = self._collect_params_grads()
        dispatcher: PSDispatcher = self.config.split_method(
            self.pserver_endpoints
        )
        self.param_blocks = (
            slice_variable(
                [p for p, _ in params_grads],
                len(self.pserver_endpoints),
                self.config.min_block_size,
            )
            if self.config.slice_var_up
            else [
                (p.name, 0, None) for p, _ in params_grads
            ]
        )
        origins = list(dict.fromkeys(b[0] for b in self.param_blocks))
        eps = dispatcher.dispatch([
            self.origin_program.global_block().vars[n] for n in origins
        ])
        self._param_endpoint = dict(zip(origins, eps))

        # annotate the program for the SPMD executors
        self.origin_program._dist_config = {
            "mode": self.config.mode,
            "trainer_id": trainer_id,
            "trainers": trainers,
            "sync_mode": sync_mode,
        }
        self._transpiled = True

    # ------------------------------------------------------------------
    def _collect_params_grads(self):
        block = self.origin_program.global_block()
        out = []
        for p in block.all_parameters():
            g = block.vars.get(p.name + "@GRAD")
            out.append((p, g))
        return out

    def get_trainer_program(self, wait_port=True) -> Program:
        """The trainer program IS the original program: gradient exchange is
        mesh-collective psum under ParallelExecutor, not send/recv ops."""
        if not self._transpiled:
            raise RuntimeError("call transpile() first")
        return self.origin_program

    def get_pserver_program(self, endpoint: str) -> Program:
        """A program holding the optimize ops for the params this endpoint
        owns (reference returns the listen_and_serv program;
        on TPU the same updates run SPMD-sharded, this exists for
        inspection/checkpoint parity)."""
        if not self._transpiled:
            raise RuntimeError("call transpile() first")
        owned = {
            name for name, ep in self._param_endpoint.items() if ep == endpoint
        }
        prog = Program()
        src_block = self.origin_program.desc.block(0)
        dst = prog.global_block()
        opt_types = {
            "sgd", "momentum", "adam", "adamax", "adagrad", "adadelta",
            "rmsprop", "ftrl", "decayed_adagrad", "lars_momentum",
        }
        for op in src_block.ops:
            if op.type in opt_types:
                params = op.input("Param")
                if params and params[0] in owned:
                    import copy

                    op_copy = copy.deepcopy(op)
                    for n in op.input_arg_names() + op.output_arg_names():
                        if src_block.has_var(n) and not dst.desc.has_var(n):
                            vd = src_block.vars[n]
                            dst.create_var(
                                name=n, shape=list(vd.shape), dtype=vd.dtype,
                                persistable=True,
                            )
                    if self.config.enable_dc_asgd:
                        self._append_dc_asgd(dst, op_copy)
                    else:
                        dst.desc.ops.append(op_copy)
        return prog

    def _append_dc_asgd(self, dst, opt_op) -> None:
        """Delay compensation (reference: distribute_transpiler.py:869
        _append_dc_asgd_ops): the stale gradient is corrected with the
        Taylor term  g_dc = g + lambda * g * g * (param - param_bak)  and
        param_bak snapshots the post-update param for the next round.
        Appends the correction ops, the rewired optimizer op, and the
        snapshot to `dst`."""
        param = opt_op.input("Param")[0]
        grad = opt_op.input("Grad")[0]
        pd = dst.vars[param].desc if hasattr(dst.vars[param], "desc") else dst.vars[param]
        shape, dtype = list(pd.shape), pd.dtype
        bak = param + "@BAK"
        if not dst.desc.has_var(bak):
            dst.create_var(name=bak, shape=shape, dtype=dtype,
                           persistable=True)

        def tmp(suffix):
            n = f"{grad}@DC.{suffix}"
            if not dst.desc.has_var(n):
                dst.create_var(name=n, shape=shape, dtype=dtype)
            return n

        gg = tmp("gg")
        diff = tmp("diff")
        corr = tmp("corr")
        scaled = tmp("scaled")
        g_dc = f"{grad}@DC"
        if not dst.desc.has_var(g_dc):
            dst.create_var(name=g_dc, shape=shape, dtype=dtype)
        from ..core.proto import OpDesc

        ops = [
            OpDesc(type="elementwise_mul",
                   inputs={"X": [grad], "Y": [grad]}, outputs={"Out": [gg]},
                   attrs={"axis": -1}),
            OpDesc(type="elementwise_sub",
                   inputs={"X": [param], "Y": [bak]},
                   outputs={"Out": [diff]}, attrs={"axis": -1}),
            OpDesc(type="elementwise_mul",
                   inputs={"X": [gg], "Y": [diff]},
                   outputs={"Out": [corr]}, attrs={"axis": -1}),
            OpDesc(type="scale", inputs={"X": [corr]},
                   outputs={"Out": [scaled]},
                   attrs={"scale": float(self.config.dc_asgd_lambda)}),
            OpDesc(type="elementwise_add",
                   inputs={"X": [grad], "Y": [scaled]},
                   outputs={"Out": [g_dc]}, attrs={"axis": -1}),
        ]
        dst.desc.ops.extend(ops)
        # the optimizer consumes the compensated gradient
        opt_op.inputs["Grad"] = [g_dc]
        dst.desc.ops.append(opt_op)
        # snapshot the updated param for the next delay window
        dst.desc.ops.append(
            OpDesc(type="assign", inputs={"X": [param]},
                   outputs={"Out": [bak]})
        )


    def get_pserver_programs(self, endpoint: str):
        prog = self.get_pserver_program(endpoint)
        return prog, self.get_startup_program(endpoint, prog)

    def get_startup_program(
        self, endpoint: str = None, pserver_program: Program = None,
        startup_program: Program = None,
    ) -> Program:
        """Startup for the vars a pserver program touches."""
        from ..core.framework import default_startup_program

        base = startup_program or default_startup_program()
        if pserver_program is None:
            return base
        needed = set()
        for op in pserver_program.desc.block(0).ops:
            needed.update(op.input_arg_names())
            needed.update(op.output_arg_names())
        prog = Program()
        dst = prog.global_block()
        for op in base.desc.block(0).ops:
            outs = set(op.output_arg_names())
            if outs & needed:
                import copy

                dst.desc.ops.append(copy.deepcopy(op))
                for n in op.output_arg_names():
                    if base.global_block().desc.has_var(n) and not dst.desc.has_var(n):
                        vd = base.global_block().vars[n]
                        dst.create_var(
                            name=n, shape=list(vd.shape), dtype=vd.dtype,
                            persistable=True,
                        )
        # DC-ASGD baks start from the param's initial value (reference
        # initializes param_bak alongside the param on the pserver)
        from ..core.proto import OpDesc

        for n in sorted(needed):
            if n.endswith("@BAK") and not dst.desc.has_var(n):
                param = n[: -len("@BAK")]
                if dst.desc.has_var(param):
                    vd = dst.desc.vars[param]
                    dst.create_var(name=n, shape=list(vd.shape),
                                   dtype=vd.dtype, persistable=True)
                    dst.desc.ops.append(
                        OpDesc(type="assign", inputs={"X": [param]},
                               outputs={"Out": [n]})
                    )
        return prog
