"""Inference-program rewrites (reference: python/paddle/fluid/transpiler/
inference_transpiler.py:24 InferenceTranspiler).

The reference folds a trained batch_norm into the preceding conv2d by
computing folded filter/bias host-side and writing them into NEW
``<name>_fuse_bn`` variables, renaming the op inputs (``_fuse_batch_norm``
inference_transpiler.py:300, ``_fuse_param`` :416) — the original
parameters survive untouched, so transpiling an inference clone against
the shared global scope while the training program is live is safe.  It
then flips every op into test mode (``_is_test_pass`` :78).  The MKLDNN-only passes (conv+relu, conv+eltwise,
bn+relu fusion, :108-:298) have no equivalent here: XLA fuses elementwise
epilogues into the conv at compile time, so those rewrites would change
nothing on TPU.

The batch-norm fold is NOT subsumed by XLA, though: Scale/Bias/Mean/
Variance are runtime inputs (parameters), so the compiler cannot constant-
fold them into the filter.  Folding host-side removes four [C] parameter
reads and the normalize chain from every inference step and — more
importantly for parity — produces the same "conv + elementwise_add only"
program shape the reference's deployment tooling expects.

Pattern handled (same contract as the reference):

  conv2d -> batch_norm              (conv without bias)
  conv2d -> elementwise_add -> batch_norm   (conv with bias)

with the batch_norm in test mode (global Mean/Variance).  Matching is by
def-use (the batch_norm must be the *only* consumer of the conv output),
which is stricter than the reference's adjacent-op scan and therefore safe
on branchy programs (ResNet residuals keep their unfused adds).
"""

from __future__ import annotations

import numpy as np

__all__ = ["InferenceTranspiler"]

# ops whose lowering changes behavior between train and test mode; the
# reference sets is_test on every op that *declares* the attr (it reads the
# registered proto); our descs only hold explicitly-set attrs, so the op
# set is spelled out.
_IS_TEST_OPS = ("batch_norm", "fused_bn_add_act", "dropout", "lrn",
                "fake_quantize_abs_max", "fake_quantize_range_abs_max")


def _is_foldable_bn(op):
    """batch_norm, or the fused twin WITHOUT a residual input (the Z-free
    fused_bn_add_act the conv builders emit for plain conv->BN(+act)
    stacks is the same conv+BN shape the fold handles; its activation is
    re-emitted as a standalone relu after the folded add)."""
    if op.type == "batch_norm":
        return True
    return (op.type == "fused_bn_add_act"
            and not (op.desc.inputs.get("Z") or []))


class InferenceTranspiler:
    """reference: inference_transpiler.py InferenceTranspiler."""

    def transpile(self, program, place, scope=None, protected_vars=None):
        """`protected_vars`: extra variable names whose VALUES must survive
        unchanged (e.g. intermediate fetch targets of a multi-output
        inference program).  Folding rewrites the conv filter, so a conv
        output that is itself fetched would silently return BN-scaled
        activations; the desc records consumers but not run-time fetch
        lists, hence the explicit hook (the reference has the same blind
        spot — its adjacency scan folds regardless of fetch targets)."""
        from paddle_tpu.core.framework import Program
        from paddle_tpu.core.scope import global_scope

        if not isinstance(program, Program):
            raise TypeError("program should be a Program")
        if scope is None:
            scope = global_scope()
        self._fuse_batch_norm(program, scope,
                              frozenset(protected_vars or ()))
        self._is_test_pass(program)
        program.desc.bump()

    # -- passes --------------------------------------------------------------
    def _is_test_pass(self, program):
        """reference: inference_transpiler.py:78."""
        for block in program.blocks:
            for op in block.ops:
                if op.type in _IS_TEST_OPS:
                    op.desc.attrs["is_test"] = True

    def _fuse_batch_norm(self, program, scope, protected):
        """reference: inference_transpiler.py:300 (math documented there:
        W' = W * scale/std;  b' = (b - mean) * scale/std + bias)."""
        block = program.block(0)

        def all_consumers(name):
            """(block0_idx, op) pairs for block-0 consumers; ops in ANY
            other block also count (sub-block ops read parent vars through
            the scope chain) but are returned with idx None so a sub-block
            reader disqualifies the fold."""
            out = [
                (j, o) for j, o in enumerate(block.ops)
                if name in o.desc.input_arg_names()
            ]
            for blk in program.blocks:
                if blk is block:
                    continue
                for o in blk.ops:
                    if name in o.desc.input_arg_names():
                        out.append((None, o))
            return out
        # single forward pass: a fold rewrites ops at indices > i only (the
        # bn is replaced in place by / merged into an elementwise_add), so
        # the scan resumes instead of restarting — O(n^2) worst case on the
        # consumer lookups, not O(n^3)
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            i += 1
            if op.type != "conv2d":
                continue
            conv_out = op.output("Output")[0]
            if conv_out in protected:
                continue
            consumers = all_consumers(conv_out)
            if len(consumers) != 1 or consumers[0][0] is None:
                continue
            j, nxt = consumers[0]
            if _is_foldable_bn(nxt) and nxt.input("X") == [conv_out]:
                self._fold(block, scope, op, bn_idx=j, bias_op=None)
                continue
            if nxt.type == "elementwise_add" and nxt.attr("axis", -1) == 1:
                bias_name = nxt.input("Y")[0]
                if not self._is_channel_bias(block, bias_name):
                    continue
                add_out = nxt.output("Out")[0]
                if add_out in protected:
                    continue
                nxt2 = all_consumers(add_out)
                if len(nxt2) == 1 and nxt2[0][0] is not None \
                        and _is_foldable_bn(nxt2[0][1]) \
                        and nxt2[0][1].input("X") == [add_out]:
                    self._fold(block, scope, op, bn_idx=nxt2[0][0],
                               bias_op=nxt)
        self._remove_unused_vars(program)

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def _is_channel_bias(block, name):
        if not block.desc.has_var(name):
            return False
        shape = block.desc.vars[name].shape
        return shape is not None and len(shape) == 1

    @staticmethod
    def _scope_array(scope, name):
        val = scope.find_var(name)
        if val is None:
            raise ValueError(
                f"InferenceTranspiler: variable '{name}' has no value in the "
                f"scope — run the startup program (and load params) first")
        return np.asarray(val)

    @staticmethod
    def _fused_copy(block, scope, src_name, value, shape):
        """Write `value` into a NEW persistable var `<src>_fuse_bn` (unique-
        suffixed if a previous fold already claimed the name, e.g. two convs
        sharing one filter) and return its name.  The reference does exactly
        this in _fuse_param (inference_transpiler.py:435 new_param_name =
        old_param_name + '_fuse_bn'): the ORIGINAL parameter survives
        untouched, so transpiling an inference clone against the shared
        global scope while the training program is live cannot corrupt
        training, and save_persistables on the training program still writes
        the true weights."""
        import dataclasses

        name = src_name + "_fuse_bn"
        n = 2
        while block.desc.has_var(name) or scope.find_var(name) is not None:
            name = f"{src_name}_fuse_bn_{n}"
            n += 1
        src_desc = block.desc.vars.get(src_name)
        if src_desc is None:
            # a runnable conv/add always carries its param descs; a missing
            # one is desc corruption — fail loudly rather than fabricate a
            # default-FP32 desc that would disagree with the scope value
            raise ValueError(
                f"InferenceTranspiler: parameter '{src_name}' has no "
                f"VarDesc in the program — cannot fold")
        desc = dataclasses.replace(
            src_desc, name=name, shape=list(shape), persistable=True)
        block.desc.vars[name] = desc
        scope.set_var(name, value)
        return name

    @staticmethod
    def _emit_act(block, idx, act, dst_name):
        """Re-emit a fused op's activation as a standalone relu at `idx`
        writing `dst_name` (the fold replaces fused_bn_add_act(act=relu)
        with add -> relu).  Returns the new pre-activation var name the
        producing add should write instead, or None when there is no
        activation."""
        import dataclasses

        if not act:
            return None
        if act != "relu":
            raise ValueError(
                f"InferenceTranspiler: cannot re-emit activation {act!r}")
        tmp = dst_name + "_prerelu"
        n = 2
        while block.desc.has_var(tmp):
            tmp = f"{dst_name}_prerelu_{n}"
            n += 1
        block.desc.vars[tmp] = dataclasses.replace(
            block.desc.vars[dst_name], name=tmp, persistable=False)
        block._insert_op(idx, type="relu", inputs={"X": [tmp]},
                         outputs={"Out": [dst_name]}, attrs={})
        return tmp

    def _fold(self, block, scope, conv_op, bn_idx, bias_op):
        bn = block.ops[bn_idx]
        act = (bn.attr("act", None)
               if bn.type == "fused_bn_add_act" else None)
        w_name = conv_op.input("Filter")[0]
        w = self._scope_array(scope, w_name)
        scale = self._scope_array(scope, bn.input("Scale")[0]).astype(np.float64)
        beta_raw = self._scope_array(scope, bn.input("Bias")[0])
        beta = beta_raw.astype(np.float64)
        mean = self._scope_array(scope, bn.input("Mean")[0]).astype(np.float64)
        var = self._scope_array(scope, bn.input("Variance")[0]).astype(np.float64)
        eps = bn.attr("epsilon", 1e-5)

        # filter is [Cout, Cin/groups, kh, kw]: channel axis 0 for any groups
        alpha = scale / np.sqrt(var + eps)
        w_new = (w.astype(np.float64) * alpha.reshape((-1,) + (1,) * (w.ndim - 1)))
        conv_op.desc.inputs["Filter"] = [self._fused_copy(
            block, scope, w_name, w_new.astype(w.dtype), w.shape)]

        bn_y = bn.output("Y")[0]
        if bias_op is not None:
            old_bias = self._scope_array(scope, bias_op.input("Y")[0])
            b_new = (old_bias.astype(np.float64) - mean) * alpha + beta
            bias_op.desc.inputs["Y"] = [self._fused_copy(
                block, scope, bias_op.input("Y")[0],
                b_new.astype(old_bias.dtype), old_bias.shape)]
            block._remove_op(bn_idx)
            # redirect the existing add's output to the bn output (or,
            # for a fused op with an activation, through a re-emitted act)
            pre = self._emit_act(block, bn_idx, act, bn_y)
            bias_op.desc.outputs["Out"] = [pre or bn_y]
        else:
            b_new = (0.0 - mean) * alpha + beta
            bias_name = self._fused_copy(
                block, scope, bn.input("Bias")[0],
                b_new.astype(beta_raw.dtype), beta.shape)
            conv_out = conv_op.output("Output")[0]
            block._remove_op(bn_idx)
            pre = self._emit_act(block, bn_idx, act, bn_y)
            block._insert_op(
                bn_idx, type="elementwise_add",
                inputs={"X": [conv_out], "Y": [bias_name]},
                outputs={"Out": [pre or bn_y]},
                attrs={"axis": 1})

    @staticmethod
    def _remove_unused_vars(program):
        """reference: inference_transpiler.py _remove_unused_var — drop desc
        vars (the stale bn Scale/Mean/Variance and intermediates) referenced
        by no op, so save_persistables after the fold skips them.  The used
        set spans EVERY block: a block-0 var consumed only inside a while/
        cond sub-block must survive (sub-block ops resolve inputs through
        the parent chain)."""
        used = set()
        for blk in program.blocks:
            for op in blk.ops:
                used.update(op.desc.input_arg_names())
                used.update(op.desc.output_arg_names())
        block = program.block(0)
        for name in list(block.desc.vars):
            if name not in used:
                del block.desc.vars[name]
                block.vars.pop(name, None)
