"""Parameter placement dispatchers
(reference: python/paddle/fluid/transpiler/ps_dispatcher.py)."""

from __future__ import annotations

__all__ = ["PSDispatcher", "HashName", "RoundRobin"]


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class HashName(PSDispatcher):
    """Place by name hash (reference: ps_dispatcher.py HashName).  Uses
    crc32, not builtin hash(): placement must agree across processes
    (PYTHONHASHSEED randomizes str hash per process)."""

    def _hash_block(self, block_str, total):
        import zlib

        return zlib.crc32(str(block_str).encode()) % total

    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            name = getattr(var, "name", var)
            if callable(name):
                name = name()
            eplist.append(self._eps[self._hash_block(name, len(self._eps))])
        return eplist


class RoundRobin(PSDispatcher):
    """reference: ps_dispatcher.py RoundRobin."""

    def dispatch(self, varlist):
        eplist = []
        for _ in varlist:
            eplist.append(self._eps[self._step])
            self._step = (self._step + 1) % len(self._eps)
        return eplist
