"""Admission router: N Engine replicas behind one submit/Future API.

Tensor parallelism (sharded.py) makes one model instance faster; this
makes MANY instances one service.  The Router owns a set of named
Engine replicas and routes each submit() to the healthiest one:

- **Health-aware dispatch**: candidates are ranked by
  ``engine.health()`` — SERVING replicas first, then (optionally)
  DEGRADED ones, least queue depth within a rank; BROKEN and DRAINING
  replicas are skipped outright.  A submit that still bounces
  (queue-full race, breaker opening between the health poll and the
  enqueue) falls through to the next candidate, so one sick replica
  costs a skip counter, never a request.
- **Lease-based membership**: an optional :class:`ReplicaDirectory`
  rides the elastic master's heartbeat/lease seam (elastic/master.py
  ``heartbeat``/``dead_workers`` — in-process or over the RPC plane's
  :class:`~paddle_tpu.elastic.rpc.RemoteMaster`): each replica process
  heartbeats ``replica/<name>``; a replica whose lease went silent past
  ``max_silence_s`` stops receiving traffic before its first failed
  dispatch.
- **Drain-based handoff**: ``drain_replica(name)`` atomically stops
  routing to a replica, then triggers the engine's own drain — queued
  and in-flight requests complete on the draining replica while new
  traffic lands on the survivors.  Zero requests are lost or duplicated
  in the handoff (tests/test_distributed_serving.py pins this).

Observability follows the serving pattern (callers gate on
FLAGS_observability): routing decisions land on the
``paddle_tpu_serving_router_decisions{decision=,replica=}`` counter,
per-replica health on ``paddle_tpu_serving_replica_health_state
{replica=}``, and every engine flight-recorder / request-trace event
carries the ``replica`` field once an engine joins a router — so after
``MetricsRegistry.aggregate_dir()`` merges per-process dumps, a BROKEN
replica's black box and kept traces are still attributable.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ... import flags as _flags
from .. import metrics as _smetrics
from ..engine import (
    Engine,
    EngineClosedError,
    EngineUnhealthyError,
    QueueFullError,
)

__all__ = ["ReplicaDirectory", "ReplicaUnavailableError", "Router"]


class ReplicaUnavailableError(RuntimeError):
    """No replica could admit the request: every member was BROKEN,
    DRAINING, lease-expired, or rejected the submit.  Carries the
    per-replica reasons on ``.skipped``."""

    def __init__(self, skipped: Dict[str, str]):
        self.skipped = dict(skipped)
        detail = ", ".join(f"{n}: {r}" for n, r in sorted(skipped.items()))
        super().__init__(
            f"no replica available ({detail or 'router has no replicas'})")


class ReplicaDirectory:
    """Replica membership on the elastic master's heartbeat/lease seam.

    ``master`` is anything speaking the MasterService liveness protocol
    — the in-process :class:`~paddle_tpu.elastic.master.MasterService`
    or a :class:`~paddle_tpu.elastic.rpc.RemoteMaster` over the TCP
    plane (cross-process replicas heartbeat the same master the elastic
    trainers use).  A replica registers once, beats periodically, and
    is considered lease-expired after ``max_silence_s`` of silence —
    the router stops routing to it without waiting for a failed
    dispatch."""

    _PREFIX = "replica/"

    def __init__(self, master, max_silence_s: float = 2.0):
        self.master = master
        self.max_silence_s = float(max_silence_s)

    def register(self, name: str, payload: Optional[dict] = None) -> None:
        self.beat(name, payload)

    def beat(self, name: str, payload: Optional[dict] = None) -> None:
        """One lease renewal; ``payload`` piggybacks the replica's
        status dict (queue depth, shed counts, health state) — the
        fleet controller's autoscaling signals ride the liveness RPC."""
        if payload is None:
            self.master.heartbeat(self._PREFIX + name)
        else:
            self.master.heartbeat(self._PREFIX + name, payload)

    def deregister(self, name: str) -> None:
        """Forget a deliberately-removed replica's lease.  Without
        this, a drained-and-removed replica stays in the master's
        heartbeat registry forever and reports lease-expired in every
        later expired() poll (the ghost-lease bug)."""
        forget = getattr(self.master, "forget_worker", None)
        if forget is not None:
            forget(self._PREFIX + name)

    def status(self) -> Dict[str, dict]:
        """Per-replica beat age + latest payload (worker_status through
        the replica/ prefix) — {} when the master predates payloads."""
        ws = getattr(self.master, "worker_status", None)
        if ws is None:
            return {}
        return {w[len(self._PREFIX):]: st for w, st in ws().items()
                if w.startswith(self._PREFIX)}

    def expired(self) -> List[str]:
        """Replica names whose lease lapsed (never-registered names are
        not listed — an unknown replica is the router's call)."""
        dead = self.master.dead_workers(self.max_silence_s)
        return [w[len(self._PREFIX):] for w in dead
                if w.startswith(self._PREFIX)]


class _Replica:
    __slots__ = ("name", "engine", "routing", "routed", "skipped",
                 "health_at", "health")

    def __init__(self, name: str, engine: Engine):
        self.name = name
        self.engine = engine
        self.routing = True   # False once drain_replica claimed it
        self.routed = 0
        self.skipped = 0
        self.health_at = -1.0   # perf_counter of the cached snapshot
        self.health: Optional[Dict[str, Any]] = None


# health states that may receive traffic, in preference order
_RANK = {"SERVING": 0, "DEGRADED": 1}


class Router:
    """Front N Engine replicas behind one thread-safe submit()."""

    def __init__(self, replicas: Optional[Sequence[Engine]] = None,
                 directory: Optional[ReplicaDirectory] = None,
                 allow_degraded: bool = True, name: str = "router",
                 health_cache_s: float = 0.05):
        self.name = name
        self.directory = directory
        self.allow_degraded = bool(allow_degraded)
        # routing reads health/lease state through a short-TTL cache so
        # per-submit cost does not scale with fleet size (engine.health()
        # takes engine locks + writes gauges; directory.expired() can be
        # an RPC).  0 disables — every submit polls fresh.  Stale reads
        # are bounded and safe: a submit that lands on a replica the
        # cache thought healthy falls over on the raced rejection.
        self.health_cache_s = float(health_cache_s)
        self._lock = threading.Lock()
        self._replicas: Dict[str, _Replica] = {}
        self._handoffs = 0
        self._expired_at = -1.0
        self._expired_cache: frozenset = frozenset()
        for eng in replicas or ():
            self.add_replica(eng)

    # -- membership -----------------------------------------------------

    def add_replica(self, engine: Engine,
                    name: Optional[str] = None) -> str:
        """Join a replica (default name: the engine's own).  The engine
        is labeled so its flight-recorder events, request traces, and
        health gauges carry ``replica=<name>`` from here on."""
        name = name or engine.name
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already joined")
            self._replicas[name] = _Replica(name, engine)
        engine.replica = name
        if self.directory is not None:
            self.directory.register(name)
        return name

    def remove_replica(self, name: str) -> Engine:
        """Forget a replica (it should be drained first — the router
        stops routing but does NOT close the engine).  Its lease is
        deregistered from the directory too: a removed replica must
        not haunt every later expired() poll as a ghost lease."""
        with self._lock:
            rep = self._replicas.pop(name)
        if self.directory is not None:
            self.directory.deregister(name)
        return rep.engine

    def replica_names(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    def engine(self, name: str) -> Engine:
        with self._lock:
            return self._replicas[name].engine

    # -- routing --------------------------------------------------------

    def _note_skip(self, rep: _Replica, reason: str,
                   skipped: Dict[str, str], obs_on: bool) -> None:
        skipped.setdefault(rep.name, reason)
        with self._lock:
            rep.skipped += 1
        if obs_on:
            _smetrics.record_router_decision("skipped_unhealthy", rep.name)

    def _expired(self) -> frozenset:
        """Lease-expired replica names, through the routing cache."""
        if self.directory is None:
            return frozenset()
        now = time.perf_counter()
        with self._lock:
            if now - self._expired_at <= self.health_cache_s \
                    and self._expired_at >= 0:
                return self._expired_cache
        expired = frozenset(self.directory.expired())  # outside the lock
        with self._lock:
            self._expired_at = time.perf_counter()
            self._expired_cache = expired
        return expired

    def _health_of(self, rep: _Replica) -> Dict[str, Any]:
        """rep.engine.health(), through the routing cache."""
        now = time.perf_counter()
        with self._lock:
            if rep.health is not None \
                    and now - rep.health_at <= self.health_cache_s:
                return rep.health
        h = rep.engine.health()  # outside the lock: takes engine locks
        with self._lock:
            rep.health_at = time.perf_counter()
            rep.health = h
        return h

    def _candidates(self, skipped: Dict[str, str],
                    obs_on: bool) -> List[Tuple[int, int, _Replica]]:
        """(rank, queue_depth, replica) for every routable replica;
        unroutable ones land in `skipped` with their reason AND on the
        skip counters — a request served elsewhere still passed this
        replica over, which is the signal an operator alerts on."""
        with self._lock:
            reps = list(self._replicas.values())
        expired = self._expired()
        out: List[Tuple[int, int, _Replica]] = []
        for rep in reps:
            if not rep.routing:
                skipped.setdefault(rep.name, "draining")
                continue  # a claimed handoff is expected, not a skip
            if rep.name in expired:
                self._note_skip(rep, "lease_expired", skipped, obs_on)
                continue
            h = self._health_of(rep)
            rank = _RANK.get(h["state"])
            if rank is None or (rank and not self.allow_degraded):
                self._note_skip(rep, h["state"].lower(), skipped, obs_on)
                continue
            out.append((rank, h["queue_depth"], rep))
        out.sort(key=lambda t: (t[0], t[1], t[2].name))
        return out

    def submit(self, feed: Dict[str, Any],
               timeout: Optional[float] = None,
               call_kwargs: Optional[Dict[str, Any]] = None) -> Future:
        """Route one request to the healthiest replica; the returned
        Future carries ``.replica`` (the serving replica's name) next to
        the engine's usual ``.trace_id``.  Raises
        ReplicaUnavailableError when nothing can admit."""
        obs_on = _flags._VALUES["FLAGS_observability"]
        skipped: Dict[str, str] = {}
        for _, _, rep in self._candidates(skipped, obs_on):
            try:
                fut = rep.engine.submit(feed, timeout=timeout,
                                        call_kwargs=call_kwargs)
            except (QueueFullError, EngineUnhealthyError,
                    EngineClosedError) as e:
                # the health poll raced the rejection — skip and try the
                # next candidate instead of failing the request
                self._note_skip(rep, type(e).__name__, skipped, obs_on)
                continue
            fut.replica = rep.name
            with self._lock:
                rep.routed += 1
                if rep.health is not None:
                    # keep least-queue ranking live INSIDE the cache
                    # TTL: the routed request deepens this replica's
                    # cached queue (copy — the snapshot was handed out)
                    rep.health = dict(
                        rep.health,
                        queue_depth=rep.health["queue_depth"] + 1)
            if obs_on:
                _smetrics.record_router_decision("routed", rep.name)
            return fut
        raise ReplicaUnavailableError(skipped)

    def infer(self, feed: Dict[str, Any],
              timeout: Optional[float] = None,
              call_kwargs: Optional[Dict[str, Any]] = None):
        return self.submit(feed, timeout=timeout,
                           call_kwargs=call_kwargs).result()

    # -- drain-based handoff ---------------------------------------------

    def drain_replica(self, name: str,
                      timeout: Optional[float] = None) -> bool:
        """Hand a replica's traffic off to the survivors: atomically
        stop routing to it, then drain its engine (queued + in-flight
        requests complete there).  Returns True when fully drained;
        False leaves the replica claimed but still finishing (poll
        again with another drain_replica call).  The replica stays a
        member until remove_replica — its health remains visible while
        it finishes."""
        with self._lock:
            rep = self._replicas[name]
            first = rep.routing
            rep.routing = False
            if first:
                self._handoffs += 1
        if first and _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_router_decision("handoff", name)
        return rep.engine.drain(timeout)

    # -- introspection ---------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """Per-replica engine.health() snapshots plus routing state —
        and, with observability on, the per-replica gauges the merged
        (aggregate_dir) view keys on."""
        obs_on = _flags._VALUES["FLAGS_observability"]
        with self._lock:
            reps = list(self._replicas.values())
        expired = set(self.directory.expired()) if self.directory else ()
        out: Dict[str, Any] = {"replicas": {}, "handoffs": self._handoffs}
        for rep in reps:
            h = rep.engine.health()
            h["routing"] = rep.routing and rep.name not in expired
            h["lease_expired"] = rep.name in expired
            out["replicas"][rep.name] = h
            if obs_on:
                _smetrics.record_replica_health(
                    rep.name, h["state"], h["queue_depth"])
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replicas": {
                    r.name: {"routed": r.routed, "skipped": r.skipped,
                             "routing": r.routing}
                    for r in self._replicas.values()
                },
                "routed": sum(r.routed for r in self._replicas.values()),
                "skipped": sum(r.skipped for r in self._replicas.values()),
                "handoffs": self._handoffs,
            }

    # -- lifecycle -------------------------------------------------------

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain and close every replica engine."""
        with self._lock:
            reps = list(self._replicas.values())
            for rep in reps:
                rep.routing = False
        for rep in reps:
            rep.engine.close(timeout)

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
