"""Tensor-parallel decode: the transformer decode step under shard_map.

The single-device decode step (serving/generate.py) tops out at one
chip's HBM bandwidth and one chip's page pool.  This module shards the
SAME model across a mesh axis (``tp``) the classic Megatron way, mapped
onto jax:

- **Column-parallel QKV**: ``wq [d, d]`` / ``wk/wv [d, H_kv*Dh]``
  split on the OUTPUT dim, so shard ``i`` computes query heads
  ``[i*H/n, (i+1)*H/n)`` and KV heads ``[i*H_kv/n, (i+1)*H_kv/n)`` —
  no collective, each shard's Q/K/V are exactly its own heads', and
  under GQA (``cfg.n_kv_head < n_head``) the query-group alignment is
  automatic: H/n local query heads are exactly (H/H_kv) groups over
  H_kv/n local KV heads, so the grouped paged kernel runs per-shard
  unchanged.  Both head counts must divide by the mesh axis.
- **Local paged KV**: :class:`ShardedKVCachePool` shards the pool
  arrays on the KV-HEAD axis (``[L, H_kv/n, P, page_size, D]`` per
  device — the GQA shrink compounds with the mesh split: each device
  holds H_kv/(H*n) of a full-head single-device pool).  Page tables
  and the free list stay host-side and global (one admission decision
  covers all shards); the K/V write and the paged-attention page walk
  are per-shard local — the pallas kernel runs unchanged, its grid was
  already per-(KV-)head.  int8 pages are NOT yet supported here: the
  sharded step writes K/V inside the shard_map body, where the
  host-side amax scale bookkeeping cannot reach (a device-side scale
  table is the follow-up); the constructor rejects ``dtype="int8"``
  loudly rather than storing garbage.
- **Row-parallel joins**: ``wo [d, d]`` splits on the INPUT dim; each
  shard contributes ``attn_local @ wo_local`` and one ``psum`` over ICI
  joins the partials (same for the MLP's ``w1``/``w2`` pair).  ``psum``
  rather than ``psum_scatter``: the joined activation immediately feeds
  the next layer's column-parallel matmuls on EVERY shard, so a
  scattered result would force an all-gather right back — the linter's
  ``collective-placement`` detector exists to catch that shape.
- **Replicated everything else**: embeddings, positions, layernorm
  scales, and the logits matmul (V is small next to the KV stream; the
  returned ``[B, V]`` logits are bit-identical on every shard, which is
  also shard_map's replication check on the output spec).

Speculation (ISSUE 16): ``verify_step_fn`` compiles the same sharded
model for Sq = 1+d ragged query rows (``q_lengths`` is a first-class
operand of the paged kernel), and ``ShardedDecodeProgram.verify_step``
drives ``generate.verify_step``'s exact host protocol — so a
program-driven ``ContinuousBatchingLoop(speculate=d)`` commits up to
d+1 tokens per mesh step instead of degrading to d=0.

Chip-less verification: an N-device CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``) runs the real
SPMD program; tests/test_distributed_serving.py holds continuous-
batching decode over it token-identical to the single-device oracle.
The AOT v5e tier (core/aot_tpu.py) compiles the same program for a
2x2 slice and banks its per-chip bytes/step (analysis zoo entry
``sharded_decode``).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...kernels.flash_attention import flash_attention
from ...kernels.paged_attention import (
    paged_decode_attention,
    repeat_kv,
    resolve_paged_impl,
)
from ..generate import DecodeConfig, _layernorm
from ..kvcache import KVCachePool

__all__ = [
    "KV_POOL_MAJOR_TO_MINOR",
    "ShardedDecodeProgram",
    "ShardedKVCachePool",
    "decode_step_fn",
    "host_mesh_devices",
    "kv_pool_layout",
    "param_partition_specs",
    "param_shape_dtypes",
    "prefill_step_fn",
    "verify_step_fn",
]

AXIS_TP = "tp"


def host_mesh_devices(n: int):
    """The first `n` local devices for a chip-less tensor-parallel mesh.
    Raises with the XLA_FLAGS recipe when the initialized platform has
    fewer — the flag only works BEFORE the backend initializes, so this
    cannot respawn, it can only tell the caller how to."""
    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(
            f"need {n} devices for the mesh but the initialized platform "
            f"has {len(devs)}; set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n} before jax "
            "initializes (tests: the conftest host_devices fixture)")
    return devs[:n]


# ---------------------------------------------------------------------------
# parameter sharding vocabulary


def param_partition_specs(cfg: DecodeConfig, axis: str = AXIS_TP) -> Dict:
    """PartitionSpec pytree matching init_decode_params' structure:
    QKV column-parallel (output dim -> heads), wo/w2 row-parallel
    (input dim), w1/b1 column-parallel, everything else replicated."""
    layer = {
        "wq": P(None, axis), "wk": P(None, axis), "wv": P(None, axis),
        "wo": P(axis, None),
        "ln1_g": P(), "ln1_b": P(),
        "w1": P(None, axis), "b1": P(axis),
        "w2": P(axis, None), "b2": P(),
        "ln2_g": P(), "ln2_b": P(),
    }
    return {
        "embed": P(),
        "pos": P(),
        "layers": [dict(layer) for _ in range(cfg.n_layer)],
    }


def param_shape_dtypes(cfg: DecodeConfig) -> Dict:
    """ShapeDtypeStruct pytree of init_decode_params(cfg) — the AOT
    capture path's abstract arguments (no host weights materialized)."""
    d, f = cfg.d_model, cfg.d_inner
    d_kv = cfg.num_kv_heads * cfg.head_dim
    sds = lambda *shape: jax.ShapeDtypeStruct(shape, jnp.float32)
    layer = {
        "wq": sds(d, d), "wk": sds(d, d_kv), "wv": sds(d, d_kv),
        "wo": sds(d, d),
        "ln1_g": sds(d), "ln1_b": sds(d),
        "w1": sds(d, f), "b1": sds(f), "w2": sds(f, d), "b2": sds(d),
        "ln2_g": sds(d), "ln2_b": sds(d),
    }
    return {
        "embed": sds(cfg.vocab_size, d),
        "pos": sds(cfg.max_length, d),
        "layers": [dict(layer) for _ in range(cfg.n_layer)],
    }


def _kv_spec(axis: str = AXIS_TP) -> P:
    """Pool arrays [L, H, P, page_size, D]: heads sharded, rest local."""
    return P(None, axis, None, None, None)


# The pool-shard LAYOUT contract (the ROADMAP "layout tax" fix, ISSUE
# 14).  The SPMD step scatter-updates the pool in place (one [H, D] row
# per appended token), so XLA prefers D, then H, innermost — physical
# [L, P, ps, H, D], i.e. major_to_minor (0, 2, 3, 1, 4) on the logical
# [L, H, P, ps, D] arrays — and the paged kernel's pool_layout="xla"
# arm consumes exactly that view.  Requesting it at the program
# boundary (entry params AND outputs — the donated pool aliases, so
# they must agree) erases every relayout copy: the banked
# sharded_decode zoo entry pins relayout-copy-pair at 0 and the
# bytes/step win.  Verified against DeviceLocalLayout.AUTO: XLA picks
# this same layout when left free.
KV_POOL_MAJOR_TO_MINOR = (0, 2, 3, 1, 4)


def kv_pool_layout(sharding: NamedSharding):
    """The XLA-preferred pool-shard layout wrapped over `sharding` — the
    in/out sharding entry the kv pool args carry on TPU compiles (the
    AOT zoo capture and the real TPU program use the same one)."""
    from jax.experimental.layout import DeviceLocalLayout, Layout

    return Layout(
        DeviceLocalLayout(major_to_minor=KV_POOL_MAJOR_TO_MINOR),
        sharding)


# ---------------------------------------------------------------------------
# the SPMD step bodies (pure; every array a shard_map gives them is the
# LOCAL shard — H_local = n_head / n_shards heads per device)


def _local_heads(cfg: DecodeConfig, n_shards: int) -> Tuple[int, int]:
    """(query, KV) heads per shard — BOTH head counts must divide by
    the mesh axis.  Under GQA the local query heads are then exactly
    H/H_kv whole groups over the local KV heads (H/n = (H/H_kv) *
    H_kv/n), so shard-local grouping matches the global mapping."""
    if cfg.n_head % n_shards:
        raise ValueError(
            f"n_head={cfg.n_head} must divide by n_shards={n_shards}")
    if cfg.num_kv_heads % n_shards:
        raise ValueError(
            f"n_kv_head={cfg.num_kv_heads} must divide by n_shards="
            f"{n_shards} — the pool shards over the KV-head axis")
    return cfg.n_head // n_shards, cfg.num_kv_heads // n_shards


def decode_step_fn(cfg: DecodeConfig, n_shards: int, axis: str = AXIS_TP,
                   impl: str = "reference", force: str = "auto"):
    """Build the shard_map body for one continuous-batching decode step.

    fn(params, tokens [B], positions [B], pages [B], slots [B],
       tables [B, maxp], lengths [B], k_pages, v_pages)
      -> (logits [B, V] replicated, new k_pages, new v_pages)

    The K/V append is the write_kv contract on the LOCAL KV-head shard;
    the paged attention walks the (global, replicated) page tables over
    the LOCAL pool arrays — every byte the hot path touches lives on
    the device that computes with it."""
    H_local, Hkv_local = _local_heads(cfg, n_shards)
    d, Dh = cfg.d_model, cfg.head_dim

    def step(params, tokens, positions, pages, slots, tables, lengths,
             k_pages, v_pages):
        B = tokens.shape[0]
        h = jnp.asarray(params["embed"])[tokens] * np.sqrt(d) \
            + jnp.asarray(params["pos"])[positions]
        for li, lp in enumerate(params["layers"]):
            q = (h @ lp["wq"]).reshape(B, H_local, Dh)
            k = (h @ lp["wk"]).reshape(B, Hkv_local, Dh)
            v = (h @ lp["wv"]).reshape(B, Hkv_local, Dh)
            k_pages = k_pages.at[li, :, pages, slots].set(k)
            v_pages = v_pages.at[li, :, pages, slots].set(v)
            attn = paged_decode_attention(
                q[:, :, None, :], k_pages[li], v_pages[li],
                tables, lengths, scale=Dh ** -0.5, impl=impl, force=force,
                # the pool was scatter-updated two lines up, INSIDE this
                # program: consume the layout XLA prefers for that
                # scatter instead of pinning kernel-native row-major —
                # this is what drives the banked sharded_decode
                # relayout-copy-pair count to zero
                pool_layout="xla",
            )  # [B, H_local, 1, Dh]
            attn = attn[:, :, 0, :].reshape(B, H_local * Dh)
            # row-parallel wo: each shard's heads contribute a [B, d]
            # partial; one psum over ICI joins them
            attn_out = jax.lax.psum(attn @ lp["wo"], axis)
            h = _layernorm(h + attn_out, lp["ln1_g"], lp["ln1_b"])
            ff = jax.lax.psum(
                jnp.maximum(h @ lp["w1"] + lp["b1"], 0.0) @ lp["w2"],
                axis) + lp["b2"]
            h = _layernorm(h + ff, lp["ln2_g"], lp["ln2_b"])
        return h @ jnp.asarray(params["embed"]).T, k_pages, v_pages

    return step


def verify_step_fn(cfg: DecodeConfig, n_shards: int, axis: str = AXIS_TP,
                   impl: str = "reference", force: str = "auto"):
    """Build the shard_map body for one speculative VERIFY step — the
    mesh twin of ``generate.verify_step`` (ISSUE 16): Sq = 1+d ragged
    query rows per sequence through ``paged_decode_attention``'s
    ``q_lengths`` arm, over the LOCAL KV-head pool shard.

    fn(params, tokens [B, Sqm], pos_c [B, Sqm], q_lens [B],
       tables [B, maxp], lengths [B], pages [B*Sqm], slots [B*Sqm],
       b_idx [B*Sqm], t_idx [B*Sqm], k_pages, v_pages)
      -> (logits [B, Sqm, V] replicated, new k_pages, new v_pages)

    The K/V append reuses the prefill body's stable-shape scatter (the
    host pads the claim to B*Sqm rows by repeating the last one —
    duplicate indices with identical values are a no-op); the page
    stream is the SAME as the decode step's (each live page reads once
    per sequence), which is the amortization mesh speculation banks.
    Rows past ``q_lens[i]`` are padding garbage the caller ignores."""
    H_local, Hkv_local = _local_heads(cfg, n_shards)
    d, Dh = cfg.d_model, cfg.head_dim

    def step(params, tokens, pos_c, q_lens, tables, lengths,
             pages, slots, b_idx, t_idx, k_pages, v_pages):
        B, Sqm = tokens.shape
        h = jnp.asarray(params["embed"])[tokens] * np.sqrt(d) \
            + jnp.asarray(params["pos"])[pos_c]  # [B, Sqm, d]
        for li, lp in enumerate(params["layers"]):
            q = (h @ lp["wq"]).reshape(B, Sqm, H_local, Dh)
            k = (h @ lp["wk"]).reshape(B, Sqm, Hkv_local, Dh)
            v = (h @ lp["wv"]).reshape(B, Sqm, Hkv_local, Dh)
            k_pages = k_pages.at[li, :, pages, slots].set(k[b_idx, t_idx])
            v_pages = v_pages.at[li, :, pages, slots].set(v[b_idx, t_idx])
            attn = paged_decode_attention(
                q.transpose(0, 2, 1, 3), k_pages[li], v_pages[li],
                tables, lengths, scale=Dh ** -0.5, impl=impl,
                force=force, q_lengths=q_lens,
                pool_layout="xla",
            )  # [B, H_local, Sqm, Dh]
            attn = attn.transpose(0, 2, 1, 3).reshape(B, Sqm,
                                                      H_local * Dh)
            attn_out = jax.lax.psum(attn @ lp["wo"], axis)
            h = _layernorm(h + attn_out, lp["ln1_g"], lp["ln1_b"])
            ff = jax.lax.psum(
                jnp.maximum(h @ lp["w1"] + lp["b1"], 0.0) @ lp["w2"],
                axis) + lp["b2"]
            h = _layernorm(h + ff, lp["ln2_g"], lp["ln2_b"])
        return h @ jnp.asarray(params["embed"]).T, k_pages, v_pages

    return step


def prefill_step_fn(cfg: DecodeConfig, n_shards: int, axis: str = AXIS_TP,
                    force: str = "auto"):
    """Build the shard_map body for one batched whole-prompt prefill.

    fn(params, tokens [B, Smax], lens [B], pages [T], slots [T],
       b_idx [T], t_idx [T], k_pages, v_pages)
      -> (last-position logits [B, V] replicated, new k_pages, new
          v_pages)

    Same sharding as the decode step; the causal pass runs through the
    flash ``k_lengths`` tier over the LOCAL heads (GQA repeats each
    local KV head over its query group for the compute — the pool
    write stays at H_kv/n heads)."""
    H_local, Hkv_local = _local_heads(cfg, n_shards)
    G = cfg.group_size
    d, Dh = cfg.d_model, cfg.head_dim

    def step(params, tokens, lens, pages, slots, b_idx, t_idx,
             k_pages, v_pages):
        B, Smax = tokens.shape
        h = jnp.asarray(params["embed"])[tokens] * np.sqrt(d) \
            + jnp.asarray(params["pos"])[None, :Smax]
        for li, lp in enumerate(params["layers"]):
            q = (h @ lp["wq"]).reshape(B, Smax, H_local, Dh)
            k = (h @ lp["wk"]).reshape(B, Smax, Hkv_local, Dh)
            v = (h @ lp["wv"]).reshape(B, Smax, Hkv_local, Dh)
            k_pages = k_pages.at[li, :, pages, slots].set(k[b_idx, t_idx])
            v_pages = v_pages.at[li, :, pages, slots].set(v[b_idx, t_idx])
            kh, vh = repeat_kv(k.transpose(0, 2, 1, 3),
                               v.transpose(0, 2, 1, 3), G)
            attn = flash_attention(
                q.transpose(0, 2, 1, 3), kh, vh, causal=True,
                scale=Dh ** -0.5, k_lengths=lens, force=force)
            attn = attn.transpose(0, 2, 1, 3).reshape(B, Smax, H_local * Dh)
            attn_out = jax.lax.psum(attn @ lp["wo"], axis)
            h = _layernorm(h + attn_out, lp["ln1_g"], lp["ln1_b"])
            ff = jax.lax.psum(
                jnp.maximum(h @ lp["w1"] + lp["b1"], 0.0) @ lp["w2"],
                axis) + lp["b2"]
            h = _layernorm(h + ff, lp["ln2_g"], lp["ln2_b"])
        h_last = h[jnp.arange(B), lens - 1]
        return h_last @ jnp.asarray(params["embed"]).T, k_pages, v_pages

    return step


def _shard_param(leaf, spec: P, mesh: Mesh):
    """Place one host weight onto the mesh under its PartitionSpec —
    column/row shards land distributed, replicated leaves everywhere."""
    return jax.device_put(jnp.asarray(leaf, jnp.float32),
                          NamedSharding(mesh, spec))


# ---------------------------------------------------------------------------
# the sharded pool


class ShardedKVCachePool(KVCachePool):
    """KVCachePool whose pages live head-sharded across a mesh axis.

    The HOST side — per-sequence page tables, the free list, admission
    accounting, check_invariants/reclaim_orphans — is inherited
    unchanged and stays global: one page id means the same (per-shard)
    page on every device, so one admission decision reserves capacity
    for the whole mesh.  The DEVICE side shards axis 1 (heads): each
    device holds ``[L, H/n_shards, num_pages, page_size, D]`` — exactly
    1/n_shards of the single-device pool's HBM footprint, which is the
    capacity play: n chips hold n× the concurrent sequences.

    K/V writes on the sharded path happen INSIDE the shard-mapped step
    (each device writes its own heads); the program hands the updated
    arrays back through :meth:`store`.

    Prefix caching (ISSUE 11) rides the host-global bookkeeping for
    free: page refcounts, ``attach_prefix``, LRU eviction, and the
    invariant audit are pure table/free-list state — inherited
    unchanged — and the copy-on-write page copy is a functional update
    along the (unsharded) page axis, so one ``_cow_tail`` executes as
    a per-shard local copy on every device.  A
    ``serving.PrefixCache(pool)`` over this pool therefore shares an
    N-way prefix at 1/n_shards bytes per device with no SPMD-side
    changes; the loop feeds cached-prefix tails through the program's
    decode step (its prefill body starts at position 0)."""

    def __init__(self, num_pages: int, page_size: int, num_layers: int,
                 num_heads: int, head_dim: int, dtype="float32",
                 name: str = "kv", mesh: Optional[Mesh] = None,
                 n_shards: Optional[int] = None, axis: str = AXIS_TP,
                 num_kv_heads: Optional[int] = None):
        import jax.numpy as jnp

        if jnp.dtype(dtype) == jnp.dtype(jnp.int8):
            raise ValueError(
                "int8 KV pages are not supported on the mesh-sharded "
                "pool yet: the SPMD step writes K/V inside shard_map "
                "where the host-side per-page scale bookkeeping cannot "
                "reach — use a replicated single-device pool for int8, "
                "or fp32/bf16 on the mesh")
        if mesh is None:
            n = int(n_shards or 1)
            mesh = Mesh(np.asarray(host_mesh_devices(n)), (axis,))
        self.mesh = mesh
        self.axis = axis
        self.n_shards = int(mesh.shape[axis])
        h_kv = int(num_kv_heads if num_kv_heads is not None else num_heads)
        if h_kv % self.n_shards:
            raise ValueError(
                f"num_kv_heads={h_kv} must divide by the mesh's "
                f"{axis} axis ({self.n_shards}) — the pool shards over "
                "the KV-head dim")
        super().__init__(num_pages, page_size, num_layers, num_heads,
                         head_dim, dtype=dtype, name=name,
                         num_kv_heads=num_kv_heads)
        self.sharding = NamedSharding(mesh, _kv_spec(axis))
        # TPU: place the pool in the XLA-preferred layout from birth
        # (kv_pool_layout) so the first step never reshards; CPU has no
        # layout choice
        placement = (kv_pool_layout(self.sharding)
                     if mesh.devices.flat[0].platform == "tpu"
                     else self.sharding)
        self.k_pages = jax.device_put(self.k_pages, placement)
        self.v_pages = jax.device_put(self.v_pages, placement)

    @property
    def heads_per_shard(self) -> int:
        return self.num_kv_heads // self.n_shards

    def bytes_per_page_per_shard(self) -> int:
        """One page's K+V bytes on ONE device (the admission math a
        per-chip HBM budget divides by)."""
        return self.bytes_per_page() // self.n_shards

    def store(self, k_pages, v_pages) -> None:
        """Adopt the step's functionally-updated pool arrays (under the
        pool lock, like every other mutation)."""
        with self._lock:
            self.k_pages = k_pages
            self.v_pages = v_pages


# ---------------------------------------------------------------------------
# the program


class ShardedDecodeProgram:
    """The decode/prefill step pair, jitted once over a tp mesh.

    Drives the same host-side protocol as serving/generate.py's module
    functions — claim (page, slot)s from the pool, run the step, adopt
    the updated pool arrays — so ``ContinuousBatchingLoop(...,
    program=...)`` swaps the single-device math for the SPMD program
    with no loop changes: admission, quarantine, retirement, and the
    page-leak invariants all run unmodified.

    ``paged_impl``: like the loop's — None reads FLAGS_serving_paged_impl
    and resolves against the pool geometry on first use ('auto' is the
    reference gather on CPU meshes; the pallas page reader runs
    per-shard unchanged on TPU, its grid was already per-head).
    """

    def __init__(self, params: Dict, cfg: DecodeConfig,
                 n_shards: Optional[int] = None,
                 devices: Optional[Sequence] = None, axis: str = AXIS_TP,
                 force: str = "auto", paged_impl: Optional[str] = None):
        if devices is None:
            devices = host_mesh_devices(int(n_shards or 1))
        elif n_shards is not None:
            if len(devices) < int(n_shards):
                raise ValueError(
                    f"n_shards={n_shards} but only {len(devices)} devices "
                    "were supplied — a silently smaller mesh would change "
                    "per-chip pool capacity and cost")
            devices = list(devices)[: int(n_shards)]
        self.cfg = cfg
        self.axis = axis
        self.n_shards = len(devices)
        _local_heads(cfg, self.n_shards)  # both head counts must split
        self.force = force
        self._requested_impl = paged_impl
        self.paged_impl: Optional[str] = None  # resolved on first pool use
        self.mesh = Mesh(np.asarray(devices), (axis,))
        self._pspecs = param_partition_specs(cfg, axis)
        # PartitionSpec is a tuple subclass, so a naive two-tree
        # tree_map would flatten INTO the specs; flatten_up_to stops at
        # the params treedef's leaves instead
        leaves, treedef = jax.tree_util.tree_flatten(dict(params))
        spec_leaves = treedef.flatten_up_to(self._pspecs)
        self.params = jax.tree_util.tree_unflatten(treedef, [
            _shard_param(leaf, spec, self.mesh)
            for leaf, spec in zip(leaves, spec_leaves)])
        self._decode_jit = None
        self._prefill_jit = None
        self._verify_jit = None

    # -- pool ----------------------------------------------------------

    def make_pool(self, num_pages: int, page_size: int,
                  dtype="float32", name: str = "kv") -> ShardedKVCachePool:
        """A pool shaped for this program's model (H_kv heads for a GQA
        config), KV-head-sharded over the program's mesh."""
        return ShardedKVCachePool(
            num_pages, page_size, self.cfg.n_layer, self.cfg.n_head,
            self.cfg.head_dim, dtype=dtype, name=name, mesh=self.mesh,
            axis=self.axis, num_kv_heads=self.cfg.num_kv_heads)

    def resolve_impl(self, pool: KVCachePool) -> str:
        """Resolve (once) the paged-attention impl against this pool's
        geometry — the label every metric carries."""
        if self.paged_impl is None:
            self.paged_impl = resolve_paged_impl(
                self._requested_impl, pool.page_size, self.cfg.head_dim,
                pool.k_pages.dtype)
        return self.paged_impl

    def _check_pool(self, pool) -> None:
        if getattr(pool, "mesh", None) is not self.mesh:
            raise ValueError(
                "pool is not sharded over this program's mesh — build it "
                "with program.make_pool(...) (a replicated or "
                "foreign-mesh pool would reshard every step)")

    # -- jit construction ----------------------------------------------

    def _build(self, body, n_rep: int = 6):
        """Jit one shard-mapped step body: `n_rep` replicated operands
        ride between the params pytree and the two kv pool shards (6
        for decode/prefill, 9 for the wider verify signature)."""
        kv = _kv_spec(self.axis)
        rep = P()
        # check_vma off: pallas_call has no replication rule, and the
        # logits ARE replicated by construction (every shard holds the
        # same psum-joined activations) — tests pin bit-identity
        fn = jax.shard_map(
            body, mesh=self.mesh,
            in_specs=(self._pspecs,) + (rep,) * n_rep + (kv, kv),
            out_specs=(rep, kv, kv), check_vma=False)
        if self.mesh.devices.flat[0].platform != "tpu":
            # CPU meshes have no layout choice to make — and no tax
            return jax.jit(fn)
        # TPU: pin the pool args/results (aliased across steps via
        # store()) to the XLA-preferred layout the kernel consumes, so
        # the pool lives relayout-free across the whole serving life
        ns = lambda spec: NamedSharding(self.mesh, spec)
        kv_io = kv_pool_layout(ns(kv))
        param_sh = jax.tree_util.tree_map(
            ns, self._pspecs,
            is_leaf=lambda x: isinstance(x, P))
        return jax.jit(
            fn,
            in_shardings=(param_sh,) + (ns(rep),) * n_rep
            + (kv_io, kv_io),
            out_shardings=(ns(rep), kv_io, kv_io))

    def _decode(self):
        if self._decode_jit is None:
            self._decode_jit = self._build(decode_step_fn(
                self.cfg, self.n_shards, self.axis,
                impl=self.paged_impl or "reference", force=self.force))
        return self._decode_jit

    def _prefill(self):
        if self._prefill_jit is None:
            self._prefill_jit = self._build(prefill_step_fn(
                self.cfg, self.n_shards, self.axis, force=self.force))
        return self._prefill_jit

    def _verify(self):
        if self._verify_jit is None:
            self._verify_jit = self._build(verify_step_fn(
                self.cfg, self.n_shards, self.axis,
                impl=self.paged_impl or "reference", force=self.force),
                n_rep=9)
        return self._verify_jit

    # -- the ContinuousBatchingLoop program protocol --------------------

    def decode_step(self, pool: ShardedKVCachePool,
                    seq_ids: Sequence[int], tokens, positions
                    ) -> np.ndarray:
        """One continuous-batching decode step (generate.decode_step's
        contract): claim one (page, slot) per sequence, run the SPMD
        step, adopt the updated pool shards; returns logits [B, V]."""
        self._check_pool(pool)
        self.resolve_impl(pool)
        tokens = np.asarray(tokens, np.int32)
        positions = np.asarray(positions, np.int32)
        pages, slots = pool.append_token(seq_ids)
        tables, lengths = pool.page_table_batch(seq_ids)
        logits, k_pages, v_pages = self._decode()(
            self.params, tokens, positions, pages, slots,
            tables, lengths, pool.k_pages, pool.v_pages)
        pool.store(k_pages, v_pages)
        return np.asarray(logits)

    def verify_step(self, pool: ShardedKVCachePool,
                    seq_ids: Sequence[int],
                    blocks: Sequence[Sequence[int]],
                    start_positions: Sequence[int],
                    pad_to: Optional[int] = None) -> np.ndarray:
        """One speculative verify step under the SPMD program —
        ``generate.verify_step``'s exact host protocol (ONE atomic
        ``append_tokens`` claim, 8-bucketed page tables, stable-shape
        scatter padding, rows past ``len(blocks[i])`` are garbage) so
        ``ContinuousBatchingLoop(..., program=...)`` speculates with no
        loop changes; returns logits [B, Sq_max, V].  The caller owns
        acceptance and rollback (``pool.truncate_seq``)."""
        self._check_pool(pool)
        self.resolve_impl(pool)
        lens = np.asarray([len(b) for b in blocks], np.int32)
        if not len(lens) or lens.min() < 1:
            raise ValueError("verify needs >= 1 fed token per sequence")
        starts = np.asarray(start_positions, np.int32)
        B, Sqm = len(blocks), int(lens.max())
        if pad_to is not None:
            if pad_to < Sqm:
                raise ValueError(
                    f"pad_to {pad_to} < longest block {Sqm}")
            Sqm = int(pad_to)
        if int((starts + lens).max()) > self.cfg.max_length:
            # before append_tokens: a failed verify must not leave
            # claimed slots with no K/V behind (the pool's atomicity
            # contract)
            raise ValueError(
                f"verify block reaches position "
                f"{int((starts + lens).max())} > max_length "
                f"{self.cfg.max_length}")
        tokens = np.zeros((B, Sqm), np.int32)
        for i, b in enumerate(blocks):
            tokens[i, :lens[i]] = b
        pages, slots = pool.append_tokens(seq_ids, lens)
        tables, lengths = pool.page_table_batch(seq_ids)
        if tables.shape[1] % 8:
            # 8-bucketed table width: one compile shape per 8 pages of
            # growth (padded entries are length-masked page-0 walks)
            padded = -(-tables.shape[1] // 8) * 8
            tables = np.pad(tables,
                            ((0, 0), (0, padded - tables.shape[1])))
        b_idx = np.repeat(np.arange(B), lens)
        t_idx = np.concatenate([np.arange(n) for n in lens])
        # stable-shape scatter: pad the claim to B*Sqm rows by
        # repeating the last (page, slot) and its source row —
        # duplicate indices with identical values are a no-op
        pad_rows = B * Sqm - len(b_idx)
        if pad_rows:
            b_idx = np.concatenate([b_idx,
                                    np.full(pad_rows, b_idx[-1])])
            t_idx = np.concatenate([t_idx,
                                    np.full(pad_rows, t_idx[-1])])
            pages = np.concatenate([pages, np.full(pad_rows, pages[-1],
                                                   pages.dtype)])
            slots = np.concatenate([slots, np.full(pad_rows, slots[-1],
                                                   slots.dtype)])
        pos = starts[:, None] + np.arange(Sqm)[None, :]
        pos_c = np.minimum(pos, self.cfg.max_length - 1)
        logits, k_pages, v_pages = self._verify()(
            self.params, tokens, pos_c.astype(np.int32), lens, tables,
            lengths, np.asarray(pages), np.asarray(slots),
            b_idx.astype(np.int32), t_idx.astype(np.int32),
            pool.k_pages, pool.v_pages)
        pool.store(k_pages, v_pages)
        return np.asarray(logits)

    def prefill_step(self, pool: ShardedKVCachePool,
                     seq_ids: Sequence[int],
                     prompts: Sequence[Sequence[int]]) -> np.ndarray:
        """Batched whole-prompt prefill (generate.prefill_step's
        contract) under the SPMD program; returns last-position logits
        [B, V]."""
        self._check_pool(pool)
        self.resolve_impl(pool)
        lens = np.asarray([len(p) for p in prompts], np.int32)
        if not len(lens) or lens.min() < 1:
            raise ValueError("prefill needs non-empty prompts")
        B, Smax = len(prompts), int(lens.max())
        if Smax > self.cfg.max_length:
            raise ValueError(
                f"prompt length {Smax} > max_length {self.cfg.max_length}")
        tokens = np.zeros((B, Smax), np.int32)
        for i, p in enumerate(prompts):
            tokens[i, :lens[i]] = p
        pages, slots = pool.append_tokens(seq_ids, lens)
        b_idx = np.repeat(np.arange(B), lens).astype(np.int32)
        t_idx = np.concatenate([np.arange(n) for n in lens]).astype(np.int32)
        logits, k_pages, v_pages = self._prefill()(
            self.params, tokens, lens, pages, slots, b_idx, t_idx,
            pool.k_pages, pool.v_pages)
        pool.store(k_pages, v_pages)
        return np.asarray(logits)
