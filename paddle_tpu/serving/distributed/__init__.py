"""Mesh-sharded serving: tensor-parallel decode + replicated engines.

The serving tier's two single-device ceilings fall here, composably:

- **Tensor parallelism** (`sharded.py`): :class:`ShardedDecodeProgram`
  runs the transformer decode step under ``jax.shard_map`` over a
  device mesh — attention and MLP weights column/row-sharded across the
  ``tp`` axis, partial products combined with ``psum`` over ICI — and
  :class:`ShardedKVCachePool` gives the paged KV cache a per-shard view
  (``[L, H/n_shards, P, page_size, D]`` per device), so every device
  owns its heads' pages and both the K/V append and the paged-attention
  page walk stay device-local.  One model, ``n_shards`` chips, no
  resharding on the decode hot path.
- **Data parallelism** (`router.py`): :class:`Router` fronts N
  ``Engine`` replicas behind one ``submit(feed) -> Future`` API —
  health-aware least-queue-depth dispatch (skipping DEGRADED/BROKEN
  replicas via ``engine.health()``), replica membership on the elastic
  master's heartbeat/lease seam (:class:`ReplicaDirectory`), and
  drain-based handoff: a draining replica finishes its in-flight
  sequences while the router routes new traffic elsewhere.

Everything is proven chip-less: ``XLA_FLAGS=--xla_force_host_platform_
device_count=N`` gives an N-device CPU mesh on which the SPMD decode
step is token-identical to the single-device oracle
(tests/test_distributed_serving.py), and the AOT v5e cost tier prices
the sharded program's per-chip bytes/step (the ``sharded_decode``
entry of the analysis model zoo, gated in AOT_COST_ZOO.json).
"""

from .router import (
    ReplicaDirectory,
    ReplicaUnavailableError,
    Router,
)
from .sharded import (
    ShardedDecodeProgram,
    ShardedKVCachePool,
    host_mesh_devices,
)

__all__ = [
    "ReplicaDirectory",
    "ReplicaUnavailableError",
    "Router",
    "ShardedDecodeProgram",
    "ShardedKVCachePool",
    "host_mesh_devices",
]
