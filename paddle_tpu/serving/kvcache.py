"""Paged KV-cache pool: fixed-size page blocks in one preallocated device
array, per-sequence page tables, alloc/free/defrag accounting.

The shape follows Ragged Paged Attention (arxiv 2604.15464): instead of
one contiguous [B, H, max_len, D] cache per sequence (whose worst-case
max_len reservation strands HBM the moment sequence lengths vary), the
cache is a pool of PAGES — [H, num_pages, page_size, D] per layer, all
layers stacked in one array so one allocation covers the model.  A
sequence owns an ordered list of page ids (its page table) and a length;
appending a token claims the next slot in its last page, allocating a
fresh page only every `page_size` tokens.  Fragmentation is impossible at
page granularity (any free page serves any sequence) and retiring a
sequence returns its pages to the free list in O(pages).

The layout is KERNEL-NATIVE: heads sit OUTSIDE the page dim so one
(page, head) block of the pallas page reader
(kernels/paged_attention.py) is a contiguous [page_size, head_dim]
plane — natively (sublane, lane)-tiled on TPU, streamed from HBM
without relayout.  Attention consumes the pool through
paged_decode_attention: `impl="pallas"` walks each sequence's page
table in SMEM and reads pages in place (no gather materialization);
`impl="reference"` gathers the pages into a contiguous [B, H, S, D]
view for the flash_attention ragged `k_lengths` tier.

Writes use jax functional updates (`.at[...].set`), so the pool works on
any backend; on TPU XLA performs them as in-place dynamic-update-slices
when the buffer is donated (the arrays are never aliased here).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from ..resilience import faultinject as _finject
from . import metrics as _smetrics

__all__ = ["KVCachePool", "PagePoolExhausted", "SequenceHandle"]


class PagePoolExhausted(RuntimeError):
    """No free page to satisfy an append — the admission controller must
    retire or refuse sequences before this fires mid-decode."""


@dataclasses.dataclass
class SequenceHandle:
    """Per-sequence page table: ordered page ids + token count."""

    seq_id: int
    pages: List[int] = dataclasses.field(default_factory=list)
    length: int = 0

    def capacity(self, page_size: int) -> int:
        return len(self.pages) * page_size


class KVCachePool:
    """Preallocated paged K/V storage for every layer of one model.

    k_pages / v_pages: [num_layers, num_heads, num_pages, page_size,
    head_dim] jax arrays (heads outermost — the pallas page reader's
    native block layout).  All mutation (allocate/append/free/defrag) is
    serialized under one lock — the continuous-batching loop drives the
    pool from its own thread while metrics/introspection may read from
    others."""

    def __init__(self, num_pages: int, page_size: int, num_layers: int,
                 num_heads: int, head_dim: int, dtype="float32",
                 name: str = "kv"):
        if num_pages < 1 or page_size < 1:
            raise ValueError("num_pages and page_size must be >= 1")
        import jax.numpy as jnp

        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.name = name
        shape = (num_layers, num_heads, num_pages, page_size, head_dim)
        self.k_pages = jnp.zeros(shape, dtype=jnp.dtype(dtype))
        self.v_pages = jnp.zeros(shape, dtype=jnp.dtype(dtype))
        self._lock = threading.Lock()
        # LIFO free list: recently-freed pages are reused first (their
        # tiles are warm in whatever cache hierarchy the backend has)
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._tables: Dict[int, SequenceHandle] = {}
        self._stats = {
            "page_allocs": 0, "page_frees": 0, "token_appends": 0,
            "defrag_moves": 0, "used_pages_high_water": 0,
            "orphans_reclaimed": 0,
        }

    # -- sizing math (documented in README "Serving") -------------------

    @classmethod
    def pages_needed(cls, tokens: int, page_size: int) -> int:
        """ceil(tokens / page_size) — the admission controller's unit."""
        return -(-int(tokens) // int(page_size))

    def bytes_per_page(self) -> int:
        itemsize = np.dtype(self.k_pages.dtype).itemsize
        return (2 * self.num_layers * self.page_size * self.num_heads
                * self.head_dim * itemsize)

    # -- lifecycle ------------------------------------------------------

    def allocate(self, seq_id: int) -> SequenceHandle:
        """Register a sequence with an empty page table (pages are
        claimed lazily by append_token)."""
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id} already allocated")
            h = SequenceHandle(seq_id)
            self._tables[seq_id] = h
            return h

    def free_seq(self, seq_id: int) -> int:
        """Retire a sequence: its pages return to the free list.
        Returns the number of pages released."""
        with self._lock:
            h = self._tables.pop(seq_id)
            for p in reversed(h.pages):
                self._free.append(p)
            self._stats["page_frees"] += len(h.pages)
            n = len(h.pages)
        self._note_pool()
        return n

    def append_token(self, seq_ids: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Claim the next (page, slot) for one new token on every
        sequence; advances lengths.  Returns (pages [B], slots [B])
        int32 arrays for write_kv.  Raises PagePoolExhausted (before
        mutating ANY table) if the claim cannot be satisfied."""
        return self.append_tokens(seq_ids, [1] * len(seq_ids))

    def append_tokens(self, seq_ids: Sequence[int],
                      counts: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Claim (page, slot)s for counts[i] new tokens on sequence i in
        ONE atomic step — the batched-prefill path (a whole prompt's
        worth of slots per sequence, one pool transaction instead of one
        per token).  Returns (pages [T], slots [T]) int32 flattened in
        (sequence order, token order) — exactly the row order of
        k[b_idx, :, t_idx] at the write_kv call site.  Raises
        PagePoolExhausted before mutating ANY table."""
        counts = [int(c) for c in counts]
        if len(counts) != len(seq_ids) or any(c < 0 for c in counts):
            raise ValueError("counts must align with seq_ids and be >= 0")
        with self._lock:
            need = 0
            for s, c in zip(seq_ids, counts):
                h = self._tables[s]
                free_slots = h.capacity(self.page_size) - h.length
                if c > free_slots:
                    need += self.pages_needed(c - free_slots, self.page_size)
            if need > len(self._free):
                raise PagePoolExhausted(
                    f"pool '{self.name}': need {need} fresh pages for "
                    f"{sum(counts)} appends but only {len(self._free)} "
                    f"free of {self.num_pages}")
            pages = np.empty(sum(counts), np.int32)
            slots = np.empty(sum(counts), np.int32)
            i = 0
            for s, c in zip(seq_ids, counts):
                h = self._tables[s]
                for _ in range(c):
                    if h.length == h.capacity(self.page_size):
                        h.pages.append(self._free.pop())
                        self._stats["page_allocs"] += 1
                    pages[i] = h.pages[-1]
                    slots[i] = h.length % self.page_size
                    h.length += 1
                    i += 1
            self._stats["token_appends"] += sum(counts)
            leak = _finject.serve_leak_pages()
            if leak:  # chaos: orphan pages (owned by nobody, not free)
                del self._free[-min(leak, len(self._free)):]
            used = self.num_pages - len(self._free)
            if used > self._stats["used_pages_high_water"]:
                self._stats["used_pages_high_water"] = used
        self._note_pool()
        return pages, slots

    def write_kv(self, layer: int, pages: np.ndarray, slots: np.ndarray,
                 k, v) -> None:
        """Write token K/V for `layer`: k/v [T, num_heads, head_dim]
        into the claimed (page, slot)s (T = batch rows for one decode
        step, or a whole prompt batch's flattened tokens for prefill).
        (page, slot) pairs must be distinct — append_token/append_tokens
        guarantee it.  Locked like every other mutation: an unlocked
        read-modify-write of the arrays would race defrag()'s
        permutation and silently drop one side's update."""
        with self._lock:
            # non-contiguous advanced indices (slice over H between
            # them): the indexed view is [T, H, D] — k/v land as-is
            self.k_pages = self.k_pages.at[layer, :, pages, slots].set(k)
            self.v_pages = self.v_pages.at[layer, :, pages, slots].set(v)

    # -- read side ------------------------------------------------------

    def page_table_batch(self, seq_ids: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Batch view for attention: (tables [B, max_pages] int32 padded
        with page 0 — the ragged k_lengths mask hides the tail — and
        lengths [B] int32 valid token counts)."""
        with self._lock:
            handles = [self._tables[s] for s in seq_ids]
            maxp = max((len(h.pages) for h in handles), default=1) or 1
            tables = np.zeros((len(handles), maxp), np.int32)
            lengths = np.empty(len(handles), np.int32)
            for i, h in enumerate(handles):
                tables[i, :len(h.pages)] = h.pages
                lengths[i] = h.length
        return tables, lengths

    def length(self, seq_id: int) -> int:
        with self._lock:
            return self._tables[seq_id].length

    def max_live_pages(self) -> int:
        """Longest live sequence's page count (0 when idle) — the width
        of the decode attention batch's page table."""
        with self._lock:
            return max((len(h.pages) for h in self._tables.values()),
                       default=0)

    # -- accounting -----------------------------------------------------

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        with self._lock:
            return self.num_pages - len(self._free)

    def utilization(self) -> float:
        return self.used_pages / float(self.num_pages)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            live = {s: h.length for s, h in self._tables.items()}
            return dict(self._stats,
                        used_pages=self.num_pages - len(self._free),
                        free_pages=len(self._free),
                        num_pages=self.num_pages,
                        live_sequences=len(live))

    def _note_pool(self) -> None:
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_page_pool(
                self.used_pages, self.num_pages, pool=self.name)

    # -- integrity watchdog ---------------------------------------------

    def check_invariants(self) -> Dict:
        """Audit page ownership: every page id must appear EXACTLY once
        across the union of live page tables and the free list.  Returns
        a report dict — `ok` plus the violating page/sequence ids:

        - orphaned_pages: owned by no table and not free (a leak — the
          pool shrinks until exhaustion; reclaim_orphans repairs)
        - double_owned_pages: in two tables, twice in one table, or in
          a table AND the free list (corruption — two sequences would
          overwrite each other's K/V)
        - free_list_errors: duplicate or out-of-range free entries
        - length_mismatches: sequences whose token count disagrees with
          their page count (length > capacity, or an entire spare page)

        Cost is O(pages + live tokens/page_size) under the pool lock —
        cheap enough for the continuous-batching loop to run every N
        steps (ContinuousBatchingLoop(check_every=N))."""
        with self._lock:
            owned: Dict[int, int] = {}
            double: List[int] = []
            mismatches: List[int] = []
            for h in self._tables.values():
                for p in h.pages:
                    if p in owned:
                        double.append(p)
                    owned[p] = h.seq_id
                cap = h.capacity(self.page_size)
                if h.length > cap or cap - h.length >= self.page_size:
                    mismatches.append(h.seq_id)
            free_errors: List[int] = []
            seen_free: set = set()
            for p in self._free:
                if p in seen_free or not 0 <= p < self.num_pages:
                    free_errors.append(p)
                seen_free.add(p)
                if p in owned:
                    double.append(p)
            orphaned = [p for p in range(self.num_pages)
                        if p not in owned and p not in seen_free]
            report = {
                "ok": not (orphaned or double or free_errors or mismatches),
                "orphaned_pages": orphaned,
                "double_owned_pages": sorted(set(double)),
                "free_list_errors": free_errors,
                "length_mismatches": mismatches,
                "used_pages": self.num_pages - len(self._free),
                "live_sequences": len(self._tables),
            }
        if _flags._VALUES["FLAGS_observability"] and not report["ok"]:
            _smetrics.record_pool_invariant_violation(pool=self.name)
        return report

    def reclaim_orphans(self) -> int:
        """Return every orphaned page (owned by no table, absent from
        the free list) to the free pool; returns how many were
        reclaimed.  The repair arm of check_invariants — a detected leak
        costs pages until this runs, never the pool's integrity (page
        tables are untouched)."""
        with self._lock:
            owned = {p for h in self._tables.values() for p in h.pages}
            free = set(self._free)
            orphans = [p for p in range(self.num_pages)
                       if p not in owned and p not in free]
            self._free.extend(reversed(orphans))
            self._stats["orphans_reclaimed"] += len(orphans)
        if orphans:
            self._note_pool()
        return len(orphans)

    # -- defrag ---------------------------------------------------------

    def defrag(self) -> int:
        """Compact used pages to the lowest indices (one permutation
        gather per K/V array) and rebuild the free list as the dense
        tail.  Page-granular allocation never NEEDS this for correctness
        — any free page serves any sequence, and the Pallas page reader
        follows the page table wherever it points — but a compacted pool
        lets an operator shrink `num_pages` between runs.  Returns the
        number of pages moved."""
        with self._lock:
            used: List[int] = []
            for h in self._tables.values():
                used.extend(h.pages)
            remap = {old: new for new, old in enumerate(sorted(used))}
            moves = sum(1 for old, new in remap.items() if old != new)
            if moves:
                perm = np.arange(self.num_pages, dtype=np.int32)
                for old, new in remap.items():
                    perm[new] = old
                # unused tail keeps a stable order: remaining page ids
                leftover = [p for p in range(self.num_pages)
                            if p not in remap]
                perm[len(remap):] = leftover
                self.k_pages = self.k_pages[:, :, perm]
                self.v_pages = self.v_pages[:, :, perm]
                for h in self._tables.values():
                    h.pages = [remap[p] for p in h.pages]
            self._free = list(range(self.num_pages - 1, len(remap) - 1, -1))
            self._stats["defrag_moves"] += moves
        return moves
