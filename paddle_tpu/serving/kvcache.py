"""Paged KV-cache pool: fixed-size page blocks in one preallocated device
array, per-sequence page tables, alloc/free/defrag accounting.

The shape follows Ragged Paged Attention (arxiv 2604.15464): instead of
one contiguous [B, H, max_len, D] cache per sequence (whose worst-case
max_len reservation strands HBM the moment sequence lengths vary), the
cache is a pool of PAGES — [H, num_pages, page_size, D] per layer, all
layers stacked in one array so one allocation covers the model.  A
sequence owns an ordered list of page ids (its page table) and a length;
appending a token claims the next slot in its last page, allocating a
fresh page only every `page_size` tokens.  Fragmentation is impossible at
page granularity (any free page serves any sequence) and retiring a
sequence returns its pages to the free list in O(pages).

The layout is KERNEL-NATIVE: heads sit OUTSIDE the page dim so one
(page, head) block of the pallas page reader
(kernels/paged_attention.py) is a contiguous [page_size, head_dim]
plane — natively (sublane, lane)-tiled on TPU, streamed from HBM
without relayout.  Attention consumes the pool through
paged_decode_attention: `impl="pallas"` walks each sequence's page
table in SMEM and reads pages in place (no gather materialization);
`impl="reference"` gathers the pages into a contiguous [B, H, S, D]
view for the flash_attention ragged `k_lengths` tier.

Writes use jax functional updates (`.at[...].set`), so the pool works on
any backend; on TPU XLA performs them as in-place dynamic-update-slices
when the buffer is donated (the arrays are never aliased here).

ISSUE 11 adds REFCOUNTED pages — the substrate of the prefix cache
(serving/prefixcache.py).  Every allocated page carries a refcount:
ordinarily 1 (its owning sequence), >1 when a prefix-cache entry and/or
additional sequences share it read-only (``attach_prefix`` /
``retain_pages``).  ``free_seq`` only returns pages whose refcount hits
zero, so an N-way-shared system prompt costs ONE page-set.  A shared
page is immutable: the first divergent append into a partially-filled
shared tail page triggers COPY-ON-WRITE inside ``append_tokens`` (fresh
page, device-side content copy, table tail swap) — accounted for in the
same atomic claim, so exhaustion still raises before any table mutates.
Under pressure the pool calls registered reclaimers (the prefix cache's
LRU eviction) to release cache-only pages before giving up.

ISSUE 12 makes the pool HEAD-GROUPED and QUANTIZABLE:

- ``num_kv_heads`` (GQA/MQA): the pool stores ``[L, H_kv, P,
  page_size, D]`` — KV storage shrinks H_q/H_kv x, and the grouped
  paged-attention kernel streams each page once per KV head while the
  group's query heads share it.  ``num_heads`` keeps meaning the
  model's QUERY heads (the attention-bytes accounting needs both).
- ``dtype="int8"``: pages hold amax-quantized int8 K/V with one fp32
  scale per (layer, page) for each of K and V, kept host-side in
  ``k_scales``/``v_scales`` ([L, P] float32, 0 = no content) —
  "alongside the page table", exactly like the table itself.
  ``write_kv`` quantizes: per touched page the scale is the running
  amax/127 (an amax that GROWS re-quantizes the page's existing int8
  content under the new scale — one small functional update over the
  touched pages only), so a page's dequantized error stays bounded by
  half an LSB of its own largest value.  Scales travel with pages
  through copy-on-write, defrag, and scrub; freeing a page clears its
  scale entries (check_invariants audits exactly that: live written
  pages have scales, freed pages must not).  ``corrupt_page`` poisons
  the K SCALE with NaN on an int8 pool — int8 content cannot encode
  non-finite, but a NaN scale dequantizes the whole page non-finite,
  which is the same detectable corruption face.
"""

from __future__ import annotations

import dataclasses
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from ..resilience import faultinject as _finject
from . import metrics as _smetrics

__all__ = ["KVCachePool", "PagePoolExhausted", "SeqExport",
           "SequenceHandle"]


class PagePoolExhausted(RuntimeError):
    """No free page to satisfy an append — the admission controller must
    retire or refuse sequences before this fires mid-decode."""


@dataclasses.dataclass
class SequenceHandle:
    """Per-sequence page table: ordered page ids + token count.

    ``starts`` (ISSUE 20) is the absolute token position of each
    page's slot 0.  ``None`` — the common case — means the implicit
    contiguous layout ``i * page_size``; it becomes explicit the first
    time sliding-window + sink EVICTION drops interior pages, after
    which the table is compacted (live pages only) and position
    masking must read the TRUE starts.  Every start is a multiple of
    page_size, strictly increasing, and the tail page is never
    evicted, so append slot math (``length % page_size``) is unchanged
    either way."""

    seq_id: int
    pages: List[int] = dataclasses.field(default_factory=list)
    length: int = 0
    starts: Optional[List[int]] = None

    def capacity(self, page_size: int) -> int:
        return len(self.pages) * page_size

    def page_starts(self, page_size: int) -> List[int]:
        """Absolute slot-0 positions, explicit or implicit."""
        if self.starts is not None:
            return self.starts
        return [i * page_size for i in range(len(self.pages))]

    def tail_free_slots(self, page_size: int) -> int:
        """Unclaimed slots in the tail page — the append-side capacity
        check that stays correct after eviction (capacity() counts
        RESIDENT pages, which undercounts an evicted sequence's logical
        extent)."""
        if not self.pages:
            return 0
        last = (self.starts[-1] if self.starts is not None
                else (len(self.pages) - 1) * page_size)
        return last + page_size - self.length


@dataclasses.dataclass
class SeqExport:
    """One sequence's KV pages serialized to HOST buffers — the
    disaggregated prefill→decode handoff payload (serving/fleet), and
    the natural unit a future host-RAM spill tier would stage.

    The staging is numpy on purpose: the same payload works when the
    source and destination pools live in different processes (pickle a
    SeqExport over any transport); when the pools share devices the
    functional page writes in ``import_seq`` stay device-side.
    ``skip_tokens`` leading tokens are NOT shipped — the destination
    re-attaches that shared prefix from its own prefix cache by hash,
    so only the unshared tail crosses the wire."""

    seq_id: int
    length: int                      # total tokens the sequence holds
    skip_tokens: int                 # leading tokens not shipped
    k: np.ndarray                    # [L, H_kv, n_pages, page_size, D]
    v: np.ndarray
    k_scales: Optional[np.ndarray]   # [L, n_pages] fp32 (int8 pools)
    v_scales: Optional[np.ndarray]
    page_size: int = 0
    num_layers: int = 0
    num_kv_heads: int = 0
    head_dim: int = 0
    dtype: str = "float32"
    pool: str = "kv"                 # source pool name
    # model variant the K/V was produced under (ISSUE 19; None = base).
    # kvtier / fleet handoff verify this at resume/admit so a payload
    # never decodes under a different adapter's weights.
    adapter_id: Optional[str] = None
    # ISSUE 20: absolute slot-0 positions of the shipped pages when the
    # source sequence was window/sink EVICTED (compacted table — pages
    # are no longer contiguous); None for the ordinary contiguous case
    starts: Optional[List[int]] = None

    def nbytes(self) -> int:
        """Payload bytes on the wire — serve_bench banks this per seq."""
        n = self.k.nbytes + self.v.nbytes
        if self.k_scales is not None:
            n += self.k_scales.nbytes + self.v_scales.nbytes
        return n

    def checksum(self) -> int:
        """CRC32 over the payload body (k, v, and any int8 scales) —
        the host KV tier records this at park and verifies at fetch so
        a corrupted parked payload is a typed rejection, never an
        imported-garbage sequence."""
        crc = zlib.crc32(np.ascontiguousarray(self.k).view(np.uint8))
        crc = zlib.crc32(np.ascontiguousarray(self.v).view(np.uint8), crc)
        if self.k_scales is not None:
            crc = zlib.crc32(
                np.ascontiguousarray(self.k_scales).view(np.uint8), crc)
            crc = zlib.crc32(
                np.ascontiguousarray(self.v_scales).view(np.uint8), crc)
        return crc & 0xFFFFFFFF


class KVCachePool:
    """Preallocated paged K/V storage for every layer of one model.

    k_pages / v_pages: [num_layers, num_heads, num_pages, page_size,
    head_dim] jax arrays (heads outermost — the pallas page reader's
    native block layout).  All mutation (allocate/append/free/defrag) is
    serialized under one lock — the continuous-batching loop drives the
    pool from its own thread while metrics/introspection may read from
    others."""

    def __init__(self, num_pages: int, page_size: int, num_layers: int,
                 num_heads: int, head_dim: int, dtype="float32",
                 name: str = "kv", num_kv_heads: Optional[int] = None):
        if num_pages < 1 or page_size < 1:
            raise ValueError("num_pages and page_size must be >= 1")
        import jax.numpy as jnp

        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.num_kv_heads = int(num_kv_heads
                                if num_kv_heads is not None else num_heads)
        from ..kernels.paged_attention import _group_size

        _group_size(self.num_heads, self.num_kv_heads)  # typed raise
        self.head_dim = int(head_dim)
        self.name = name
        shape = (num_layers, self.num_kv_heads, num_pages, page_size,
                 head_dim)
        self.k_pages = jnp.zeros(shape, dtype=jnp.dtype(dtype))
        self.v_pages = jnp.zeros(shape, dtype=jnp.dtype(dtype))
        # int8 pages: one fp32 amax scale per (layer, page) for each of
        # K and V, host-side next to the page tables (0 = no content).
        # fp32/bf16 pools carry no scale state at all.
        self.quantized = jnp.dtype(dtype) == jnp.dtype(jnp.int8)
        if self.quantized:
            self.k_scales = np.zeros((self.num_layers, self.num_pages),
                                     np.float32)
            self.v_scales = np.zeros((self.num_layers, self.num_pages),
                                     np.float32)
        else:
            self.k_scales = self.v_scales = None
        # RLock: pressure reclaimers (prefix-cache LRU eviction) run
        # INSIDE append_tokens' critical section and call back into
        # release_pages on the same thread
        self._lock = threading.RLock()
        # LIFO free list: recently-freed pages are reused first (their
        # tiles are warm in whatever cache hierarchy the backend has)
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._tables: Dict[int, SequenceHandle] = {}
        # per-page refcount: 0 = free, 1 = single owner, >1 = shared
        # read-only (prefix cache and/or attached sequences)
        self._ref: List[int] = [0] * self.num_pages
        # page -> the LIVE sequence whose admission charge covers it
        # (set at allocation, cleared when that sequence retires while
        # the page lives on, or when the page frees).  Admission's
        # uncharged_live_pages() is exact off this map — it cannot be
        # fooled by prefix-cache entry bookkeeping
        self._allocator: Dict[int, int] = {}
        # pressure reclaimers: fn(pages_short) -> pages freed (the
        # prefix cache's LRU eviction registers here)
        self._reclaim_hooks: List = []
        # external owners: fn() -> Dict[page, holds] (refcounts a table
        # does not explain — the prefix cache's entry holds)
        self._owner_hooks: List = []
        # defrag listeners: fn(remap Dict[old_page, new_page])
        self._remap_hooks: List = []
        self._stats = {
            "page_allocs": 0, "page_frees": 0, "token_appends": 0,
            "defrag_moves": 0, "used_pages_high_water": 0,
            "orphans_reclaimed": 0, "cow_copies": 0,
            "shared_attach_pages": 0, "tokens_truncated": 0,
            "seqs_exported": 0, "seqs_imported": 0,
            "pages_evicted": 0,
        }

    # -- sizing math (documented in README "Serving") -------------------

    @classmethod
    def pages_needed(cls, tokens: int, page_size: int) -> int:
        """ceil(tokens / page_size) — the admission controller's unit."""
        return -(-int(tokens) // int(page_size))

    def bytes_per_page(self) -> int:
        """One page's K+V bytes — the admission controller's divisor.
        KV storage scales with num_KV_heads (the GQA shrink) at the
        pool's REAL element size; an int8 pool adds its two fp32
        per-layer scale entries (README "Serving" sizing math)."""
        itemsize = np.dtype(self.k_pages.dtype).itemsize
        nbytes = (2 * self.num_layers * self.page_size * self.num_kv_heads
                  * self.head_dim * itemsize)
        if self.quantized:
            nbytes += 2 * self.num_layers * 4  # fp32 K + V scale / layer
        return nbytes

    def layer_scales(self, layer: int):
        """(k_scales [P], v_scales [P]) fp32 rows for one layer of an
        int8 pool — the dequant operands paged_decode_attention and
        gather_kv_pages take; (None, None) for unquantized pools."""
        if not self.quantized:
            return None, None
        with self._lock:
            return self.k_scales[layer].copy(), self.v_scales[layer].copy()

    def _clear_scales(self, pages: Sequence[int]) -> None:
        """Drop freed pages' scale entries (caller holds the lock) — a
        page on the free list must not keep a stale scale (audited)."""
        if self.quantized and len(pages):
            idx = np.asarray(pages, np.int32)
            self.k_scales[:, idx] = 0.0
            self.v_scales[:, idx] = 0.0

    # -- lifecycle ------------------------------------------------------

    def allocate(self, seq_id: int) -> SequenceHandle:
        """Register a sequence with an empty page table (pages are
        claimed lazily by append_token)."""
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id} already allocated")
            h = SequenceHandle(seq_id)
            self._tables[seq_id] = h
            return h

    def free_seq(self, seq_id: int) -> int:
        """Retire a sequence: each of its pages drops one refcount, and
        ONLY pages whose refcount hits zero return to the free list —
        pages shared with the prefix cache or other sequences stay
        live.  Returns the number of pages actually released."""
        with self._lock:
            h = self._tables.pop(seq_id)
            n = 0
            released: List[int] = []
            for p in reversed(h.pages):
                self._ref[p] -= 1
                if self._ref[p] <= 0:
                    self._ref[p] = 0
                    self._free.append(p)
                    self._allocator.pop(p, None)
                    released.append(p)
                    n += 1
                elif self._allocator.get(p) == seq_id:
                    # the charging sequence is gone but readers keep
                    # the page alive: it is now UNCHARGED (admission's
                    # uncharged_live_pages sets it aside)
                    del self._allocator[p]
            self._clear_scales(released)
            self._stats["page_frees"] += n
        self._note_pool()
        return n

    def truncate_seq(self, seq_id: int, length: int) -> int:
        """Atomically shrink a sequence's table to `length` tokens —
        the speculative-decode ROLLBACK (ISSUE 13): rejected draft
        tokens' claims are undone in one locked step.  Pages past
        ``ceil(length / page_size)`` leave the table, each dropping ONE
        refcount hold — only pages hitting zero return to the free
        list, so a truncation through a prefix-cache share or a page
        other sequences still read releases this sequence's hold and
        nothing else (never strands or frees a shared prefix).  Freed
        pages' int8 quantization scales clear with them (the audited
        freed-pages-carry-no-scale invariant); the kept tail page's
        surplus slots hold stale-but-finite content that the length
        masks and the next append overwrites — exactly the state a
        shorter sequence would be in.  Returns the number of pages
        actually freed.  `length` must not exceed the current token
        count (growth is append_tokens' job)."""
        with self._lock:
            h = self._tables[seq_id]
            n = int(length)
            if n < 0 or n > h.length:
                raise ValueError(
                    f"cannot truncate sequence {seq_id} from {h.length} "
                    f"to {n} tokens — length must shrink into [0, "
                    f"{h.length}]")
            if n == h.length:
                return 0
            if h.starts is None:
                keep = self.pages_needed(n, self.page_size)
            else:
                # evicted table: keep exactly the pages whose content
                # starts below the new length.  A rollback only ever
                # removes just-appended TAIL tokens, so the new length
                # must still land inside the kept tail page — shrinking
                # into a dropped interior gap has no page to hold it
                keep = sum(1 for st in h.starts if st < n)
                if n and (not keep or n > h.starts[keep - 1]
                          + self.page_size):
                    raise ValueError(
                        f"cannot truncate evicted sequence {seq_id} to "
                        f"{n} tokens — that position falls in a dropped "
                        "interior gap")
                h.starts = h.starts[:keep]
            dropped = h.pages[keep:]
            h.pages = h.pages[:keep]
            self._stats["tokens_truncated"] += h.length - n
            h.length = n
            freed: List[int] = []
            for p in reversed(dropped):
                self._ref[p] -= 1
                if self._ref[p] <= 0:
                    self._ref[p] = 0
                    self._free.append(p)
                    self._allocator.pop(p, None)
                    freed.append(p)
                elif self._allocator.get(p) == seq_id:
                    # readers (prefix cache, attached sequences) keep
                    # the page alive past its charging sequence's
                    # rollback: it is now UNCHARGED, like free_seq
                    del self._allocator[p]
            self._clear_scales(freed)
            self._stats["page_frees"] += len(freed)
        if freed:
            self._note_pool()
        return len(freed)

    def evict_interior(self, seq_id: int, window: int,
                       sinks: int = 0) -> int:
        """Sliding-window + attention-sink eviction (ISSUE 20): drop
        the pages a windowed decode can never attend again.  A page
        starting at token ``st`` is dropped iff it is past the sinks
        (``st >= sinks``) AND entirely outside every FUTURE query's
        window (``st + page_size <= length - window`` — window >= 1
        keeps the tail page, and the window's trailing edge only moves
        forward, so a page invisible now stays invisible).  The kept
        pages' token positions move into the handle's explicit
        ``starts`` list; the kernel's per-page start operand and the
        masked oracle read the SAME rule, which is what makes windowed
        decode token-identical to full attention under that mask.

        Refcount semantics match truncate_seq exactly: each dropped
        page RELEASES this sequence's one hold — a page the prefix
        cache pins or another sequence reads stays live (never freed
        out from under a reader), and a reader-kept page whose charge
        this sequence carried becomes uncharged.  Freed pages' int8
        scales clear with them.  Returns the number of pages dropped
        from THIS table (freed count lands in stats["page_frees"])."""
        window = int(window)
        sinks = int(sinks)
        if window < 1:
            raise ValueError(f"window must be >= 1 token, got {window}")
        if sinks < 0:
            raise ValueError(f"sinks must be >= 0 tokens, got {sinks}")
        with self._lock:
            h = self._tables[seq_id]
            starts = h.page_starts(self.page_size)
            keep = [i for i, st in enumerate(starts)
                    if st < sinks or st + self.page_size > h.length - window]
            if len(keep) == len(h.pages):
                return 0
            dropped = [h.pages[i] for i in range(len(h.pages))
                       if i not in set(keep)]
            h.starts = [starts[i] for i in keep]
            h.pages = [h.pages[i] for i in keep]
            freed: List[int] = []
            for p in reversed(dropped):
                self._ref[p] -= 1
                if self._ref[p] <= 0:
                    self._ref[p] = 0
                    self._free.append(p)
                    self._allocator.pop(p, None)
                    freed.append(p)
                elif self._allocator.get(p) == seq_id:
                    # a reader (prefix cache, attached sequence) keeps
                    # the dropped page alive: it is now UNCHARGED
                    del self._allocator[p]
            self._clear_scales(freed)
            self._stats["pages_evicted"] += len(dropped)
            self._stats["page_frees"] += len(freed)
        if freed:
            self._note_pool()
        return len(dropped)

    # -- cross-pool handoff (the disaggregation substrate) --------------

    def export_seq(self, seq_id: int, skip_tokens: int = 0,
                   adapter_id: Optional[str] = None) -> SeqExport:
        """Serialize one sequence's pages + lengths (+ int8 scales) into
        host buffers — the prefill→decode handoff payload
        (serving/fleet).  The source sequence is left UNTOUCHED (the
        caller frees it once the payload is safely handed off, so a
        dropped handoff costs a re-prefill, never corruption).
        ``adapter_id`` stamps the payload with the model variant its
        K/V was produced under (None = base model).

        ``skip_tokens`` (a multiple of page_size) leading tokens are
        omitted from the payload: the destination re-attaches that
        shared prefix from its OWN prefix cache (the caller reserved it
        there first), so only the unshared tail ships.  Works on the
        mesh pool too — indexing the sharded arrays gathers each
        device's head shard into the full host view.

        The D2H copy is staged OUTSIDE the pool lock (ISSUE 20
        satellite — the ROADMAP off-lock-spill note): under the lock
        the pages are pinned (one refcount hold each) and the
        IMMUTABLE jax array references snapshotted; the copy itself —
        the milliseconds-long part that used to serialize every
        concurrent ``append_tokens`` behind a kvtier park — then runs
        lock-free against the snapshot (functional updates by
        concurrent writers build NEW arrays, so the snapshot stays
        consistent), and the pins drop after.  The exported sequence
        itself must be quiescent (kvtier parks idle sessions; fleet
        hands off after prefill) — concurrent appends to OTHER
        sequences are exactly what the staging no longer blocks.

        A window/sink-evicted sequence (compacted table) exports with
        its page ``starts`` in the payload and requires skip_tokens=0
        (its leading pages are sinks, not a contiguous prefix)."""
        with self._lock:
            h = self._tables[seq_id]
            skip = int(skip_tokens)
            if skip % self.page_size or not 0 <= skip < h.length:
                raise ValueError(
                    f"skip_tokens {skip} must be a multiple of page_size "
                    f"{self.page_size} in [0, {h.length}) — the shipped "
                    "tail must start on a page boundary with >= 1 token")
            if h.starts is not None and skip:
                raise ValueError(
                    f"sequence {seq_id} is window-evicted — its resident "
                    "pages are not a contiguous prefix, export it whole "
                    "(skip_tokens=0)")
            ship = list(h.pages[skip // self.page_size:])
            starts = (list(h.starts[skip // self.page_size:])
                      if h.starts is not None else None)
            idx = np.asarray(ship, np.int32)
            length = h.length
            # pin the shipped pages, snapshot the immutable arrays
            for p in ship:
                self._ref[p] += 1
            k_src, v_src = self.k_pages, self.v_pages
            ks = vs = None
            if self.quantized:
                ks = self.k_scales[:, idx].copy()
                vs = self.v_scales[:, idx].copy()
        try:
            k, v = self._stage_d2h(k_src, v_src, idx)
        finally:
            self.release_pages(ship)
        with self._lock:
            self._stats["seqs_exported"] += 1
        return SeqExport(
            seq_id=seq_id, length=length, skip_tokens=skip,
            k=k, v=v, k_scales=ks, v_scales=vs,
            page_size=self.page_size, num_layers=self.num_layers,
            num_kv_heads=self.num_kv_heads, head_dim=self.head_dim,
            dtype=np.dtype(self.k_pages.dtype).name, pool=self.name,
            adapter_id=adapter_id, starts=starts)

    def _stage_d2h(self, k_src, v_src, idx: np.ndarray):
        """The export's device→host staging, OUTSIDE the pool lock and
        double-buffered: both device-side page gathers dispatch first
        (jax async dispatch — the second gather runs while the first
        drains to the host), each landing in its own host buffer.
        Split out so tests can instrument the off-lock window."""
        k_dev = k_src[:, :, idx]
        v_dev = v_src[:, :, idx]
        return np.asarray(k_dev), np.asarray(v_dev)

    def import_seq(self, export: SeqExport,
                   seq_id: int) -> Tuple[int, int]:
        """Materialize an exported sequence into THIS pool: claim pages
        for the shipped tail in ONE atomic ``append_tokens`` step (the
        admission charge — PagePoolExhausted fires before any table
        mutates, and pressure reclaimers run first, like every other
        claim) and write the payload's page content (+ int8 scales)
        into them.  ``seq_id`` must be freshly allocated and hold
        EXACTLY ``export.skip_tokens`` tokens of full attached pages —
        the shared prefix the destination re-attached from its own
        prefix cache before importing.  Returns (pages_claimed,
        tokens_imported)."""
        import jax.numpy as jnp

        for attr in ("page_size", "num_layers", "num_kv_heads",
                     "head_dim"):
            if getattr(export, attr) != getattr(self, attr):
                raise ValueError(
                    f"pool geometry mismatch on {attr}: payload from "
                    f"'{export.pool}' has {getattr(export, attr)}, pool "
                    f"'{self.name}' has {getattr(self, attr)}")
        if export.dtype != np.dtype(self.k_pages.dtype).name:
            raise ValueError(
                f"pool dtype mismatch: payload is {export.dtype}, pool "
                f"'{self.name}' is {np.dtype(self.k_pages.dtype).name}")
        with self._lock:
            h = self._tables[seq_id]
            if h.length != export.skip_tokens:
                raise ValueError(
                    f"sequence {seq_id} holds {h.length} tokens but the "
                    f"payload skips {export.skip_tokens} — re-attach "
                    "exactly the skipped shared prefix before importing")
            if h.length % self.page_size:
                raise ValueError(
                    "the re-attached prefix must be FULL pages — the "
                    "shipped tail starts on a page boundary")
            if export.starts is not None:
                # window-evicted payload: the shipped pages are NOT a
                # contiguous run, so the claim is manual (same atomic
                # shape as append_tokens: reclaimers, then exhaustion
                # check, then table mutation) and the start positions
                # travel with the table
                if export.skip_tokens or h.pages:
                    raise ValueError(
                        "an evicted payload imports whole into an empty "
                        "sequence — its pages are not a prefix to skip "
                        "into")
                tail = export.length
                want = export.k.shape[2]
                if len(export.starts) != want:
                    raise ValueError(
                        f"payload ships {want} pages but "
                        f"{len(export.starts)} start positions")
                if want > len(self._free):
                    for cb in self._reclaim_hooks:
                        if want <= len(self._free):
                            break
                        cb(want - len(self._free))
                if want > len(self._free):
                    raise PagePoolExhausted(
                        f"pool '{self.name}': need {want} fresh pages "
                        f"to import sequence {seq_id} but only "
                        f"{len(self._free)} free of {self.num_pages}")
                new = [self._free.pop() for _ in range(want)]
                for p in new:
                    self._ref[p] = 1
                    self._allocator[p] = h.seq_id
                h.pages = list(new)
                h.starts = list(export.starts)
                h.length = export.length
                self._stats["page_allocs"] += want
                self._stats["token_appends"] += tail
                used = self.num_pages - len(self._free)
                if used > self._stats["used_pages_high_water"]:
                    self._stats["used_pages_high_water"] = used
            else:
                tail = export.length - export.skip_tokens
                want = self.pages_needed(tail, self.page_size)
                if export.k.shape[2] != want:
                    raise ValueError(
                        f"payload ships {export.k.shape[2]} pages but "
                        f"{tail} tokens need {want}")
                before = len(h.pages)
                self.append_tokens([seq_id], [tail])  # atomic claim
                new = h.pages[before:]
            idx = np.asarray(new, np.int32)
            self.k_pages = self.k_pages.at[:, :, idx].set(
                jnp.asarray(export.k))
            self.v_pages = self.v_pages.at[:, :, idx].set(
                jnp.asarray(export.v))
            if self.quantized:
                self.k_scales[:, idx] = export.k_scales
                self.v_scales[:, idx] = export.v_scales
            self._stats["seqs_imported"] += 1
        self._note_pool()
        return len(new), tail

    # -- refcount / sharing API (the prefix-cache substrate) -----------

    def attach_prefix(self, seq_id: int, pages: Sequence[int],
                      length: int) -> None:
        """Attach already-written pages READ-ONLY to a sequence with an
        EMPTY page table: each page's refcount increments and the
        sequence starts at `length` tokens without touching the free
        list — the prefix-cache hit path.  `length` must land inside
        the last attached page (the pages exactly cover it)."""
        pages = [int(p) for p in pages]
        if length < 1 or not pages:
            raise ValueError("attach_prefix needs pages covering >= 1 token")
        cap = len(pages) * self.page_size
        if not cap - self.page_size < length <= cap:
            raise ValueError(
                f"length {length} does not land in the last of "
                f"{len(pages)} pages (page_size {self.page_size})")
        with self._lock:
            h = self._tables[seq_id]
            if h.pages or h.length:
                raise ValueError(
                    f"sequence {seq_id} already holds pages — prefixes "
                    "attach only at admission")
            for p in pages:
                if not 0 <= p < self.num_pages or self._ref[p] < 1:
                    raise ValueError(
                        f"page {p} is not live — cannot share a free or "
                        "out-of-range page")
            for p in pages:
                self._ref[p] += 1
            h.pages = list(pages)
            h.length = int(length)
            self._stats["shared_attach_pages"] += len(pages)
        self._note_pool()

    def retain_pages(self, pages: Sequence[int]) -> None:
        """Add one refcount hold per page (the prefix cache pinning a
        prompt's pages when an entry is inserted).  Pages must be live."""
        with self._lock:
            for p in pages:
                if not 0 <= int(p) < self.num_pages or self._ref[int(p)] < 1:
                    raise ValueError(f"page {p} is not live")
            for p in pages:
                self._ref[int(p)] += 1

    def release_pages(self, pages: Sequence[int],
                      scrub: bool = False) -> int:
        """Drop one refcount hold per page; pages hitting zero return
        to the free list.  With `scrub`, freed pages' K/V content is
        zeroed first — the poison-containment arm: masked attention
        multiplies a recycled page's unwritten slots by exactly-zero
        weights, and 0 * NaN is NaN, so non-finite garbage must never
        ride the free list.  Returns how many pages were freed."""
        with self._lock:
            n = 0
            freed: List[int] = []
            for p in pages:
                p = int(p)
                self._ref[p] -= 1
                if self._ref[p] <= 0:
                    self._ref[p] = 0
                    self._free.append(p)
                    self._allocator.pop(p, None)
                    freed.append(p)
                    n += 1
            if scrub and freed:
                self._scrub(freed)
            self._clear_scales(freed)
            self._stats["page_frees"] += n
        if n:
            self._note_pool()
        return n

    def _scrub(self, pages: Sequence[int]) -> None:
        """Zero the K/V content of `pages` — and their quantization
        scales, so a scrubbed page dequantizes to exactly zero (caller
        holds the lock)."""
        idx = np.asarray(pages, np.int32)
        self.k_pages = self.k_pages.at[:, :, idx].set(0)
        self.v_pages = self.v_pages.at[:, :, idx].set(0)
        self._clear_scales(pages)

    def scrub_seq_pages(self, seq_id: int) -> int:
        """Zero the content of a live sequence's EXCLUSIVELY-owned
        pages (refcount 1) — the quarantine path calls this before
        free_seq so a poisoned sequence's non-finite K/V cannot leak
        into later reuse through masked-weight propagation (0 * NaN).
        Shared pages are left alone: other readers still need them.
        Returns how many pages were scrubbed."""
        with self._lock:
            h = self._tables[seq_id]
            own = [p for p in h.pages if self._ref[p] == 1]
            if own:
                self._scrub(own)
            return len(own)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._ref[int(page)]

    def table_snapshot(self, seq_id: int) -> Tuple[List[int], int]:
        """(pages, length) copy of one sequence's table — the prefix
        cache reads it when inserting a finished prompt's pages."""
        with self._lock:
            h = self._tables[seq_id]
            return list(h.pages), h.length

    def uncharged_live_pages(self) -> int:
        """Distinct pages referenced by >= 1 live page table whose
        charging sequence has retired (attached shared prefixes whose
        allocator is gone).  No live admission charge covers them and
        they cannot be evicted under pressure while their readers
        live, so the admission controller sets exactly this many pages
        aside.  Ground truth from the pool's own allocator map — a
        prefix cache dropping an ENTRY (capacity cap, quarantine
        invalidation) cannot make an attached page invisible here."""
        with self._lock:
            table_pages = {p for h in self._tables.values()
                           for p in h.pages}
            return sum(1 for p in table_pages
                       if p not in self._allocator)

    def register_reclaimer(self, fn) -> None:
        """`fn(pages_short) -> freed` is called (under the pool lock)
        when an append cannot find enough free pages — the prefix
        cache's LRU eviction.  Hooks run before PagePoolExhausted."""
        self._reclaim_hooks.append(fn)

    def register_owner(self, fn) -> None:
        """`fn() -> Dict[page, holds]` explains refcounts that no page
        table covers (prefix-cache entry holds) to check_invariants."""
        self._owner_hooks.append(fn)

    def register_remap_hook(self, fn) -> None:
        """`fn(remap: Dict[old, new])` fires inside defrag() so external
        page holders (the prefix cache) follow the compaction."""
        self._remap_hooks.append(fn)

    def append_token(self, seq_ids: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Claim the next (page, slot) for one new token on every
        sequence; advances lengths.  Returns (pages [B], slots [B])
        int32 arrays for write_kv.  Raises PagePoolExhausted (before
        mutating ANY table) if the claim cannot be satisfied."""
        return self.append_tokens(seq_ids, [1] * len(seq_ids))

    def append_tokens(self, seq_ids: Sequence[int],
                      counts: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Claim (page, slot)s for counts[i] new tokens on sequence i in
        ONE atomic step — the batched-prefill path (a whole prompt's
        worth of slots per sequence, one pool transaction instead of one
        per token).  Returns (pages [T], slots [T]) int32 flattened in
        (sequence order, token order) — exactly the row order of
        k[b_idx, :, t_idx] at the write_kv call site.  Raises
        PagePoolExhausted before mutating ANY table."""
        counts = [int(c) for c in counts]
        if len(counts) != len(seq_ids) or any(c < 0 for c in counts):
            raise ValueError("counts must align with seq_ids and be >= 0")
        with self._lock:
            need = 0
            for s, c in zip(seq_ids, counts):
                h = self._tables[s]
                free_slots = h.tail_free_slots(self.page_size)
                if c > 0 and free_slots and self._ref[h.pages[-1]] > 1:
                    # shared partially-filled tail: the divergent append
                    # will copy-on-write it onto a fresh page
                    need += 1
                if c > free_slots:
                    need += self.pages_needed(c - free_slots, self.page_size)
            if need > len(self._free):
                # pressure: ask reclaimers (prefix-cache LRU eviction)
                # to release cache-only pages before giving up
                for cb in self._reclaim_hooks:
                    if need <= len(self._free):
                        break
                    cb(need - len(self._free))
            if need > len(self._free):
                raise PagePoolExhausted(
                    f"pool '{self.name}': need {need} fresh pages for "
                    f"{sum(counts)} appends but only {len(self._free)} "
                    f"free of {self.num_pages}")
            pages = np.empty(sum(counts), np.int32)
            slots = np.empty(sum(counts), np.int32)
            i = 0
            for s, c in zip(seq_ids, counts):
                h = self._tables[s]
                if (c > 0 and h.tail_free_slots(self.page_size)
                        and self._ref[h.pages[-1]] > 1):
                    self._cow_tail(h)
                for _ in range(c):
                    if h.tail_free_slots(self.page_size) == 0:
                        p = self._free.pop()
                        self._ref[p] = 1
                        self._allocator[p] = h.seq_id
                        h.pages.append(p)
                        if h.starts is not None:
                            # evicted table: the fresh tail page's
                            # content starts at the CURRENT length (a
                            # page multiple — the tail was full)
                            h.starts.append(h.length)
                        self._stats["page_allocs"] += 1
                    pages[i] = h.pages[-1]
                    slots[i] = h.length % self.page_size
                    h.length += 1
                    i += 1
            self._stats["token_appends"] += sum(counts)
            leak = _finject.serve_leak_pages()
            if leak:  # chaos: orphan pages (owned by nobody, not free)
                del self._free[-min(leak, len(self._free)):]
            used = self.num_pages - len(self._free)
            if used > self._stats["used_pages_high_water"]:
                self._stats["used_pages_high_water"] = used
        self._note_pool()
        return pages, slots

    def _cow_tail(self, h: SequenceHandle) -> None:
        """Copy-on-write the sequence's shared, partially-filled tail
        page: claim a fresh page, copy the shared page's K/V content
        (every layer, both arrays — one functional update each), drop
        one refcount on the original, and swap the table tail.  Called
        under the pool lock from append_tokens AFTER the atomic claim
        check counted the extra page."""
        old = h.pages[-1]
        new = self._free.pop()
        self._ref[new] = 1
        self._allocator[new] = h.seq_id
        self._ref[old] -= 1
        # device-side page copy: the page dim is unsharded on the mesh
        # pool, so the same functional update works per-shard there
        self.k_pages = self.k_pages.at[:, :, new].set(
            self.k_pages[:, :, old])
        self.v_pages = self.v_pages.at[:, :, new].set(
            self.v_pages[:, :, old])
        if self.quantized:
            # int8 content copies verbatim, so the scales travel with it
            self.k_scales[:, new] = self.k_scales[:, old]
            self.v_scales[:, new] = self.v_scales[:, old]
        h.pages[-1] = new
        self._stats["page_allocs"] += 1
        self._stats["cow_copies"] += 1

    def corrupt_page(self, page: int) -> None:
        """Chaos helper (FAULT_SERVE_PREFIX_CORRUPT): poison one page's
        K content with NaN — flipped exponent bytes surfacing as
        non-finite activations, the detectable face of silent page
        corruption.  K only: a NaN key is masked out (jnp.where) for
        sequences that do not read the page, while any sequence whose
        valid prefix includes it goes non-finite and quarantines.  An
        int8 page cannot encode non-finite content, so the poison lands
        on its K SCALE instead — dequantization spreads the NaN over
        the whole page, the same detectable face."""
        with self._lock:
            if self.quantized:
                self.k_scales[:, int(page)] = float("nan")
            else:
                self.k_pages = self.k_pages.at[:, :, int(page)].set(
                    float("nan"))

    def write_kv(self, layer: int, pages: np.ndarray, slots: np.ndarray,
                 k, v) -> None:
        """Write token K/V for `layer`: k/v [T, num_kv_heads, head_dim]
        into the claimed (page, slot)s (T = batch rows for one decode
        step, or a whole prompt batch's flattened tokens for prefill).
        (page, slot) pairs must be distinct — append_token/append_tokens
        guarantee it — EXCEPT that a pair may repeat when its rows are
        value-identical (a duplicate scatter of the same content is a
        no-op; verify_step pads its writes that way to keep scatter
        shapes compile-stable).  An int8 pool amax-quantizes on the way in (see
        the class docstring).  Locked like every other mutation: an
        unlocked read-modify-write of the arrays would race defrag()'s
        permutation and silently drop one side's update."""
        with self._lock:
            if self.quantized:
                self.k_pages = self._quantized_write(
                    self.k_pages, self.k_scales, layer, pages, slots, k)
                self.v_pages = self._quantized_write(
                    self.v_pages, self.v_scales, layer, pages, slots, v)
                return
            # non-contiguous advanced indices (slice over H between
            # them): the indexed view is [T, H, D] — k/v land as-is
            self.k_pages = self.k_pages.at[layer, :, pages, slots].set(k)
            self.v_pages = self.v_pages.at[layer, :, pages, slots].set(v)

    def _quantized_write(self, arr, scales, layer, pages, slots, x):
        """amax-quantize rows x [T, H_kv, D] into int8 page slots.  Per
        touched page the scale is the running amax / 127: a scale that
        GROWS re-quantizes that page's existing int8 content under the
        new scale (one functional update over the touched pages only —
        factor <= 1, and factor == 1 round-trips exactly), so every
        value in a page stays within half an int8 LSB of ITS page's
        largest magnitude.  Caller holds the lock."""
        import jax.numpy as jnp

        xh = np.asarray(x, np.float32)
        row_amax = np.max(np.abs(xh), axis=(1, 2)) if xh.size else \
            np.zeros((0,), np.float32)
        upages, inv = np.unique(pages, return_inverse=True)
        page_amax = np.zeros(len(upages), np.float32)
        with np.errstate(invalid="ignore"):
            # a poisoned sequence writes NaN rows: the NaN propagates
            # into that page's scale (kept — the quarantine path scrubs
            # the page) without warning-spamming healthy batch-mates
            np.maximum.at(page_amax, inv, row_amax)
            old_scale = scales[layer, upages]
            new_scale = np.maximum(old_scale, page_amax / 127.0)
        grow = new_scale > old_scale
        requant = grow & (old_scale > 0)
        if np.any(requant):
            idx = upages[requant].astype(np.int32)
            factor = (old_scale[requant] / new_scale[requant]).astype(
                np.float32)
            # [layer, :, idx] puts the advanced page index FIRST:
            # the touched-page block is [U, H_kv, page_size, D]
            block = arr[layer, :, idx].astype(jnp.float32)
            arr = arr.at[layer, :, idx].set(
                jnp.clip(jnp.round(block * factor[:, None, None, None]),
                         -127, 127).astype(jnp.int8))
        row_scale = new_scale[inv]
        safe = np.where(row_scale > 0, row_scale, 1.0).astype(np.float32)
        q = jnp.clip(jnp.round(jnp.asarray(xh) / safe[:, None, None]),
                     -127, 127).astype(jnp.int8)
        scales[layer, upages] = new_scale
        return arr.at[layer, :, pages, slots].set(q)

    # -- read side ------------------------------------------------------

    def page_table_batch(self, seq_ids: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Batch view for attention: (tables [B, max_pages] int32 padded
        with page 0 — the ragged k_lengths mask hides the tail — and
        lengths [B] int32 valid token counts)."""
        with self._lock:
            handles = [self._tables[s] for s in seq_ids]
            maxp = max((len(h.pages) for h in handles), default=1) or 1
            tables = np.zeros((len(handles), maxp), np.int32)
            lengths = np.empty(len(handles), np.int32)
            for i, h in enumerate(handles):
                tables[i, :len(h.pages)] = h.pages
                lengths[i] = h.length
        return tables, lengths

    def page_tables_with_starts(self, seq_ids: Sequence[int]):
        """Batch view for WINDOWED attention (ISSUE 20): like
        page_table_batch plus a [B, max_pages] int32 array of each
        page's token start position — PAD_START in the padded tail, so
        the kernel's position mask (pos >= length) hides pad slots even
        when an evicted table's real pages no longer sit at implicit
        i*page_size positions.  Returns (tables, starts, lengths)."""
        from ..kernels.paged_attention import PAD_START

        with self._lock:
            handles = [self._tables[s] for s in seq_ids]
            maxp = max((len(h.pages) for h in handles), default=1) or 1
            tables = np.zeros((len(handles), maxp), np.int32)
            starts = np.full((len(handles), maxp), PAD_START, np.int32)
            lengths = np.empty(len(handles), np.int32)
            for i, h in enumerate(handles):
                n = len(h.pages)
                tables[i, :n] = h.pages
                starts[i, :n] = h.page_starts(self.page_size)
                lengths[i] = h.length
        return tables, starts, lengths

    def two_level_tables(self, seq_ids: Sequence[int], block_size: int):
        """Batch view as a TWO-LEVEL page table (ISSUE 20 tentpole):
        the kernel's scalar-prefetch operand becomes a compact [B,
        ceil(max_pages/block_size)] L1 directory over [n_blocks,
        block_size] L2 page-id and start-position blocks, so SMEM
        grows with LIVE table blocks instead of B * max_pages — the
        difference between a ~1k-page long-context batch fitting the
        scalar core's memory and not.  Block 0 is the shared pad block
        (page 0, starts PAD_START); every L1 row pads with it, so a
        short sequence prices one directory row, not a full-width
        table row.  Returns (TwoLevelTables, lengths [B])."""
        from ..kernels.paged_attention import PAD_START, TwoLevelTables

        bs = int(block_size)
        if bs < 1:
            raise ValueError(f"block_size must be >= 1, got {bs}")
        with self._lock:
            handles = [self._tables[s] for s in seq_ids]
            maxp = max((len(h.pages) for h in handles), default=1) or 1
            n_l1 = self.pages_needed(maxp, bs)
            l2_blocks = [np.zeros(bs, np.int32)]  # shared pad block
            st_blocks = [np.full(bs, PAD_START, np.int32)]
            l1 = np.zeros((len(handles), n_l1), np.int32)
            lengths = np.empty(len(handles), np.int32)
            for i, h in enumerate(handles):
                sts = h.page_starts(self.page_size)
                for j in range(self.pages_needed(len(h.pages), bs)):
                    chunk = h.pages[j * bs:(j + 1) * bs]
                    l2b = np.zeros(bs, np.int32)
                    stb = np.full(bs, PAD_START, np.int32)
                    l2b[:len(chunk)] = chunk
                    stb[:len(chunk)] = sts[j * bs:(j + 1) * bs]
                    l1[i, j] = len(l2_blocks)
                    l2_blocks.append(l2b)
                    st_blocks.append(stb)
                lengths[i] = h.length
        return TwoLevelTables(
            l1=l1, l2=np.stack(l2_blocks), starts=np.stack(st_blocks),
            block_size=bs), lengths

    def length(self, seq_id: int) -> int:
        with self._lock:
            return self._tables[seq_id].length

    def max_live_pages(self) -> int:
        """Longest live sequence's page count (0 when idle) — the width
        of the decode attention batch's page table."""
        with self._lock:
            return max((len(h.pages) for h in self._tables.values()),
                       default=0)

    # -- accounting -----------------------------------------------------

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_pages(self) -> int:
        with self._lock:
            return self.num_pages - len(self._free)

    def utilization(self) -> float:
        return self.used_pages / float(self.num_pages)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            live = {s: h.length for s, h in self._tables.items()}
            return dict(self._stats,
                        used_pages=self.num_pages - len(self._free),
                        free_pages=len(self._free),
                        num_pages=self.num_pages,
                        live_sequences=len(live))

    def _note_pool(self) -> None:
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_page_pool(
                self.used_pages, self.num_pages, pool=self.name)

    # -- integrity watchdog ---------------------------------------------

    def _true_refs(self) -> List[int]:
        """Ground-truth per-page ownership: table occurrences plus the
        registered external owners' holds (prefix-cache entries).
        Callers hold the pool lock."""
        refs = [0] * self.num_pages
        for h in self._tables.values():
            for p in h.pages:
                if 0 <= p < self.num_pages:
                    refs[p] += 1
        for fn in self._owner_hooks:
            for p, holds in fn().items():
                if 0 <= int(p) < self.num_pages:
                    refs[int(p)] += int(holds)
        return refs

    def check_invariants(self) -> Dict:
        """Audit page ownership AGAINST REFCOUNTS: every page id must be
        either free (refcount 0, exactly once on the free list) or live
        with a refcount equal to its table occurrences plus registered
        external holds (prefix-cache entries) — a page legitimately
        shared by N sequences and the cache is N+1-owned and FINE, not
        "double-owned" corruption.  Returns a report dict — `ok` plus
        the violating page/sequence ids:

        - orphaned_pages: held by no table and no external owner yet not
          free (a leak — the pool shrinks until exhaustion;
          reclaim_orphans repairs)
        - double_owned_pages: more owners than the refcount covers (two
          tables claiming an unshared page, a duplicate within one
          table, or a table AND the free list — two sequences would
          overwrite each other's K/V)
        - refcount_mismatches: refcount disagrees with the audited
          ownership in either direction (stale hold or lost hold)
        - free_list_errors: duplicate or out-of-range free entries
        - length_mismatches: sequences whose token count disagrees with
          their page count (length > capacity, or an entire spare page)
        - scale_errors (int8 pools): a LIVE written page whose K or V
          scale entries are INCONSISTENT across layers — some layers
          carry one, others lost theirs, so part of the content would
          dequantize garbage-as-zero (all-zero is legitimate: a
          scrub_seq_pages'd live page holds zeros that dequantize to
          exactly zero) — or a FREED page still carrying any entry (a
          stale scale would survive onto the next owner) — always []
          for unquantized pools

        Cost is O(pages + live tokens/page_size) under the pool lock —
        cheap enough for the continuous-batching loop to run every N
        steps (ContinuousBatchingLoop(check_every=N))."""
        with self._lock:
            true_refs = self._true_refs()
            double: List[int] = []
            mismatches: List[int] = []
            ref_bad: List[int] = []
            for h in self._tables.values():
                seen_in_table: set = set()
                for p in h.pages:
                    if p in seen_in_table:
                        double.append(p)
                    seen_in_table.add(p)
                if h.starts is None:
                    cap = h.capacity(self.page_size)
                    if h.length > cap or cap - h.length >= self.page_size:
                        mismatches.append(h.seq_id)
                else:
                    # window-evicted table: one start per page, each a
                    # page multiple, strictly increasing, and the TAIL
                    # page must be the one covering the current length
                    # (eviction never drops the tail — the window's >= 1
                    # newest token always lives there)
                    st = h.starts
                    ps = self.page_size
                    if not st:
                        if h.length or h.pages:
                            mismatches.append(h.seq_id)
                    elif not (
                            len(st) == len(h.pages)
                            and all(s % ps == 0 for s in st)
                            and all(a < b for a, b in zip(st, st[1:]))
                            and st[-1] < h.length <= st[-1] + ps
                            and st[-1] == (self.pages_needed(
                                h.length, ps) - 1) * ps):
                        mismatches.append(h.seq_id)
            free_errors: List[int] = []
            seen_free: set = set()
            for p in self._free:
                if p in seen_free or not 0 <= p < self.num_pages:
                    free_errors.append(p)
                    continue
                seen_free.add(p)
                if true_refs[p] > 0:
                    double.append(p)  # free AND owned: corruption
            orphaned: List[int] = []
            for p in range(self.num_pages):
                if true_refs[p] == 0 and p not in seen_free:
                    orphaned.append(p)
                if self._ref[p] != true_refs[p]:
                    ref_bad.append(p)
                    if true_refs[p] > self._ref[p]:
                        # more owners than the refcount covers: a free
                        # would return a still-referenced page
                        double.append(p)
            scale_bad: List[int] = []
            if self.quantized:
                # pages whose content was actually written: table pages
                # the sequence's length covers, plus every externally
                # held page (cache entries only ever pin written pages)
                written: set = set()
                for h in self._tables.values():
                    covered = self.pages_needed(h.length, self.page_size)
                    written.update(h.pages[:covered])
                for fn in self._owner_hooks:
                    written.update(int(p) for p in fn())
                k_has = np.all(self.k_scales != 0, axis=0)  # [P]
                v_has = np.all(self.v_scales != 0, axis=0)
                k_none = np.all(self.k_scales == 0, axis=0)
                v_none = np.all(self.v_scales == 0, axis=0)
                for p in range(self.num_pages):
                    if true_refs[p] == 0:
                        if not (k_none[p] and v_none[p]):
                            scale_bad.append(p)  # freed but scaled
                    elif p in written and not (
                            (k_has[p] or k_none[p])
                            and (v_has[p] or v_none[p])):
                        # live written, entries LOST in some layers but
                        # not others (all-zero = scrubbed, legitimate)
                        scale_bad.append(p)
            report = {
                "ok": not (orphaned or double or free_errors
                           or mismatches or ref_bad or scale_bad),
                "orphaned_pages": orphaned,
                "double_owned_pages": sorted(set(double)),
                "refcount_mismatches": sorted(set(ref_bad)),
                "free_list_errors": free_errors,
                "length_mismatches": mismatches,
                "scale_errors": sorted(set(scale_bad)),
                "used_pages": self.num_pages - len(self._free),
                "shared_pages": sum(1 for r in true_refs if r > 1),
                "live_sequences": len(self._tables),
            }
        if _flags._VALUES["FLAGS_observability"] and not report["ok"]:
            _smetrics.record_pool_invariant_violation(pool=self.name)
        return report

    def reclaim_orphans(self) -> int:
        """Return every orphaned page (no table occurrence, no external
        hold, absent from the free list) to the free pool and re-true
        every refcount to the audited ownership; returns how many pages
        were reclaimed.  The repair arm of check_invariants — a detected
        leak costs pages until this runs, never the pool's integrity
        (page tables are untouched), and the repair is refcount-correct:
        a page still shared by live sequences or the prefix cache is
        never freed, its refcount is only re-trued."""
        with self._lock:
            true_refs = self._true_refs()
            free = set(self._free)
            orphans = [p for p in range(self.num_pages)
                       if true_refs[p] == 0 and p not in free]
            self._free.extend(reversed(orphans))
            self._ref = true_refs
            for p in orphans:
                self._allocator.pop(p, None)
            # a reclaimed page re-enters the free list scale-less (and
            # any freed page whose stale scale slipped through is
            # re-trued the same way the refcounts are)
            if self.quantized:
                self._clear_scales(
                    [p for p in range(self.num_pages)
                     if true_refs[p] == 0])
            self._stats["orphans_reclaimed"] += len(orphans)
        if orphans:
            self._note_pool()
        return len(orphans)

    # -- defrag ---------------------------------------------------------

    def defrag(self) -> int:
        """Compact used pages to the lowest indices (one permutation
        gather per K/V array) and rebuild the free list as the dense
        tail.  Page-granular allocation never NEEDS this for correctness
        — any free page serves any sequence, and the Pallas page reader
        follows the page table wherever it points — but a compacted pool
        lets an operator shrink `num_pages` between runs.  Returns the
        number of pages moved."""
        with self._lock:
            # live = any page with a refcount (tables AND cache-held
            # pages move together; a shared page moves ONCE)
            used = sorted(p for p in range(self.num_pages)
                          if self._ref[p] > 0)
            remap = {old: new for new, old in enumerate(used)}
            moves = sum(1 for old, new in remap.items() if old != new)
            if moves:
                perm = np.arange(self.num_pages, dtype=np.int32)
                for old, new in remap.items():
                    perm[new] = old
                # unused tail keeps a stable order: remaining page ids
                leftover = [p for p in range(self.num_pages)
                            if p not in remap]
                perm[len(remap):] = leftover
                self.k_pages = self.k_pages[:, :, perm]
                self.v_pages = self.v_pages[:, :, perm]
                if self.quantized:
                    # scales follow their pages through the compaction
                    self.k_scales = self.k_scales[:, perm]
                    self.v_scales = self.v_scales[:, perm]
                new_ref = [0] * self.num_pages
                for old, new in remap.items():
                    new_ref[new] = self._ref[old]
                self._ref = new_ref
                self._allocator = {remap[p]: s for p, s
                                   in self._allocator.items()
                                   if p in remap}
                for h in self._tables.values():
                    h.pages = [remap[p] for p in h.pages]
                for fn in self._remap_hooks:
                    fn(remap)
            self._free = list(range(self.num_pages - 1, len(remap) - 1, -1))
            self._stats["defrag_moves"] += moves
        return moves
