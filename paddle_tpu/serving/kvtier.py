"""Tiered KV cache: park idle sessions' KV pages in host RAM.

One chip's HBM caps concurrent sessions — every idle multi-turn
conversation holds its KV pages hostage between turns.  This module
adds the host tier that removes that bound:

- :class:`HostKVTier` — a checksummed parking lot for
  :class:`~paddle_tpu.serving.kvcache.SeqExport` payloads in host
  buffers, with byte-capacity accounting, LRU order, and its own
  ``check_invariants`` (a parked payload must still match the CRC it
  parked with — a corrupted or lost payload is a typed rejection at
  resume time, never imported garbage).
- :class:`TieredSessionManager` — decides WHEN.  Sessions retire
  RESIDENT (their pool pages stay live between turns); an LRU/idle
  victim policy spills them (``export_seq`` → park → ``free_seq``,
  pages freed only after the park lands — the fleet collector's ack
  discipline) either asynchronously on a spill-writer thread
  (overlapped with decode) or inline under pool-pressure via the
  pool's reclaimer hook.  A resume re-attaches the spill-time
  prefix-cache match (pinned across the park exactly like a fleet
  ``PrefixReservation``) and imports only the unshared tail through
  the atomic ``append_tokens`` claim.
- :class:`TierSession` — the per-conversation carrier a caller puts on
  ``DecodeRequest.session``; the decode loop's admission consults the
  manager through it.

Lock discipline mirrors :mod:`~paddle_tpu.serving.prefixcache`: the
manager shares the POOL's RLock (so the pressure reclaimer, which runs
inside ``append_tokens``' critical section, can spill inline on the
same thread), and the tier keeps a private host-side lock that never
takes the pool lock — pool→tier is the only acquisition order.

Sizing math (README "Tiered KV cache"): the admission controller
reserves against the COMBINED tier.  HBM admits
``reserved_pages + need <= num_pages - locked`` where ``locked`` sets
aside idle-resident sessions' pages and live attached pages no charge
covers; when the bound fails, ``make_room`` moves idle sessions to the
host tier — so session capacity is
``num_pages + host_capacity_bytes / pool.bytes_per_page()`` pages,
while ACTIVE decode is still bounded by HBM alone.  An admitted resume
charged ``ceil((prompt+max_new - pinned_full)/page_size)`` pages can
therefore never die mid-decode.

Chaos: ``FAULT_SERVE_SPILL_CORRUPT`` poisons a payload after its CRC
is recorded (resume sees :class:`SpillCorruptError` and re-prefills);
``FAULT_SERVE_SPILL_DROP`` loses one parked payload at fetch
(:class:`SpillMissingError`, same re-prefill fallback).
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from ..observability import flight as _flight
from ..resilience import faultinject as _finject
from . import metrics as _smetrics
from .adapters import AdapterMismatchError
from .kvcache import KVCachePool, SeqExport

_log = logging.getLogger("paddle_tpu.serving.kvtier")

__all__ = [
    "HostKVTier",
    "HostTierFullError",
    "SpillCorruptError",
    "SpillMissingError",
    "TierSession",
    "TieredSessionManager",
]


class HostTierFullError(RuntimeError):
    """The host tier cannot hold this payload within its byte capacity
    — the manager evicts LRU parked sessions and retries, and an
    eviction's session falls back to a fresh prefill at resume."""


class SpillCorruptError(RuntimeError):
    """A parked payload failed its CRC at fetch — the resume must
    reject it typed (never import garbage) and re-prefill."""


class SpillMissingError(RuntimeError):
    """The parked payload is gone (chaos drop or an eviction raced the
    resume) — the resume falls back to a fresh prefill."""


class _Parked:
    __slots__ = ("key", "export", "crc", "nbytes")

    def __init__(self, key, export: SeqExport, crc: int, nbytes: int):
        self.key = key
        self.export = export
        self.crc = crc
        self.nbytes = nbytes


class HostKVTier:
    """Pinned host buffers for exported sequences, CRC-verified.

    ``capacity_bytes=0`` means unbounded (tests and single-tenant
    tools); a bounded tier raises :class:`HostTierFullError` at park
    and the manager decides who to evict.  Entries keep insertion
    order = LRU order (a parked session is touched exactly twice:
    park and fetch)."""

    def __init__(self, capacity_bytes: int = 0, name: str = "host"):
        if capacity_bytes < 0:
            raise ValueError("capacity_bytes must be >= 0 (0 = unbounded)")
        self.capacity_bytes = int(capacity_bytes)
        self.name = name
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[object, _Parked]" = \
            collections.OrderedDict()
        self.bytes_used = 0
        self._stats = {
            "parks": 0, "fetches": 0, "discards": 0,
            "corrupt_rejected": 0, "lost": 0,
            "bytes_parked_total": 0, "bytes_fetched_total": 0,
            "bytes_high_water": 0,
        }

    # -- capacity -------------------------------------------------------

    def free_bytes(self) -> int:
        if not self.capacity_bytes:
            return 1 << 62  # unbounded
        with self._lock:
            return max(0, self.capacity_bytes - self.bytes_used)

    def utilization(self) -> float:
        if not self.capacity_bytes:
            return 0.0
        with self._lock:
            return self.bytes_used / float(self.capacity_bytes)

    # -- park / fetch / discard ----------------------------------------

    def park(self, key, export: SeqExport) -> int:
        """Take ownership of `export` under `key`; returns its bytes.
        The CRC is recorded BEFORE the chaos hook runs, so a poisoned
        payload is detectable at fetch — the never-import-garbage bar."""
        n = export.nbytes()
        with self._lock:
            if key in self._entries:
                raise ValueError(f"key {key!r} is already parked")
            if self.capacity_bytes \
                    and self.bytes_used + n > self.capacity_bytes:
                raise HostTierFullError(
                    f"host tier '{self.name}' holds {self.bytes_used} of "
                    f"{self.capacity_bytes} bytes; payload needs {n}")
            crc = export.checksum()
            if _finject.serve_spill_corrupt():
                # chaos: silent host-memory corruption after the park —
                # flip one byte of the payload body so the fetch-side
                # CRC verify must catch it (exports of a jax-backed
                # pool are read-only views, hence the copy)
                bad = export.k.copy()
                bad.reshape(-1).view(np.uint8)[0] ^= 0xFF
                export.k = bad
            self._entries[key] = _Parked(key, export, crc, n)
            self.bytes_used += n
            self._stats["parks"] += 1
            self._stats["bytes_parked_total"] += n
            self._stats["bytes_high_water"] = max(
                self._stats["bytes_high_water"], self.bytes_used)
        return n

    def fetch(self, key) -> SeqExport:
        """Unpark: the entry leaves the tier whether or not the payload
        verifies — a rejected payload must not be retried into a
        session forever.  Raises typed on loss or corruption."""
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self.bytes_used -= e.nbytes
            if e is None:
                self._stats["lost"] += 1
            elif _finject.serve_spill_drop():
                self._stats["lost"] += 1
                e = None
        if e is None:
            raise SpillMissingError(
                f"no parked payload under key {key!r} in host tier "
                f"'{self.name}' (evicted, dropped, or never parked)")
        if e.export.checksum() != e.crc:
            with self._lock:
                self._stats["corrupt_rejected"] += 1
            raise SpillCorruptError(
                f"parked payload {key!r} failed its CRC — rejecting "
                "instead of importing garbage")
        with self._lock:
            self._stats["fetches"] += 1
            self._stats["bytes_fetched_total"] += e.nbytes
        return e.export

    def discard(self, key) -> int:
        """Drop a parked payload (eviction / session close); returns
        the bytes freed (0 when the key was not parked)."""
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                return 0
            self.bytes_used -= e.nbytes
            self._stats["discards"] += 1
            return e.nbytes

    def lru_key(self):
        """Oldest parked key (eviction candidate), or None."""
        with self._lock:
            return next(iter(self._entries), None)

    def keys(self) -> List:
        with self._lock:
            return list(self._entries)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> int:
        with self._lock:
            freed = self.bytes_used
            self._entries.clear()
            self.bytes_used = 0
            return freed

    # -- audit ----------------------------------------------------------

    def check_invariants(self) -> Dict:
        """Tier-side audit: byte accounting must match the entries, and
        every parked payload must still verify against its park-time
        CRC (a parked page is owned and INTACT, not orphaned)."""
        with self._lock:
            errors: List[str] = []
            total = sum(e.nbytes for e in self._entries.values())
            if total != self.bytes_used:
                errors.append(
                    f"bytes_used {self.bytes_used} != sum of entries "
                    f"{total}")
            for key, e in self._entries.items():
                if e.export.checksum() != e.crc:
                    errors.append(f"entry {key!r} fails its CRC")
            return {"ok": not errors, "entries": len(self._entries),
                    "bytes_used": self.bytes_used, "errors": errors}

    def stats(self) -> Dict:
        with self._lock:
            st = dict(self._stats)
            st["entries"] = len(self._entries)
            st["bytes_used"] = self.bytes_used
            st["capacity_bytes"] = self.capacity_bytes
            return st


# session lifecycle: fresh -> active -> idle -> (spilling -> parked ->
# resuming -> active)* -> closed; quarantine resets any state to fresh
_SPILLABLE = ("idle",)


class TierSession:
    """One multi-turn conversation's KV residency state.  Created by
    :meth:`TieredSessionManager.open_session` and carried on
    ``DecodeRequest.session``; all transitions run inside the manager
    (under the pool lock)."""

    __slots__ = ("manager", "session_id", "state", "seq_id", "history",
                 "pinned_keys", "pinned_pages", "pinned_tokens",
                 "parked_bytes", "last_used", "last_trace_id",
                 "last_freed", "spills", "resumes", "adapter_id",
                 "_spilled_ev")

    def __init__(self, manager: "TieredSessionManager", session_id: int):
        self.manager = manager
        self.session_id = session_id
        self.state = "fresh"
        self.seq_id: Optional[int] = None
        # tokens whose K/V the session retains (pool-resident or
        # parked) — the strict prefix the next turn's prompt must carry
        self.history: List[int] = []
        # spill-time prefix-cache match, refcount-pinned across the
        # park so resume can always re-attach (the PrefixReservation
        # idiom) — export ships only the tail past pinned_tokens
        self.pinned_keys: List[str] = []
        self.pinned_pages: List[int] = []
        self.pinned_tokens = 0
        self.parked_bytes = 0
        self.last_used = 0
        self.last_trace_id: Optional[str] = None
        self.last_freed = 0
        self.spills = 0
        self.resumes = 0
        # model variant the retained K/V was produced under (ISSUE 19):
        # None = base model.  LoRA on QKV changes K/V content, so a
        # resume under a DIFFERENT adapter must reset, never reuse.
        self.adapter_id: Optional[str] = None
        self._spilled_ev = threading.Event()

    def resumable(self) -> bool:
        return self.state in ("idle", "spilling", "parked")

    def tokens_retained(self) -> int:
        return len(self.history)

    def close(self) -> None:
        self.manager.close_session(self)

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return (f"TierSession(id={self.session_id}, state={self.state}, "
                f"seq={self.seq_id}, tokens={len(self.history)})")


class _ResumePlan:
    """Admission-time resume decision, held while the loop checks its
    reservation bound.  Planning CASes the session to ``resuming`` so
    the spill writer / pressure reclaimer cannot steal it between the
    plan and the acquire; an admission that breaks instead calls
    :meth:`TieredSessionManager.abort_resume`."""

    __slots__ = ("session", "kind", "present", "charge_matched")

    def __init__(self, session: TierSession, kind: str, present: int,
                 charge_matched: int):
        self.session = session
        self.kind = kind                    # "resident" | "parked"
        self.present = present              # KV tokens after acquire
        self.charge_matched = charge_matched  # footprint discount


class TieredSessionManager:
    """Decides when sessions spill to the host tier and how they come
    back.  Wire it to the pool (and the pool's prefix cache) and hand
    it to the decode loop::

        pool = KVCachePool(...)
        cache = PrefixCache(pool)
        mgr = TieredSessionManager(pool, prefix_cache=cache,
                                   host_bytes=1 << 30)
        loop = ContinuousBatchingLoop(params, cfg, pool,
                                      prefix_cache=cache,
                                      session_manager=mgr)
        sess = mgr.open_session()
        loop.run([DecodeRequest(prompt, n, session=sess)])

    The constructor registers the manager as the pool's pressure
    reclaimer (idle sessions spill INLINE when ``append_tokens`` runs
    short — the fleet's queue-depth pressure arrives through exactly
    this hook), external owner (a parked session's pinned prefix pages
    are owned, not orphaned, to ``check_invariants``), and defrag
    remap listener."""

    def __init__(self, pool: KVCachePool, prefix_cache=None,
                 host_bytes: int = 0, tier: Optional[HostKVTier] = None,
                 spill_after_s: float = 0.0, name: str = "kvtier"):
        if prefix_cache is not None and prefix_cache.pool is not pool:
            raise ValueError(
                "prefix_cache is wired to a different pool — the "
                "spill-time match must pin pages in the pool sessions "
                "spill from")
        self.pool = pool
        self.cache = prefix_cache
        self.tier = tier if tier is not None else HostKVTier(host_bytes)
        self.name = name
        # idle-age threshold for spill_idle() (0 = any idle session)
        self.spill_after_s = float(spill_after_s)
        self._lock = pool._lock  # ONE lock: see module docstring
        self._sessions: Dict[int, TierSession] = {}
        self._next_session = 0
        # page -> transfer holds this manager has taken (spill-time
        # pins, live from retain to resume-attach/discard) — the owner
        # hook's ground truth, covering the mid-spill window too
        self._pin_holds: Dict[int, int] = {}
        self._stats = {
            "spills": 0, "resumes": 0, "resumed_resident": 0,
            "resumed_host": 0, "re_prefills": 0, "evictions": 0,
            "mismatch_resets": 0, "adapter_mismatch_resets": 0,
            "pressure_spills": 0, "spill_aborts": 0,
        }
        self._closing = False
        pool.register_reclaimer(self._reclaim)
        pool.register_owner(self._holds)
        pool.register_remap_hook(self._remap)
        self._spill_q: "queue.Queue[Optional[TierSession]]" = queue.Queue()
        self._writer = threading.Thread(
            target=self._spill_loop, daemon=True,
            name=f"{name}-spill-writer")
        self._writer.start()

    # -- session lifecycle ---------------------------------------------

    def open_session(self) -> TierSession:
        with self._lock:
            if self._closing:
                raise RuntimeError(f"manager {self.name} is closed")
            sid = self._next_session
            self._next_session += 1
            s = TierSession(self, sid)
            self._sessions[sid] = s
            return s

    def close_session(self, s: TierSession) -> None:
        """Release everything the session holds in either tier."""
        with self._lock:
            in_flight = s.state == "spilling"
        if in_flight:
            s._spilled_ev.wait(10.0)  # let the writer land its park
        with self._lock:
            if s.state == "idle" and s.seq_id is not None:
                self.pool.free_seq(s.seq_id)
            if s.state == "parked":
                self.tier.discard(s.session_id)
                self._unpin(s)
            s.state = "closed"
            s.seq_id = None
            s.history = []
            self._sessions.pop(s.session_id, None)

    def close(self) -> None:
        """Drain the writer and release every session — after this,
        zero pages in the pool and zero bytes in the tier belong to
        sessions (the leak bar both tiers are audited against)."""
        with self._lock:
            self._closing = True
            sessions = list(self._sessions.values())
        self._spill_q.put(None)
        self._writer.join(timeout=10.0)
        for s in sessions:
            self.close_session(s)

    # -- the decode loop's admission surface ---------------------------

    def plan_resume(self, s: TierSession, prompt: Sequence[int],
                    adapter_id: Optional[str] = None
                    ) -> Optional[_ResumePlan]:
        """Admission probe: can this request resume `s`?  Returns a
        plan (session CASed to ``resuming``) or None for the fresh
        path.  A diverged history resets the session (its retained KV
        is useless for this prompt), and so does a DIFFERENT adapter
        id: the retained K/V was produced under the session's variant
        and is content-wrong for any other — the typed
        ``adapter_mismatch`` reset, never a silent wrong-variant
        decode (ISSUE 19)."""
        while True:
            with self._lock:
                if s.manager is not self:
                    raise ValueError("session belongs to another manager")
                st = s.state
                if st == "idle":
                    if adapter_id != s.adapter_id:
                        self._reset_resident(s, why="adapter_mismatch")
                        return None
                    c = self._common_prefix(s.history, prompt)
                    if c <= 0:
                        self._reset_resident(s, why="mismatch")
                        return None
                    s.state = "resuming"
                    return _ResumePlan(s, "resident", present=c,
                                      charge_matched=0)
                if st == "parked":
                    if adapter_id != s.adapter_id:
                        self._discard_parked(s, why="adapter_mismatch")
                        return None
                    kv = len(s.history)
                    if kv > len(prompt) - 1 \
                            or list(prompt[:kv]) != s.history:
                        self._discard_parked(s, why="mismatch")
                        return None
                    s.state = "resuming"
                    return _ResumePlan(s, "parked", present=kv,
                                      charge_matched=s.pinned_tokens)
                if st != "spilling":
                    return None  # fresh/active/closed: normal path
                ev = s._spilled_ev
            # a spill is in flight on the writer — wait for it to land
            # (pages freed + payload parked), then re-plan as parked
            if not ev.wait(10.0):
                return None

    def abort_resume(self, plan: _ResumePlan) -> None:
        """The admission bound broke after planning: put the session
        back where the plan found it."""
        with self._lock:
            if plan.session.state == "resuming":
                plan.session.state = (
                    "idle" if plan.kind == "resident" else "parked")

    def resume(self, plan: _ResumePlan, seq_id: int,
               trace_id: Optional[str] = None) -> int:
        """Acquire the planned KV for `seq_id`; returns the tokens now
        present (``a.pos`` starts there).  Resident: the session's own
        table continues (truncated when the new prompt diverges inside
        it).  Parked: re-attach the pinned prefix, then import the
        parked tail — a corrupt/lost payload degrades to the pinned
        prefix alone (typed, counted, re-prefilled), never garbage."""
        s = plan.session
        obs_on = _flags._VALUES["FLAGS_observability"]
        if plan.kind == "resident":
            with self._lock:
                if s.seq_id != seq_id:
                    raise ValueError(
                        f"resident resume must reuse seq {s.seq_id}, "
                        f"got {seq_id}")
                if plan.present < self.pool.length(seq_id):
                    self.pool.truncate_seq(seq_id, plan.present)
                s.history = s.history[:plan.present]
                s.state = "active"
                s.resumes += 1
                self._stats["resumes"] += 1
                self._stats["resumed_resident"] += 1
            if obs_on:
                _smetrics.record_tier_event("resume_resident")
                _flight.default_flight().record(
                    "resume", session=s.session_id, seq_id=seq_id,
                    tier="hbm", tokens=plan.present, bytes=0,
                    trace_id=trace_id)
                self._note_tier()
            return plan.present
        # parked
        present = 0
        nbytes = 0
        fell_back = False
        with self._lock:
            if s.pinned_tokens:
                if self.cache is not None:
                    from .prefixcache import PrefixMatch

                    self.cache.attach(seq_id, PrefixMatch(
                        keys=list(s.pinned_keys),
                        pages=list(s.pinned_pages),
                        tokens=s.pinned_tokens))
                else:
                    self.pool.attach_prefix(
                        seq_id, list(s.pinned_pages), s.pinned_tokens)
                present = s.pinned_tokens
                self._unpin(s)
        try:
            export = self.tier.fetch(s.session_id)
            if getattr(export, "adapter_id", None) != s.adapter_id:
                # the payload travelled (proc plane / stale park) and
                # carries another variant's K/V — typed reject, then
                # re-prefill under the session's own adapter
                raise AdapterMismatchError(
                    f"parked payload for session {s.session_id} was "
                    f"exported under adapter "
                    f"{getattr(export, 'adapter_id', None)!r} but the "
                    f"session resumes under {s.adapter_id!r}")
            with self._lock:
                self.pool.import_seq(export, seq_id)
            present = export.length
            nbytes = export.nbytes()
        except (SpillCorruptError, SpillMissingError,
                AdapterMismatchError) as e:
            fell_back = True
            with self._lock:
                self._stats["re_prefills"] += 1
            _log.warning(
                "session %d resume fell back to re-prefill at %d "
                "tokens: %s", s.session_id, present, e)
            if obs_on:
                _smetrics.record_tier_event("re_prefill")
                _flight.default_flight().record(
                    "spill_reject", session=s.session_id, seq_id=seq_id,
                    reason=type(e).__name__, tokens_kept=present,
                    trace_id=trace_id)
        with self._lock:
            s.state = "active"
            s.seq_id = seq_id
            s.history = s.history[:present]
            s.parked_bytes = 0
            s.resumes += 1
            self._stats["resumes"] += 1
            if not fell_back:
                self._stats["resumed_host"] += 1
        if obs_on:
            if not fell_back:
                _smetrics.record_tier_event("resume_host")
                _smetrics.record_tier_transfer(nbytes, "resume")
            _flight.default_flight().record(
                "resume", session=s.session_id, seq_id=seq_id,
                tier="host", tokens=present, bytes=nbytes,
                trace_id=trace_id)
            self._note_tier()
        return present

    def on_retire(self, s: TierSession, seq_id: int,
                  prompt: Sequence[int], generated: Sequence[int],
                  trace_id: Optional[str] = None,
                  adapter_id: Optional[str] = None) -> bool:
        """A sequence carrying this session retired cleanly: adopt its
        pool pages (the loop skips ``free_seq``) and go idle, recording
        the adapter the K/V was produced under.  Returns False when the
        session cannot keep residency (closed/stale) — the loop then
        frees the pages as usual."""
        with self._lock:
            if self._closing or s.state not in ("fresh", "active"):
                return False
            kv = self.pool.length(seq_id)
            s.seq_id = seq_id
            s.history = ([int(t) for t in prompt]
                         + [int(t) for t in generated])[:kv]
            s.state = "idle"
            s.last_used = self._now()
            s.last_trace_id = trace_id
            s.adapter_id = adapter_id
            s._spilled_ev.clear()
            return True

    def on_quarantine(self, s: TierSession) -> None:
        """The carrying sequence was quarantined (or the run died): the
        pool side is already freed by the evictor — reset the session
        so its next turn prefills fresh."""
        with self._lock:
            if s.state == "parked":
                self.tier.discard(s.session_id)
                self._unpin(s)
            s.state = "fresh"
            s.seq_id = None
            s.history = []
            s.parked_bytes = 0
            s.adapter_id = None

    def locked_pages(self) -> int:
        """Pool pages held by IDLE (or mid-spill) sessions that no
        active admission reservation covers — the admission bound sets
        exactly these aside (and ``make_room`` can free them).  Pages
        an idle session merely shares with a live charged sequence are
        that charge's problem, not ours."""
        with self._lock:
            n = 0
            seen = set()
            for s in self._sessions.values():
                if s.state not in ("idle", "spilling", "resuming") \
                        or s.seq_id is None:
                    continue
                h = self.pool._tables.get(s.seq_id)
                if h is None:
                    continue
                for p in h.pages:
                    if p not in seen \
                            and self.pool._allocator.get(p) == s.seq_id:
                        seen.add(p)
                        n += 1
            return n

    def make_room(self, pages_short: int, wait_s: float = 5.0) -> int:
        """Admission pressure (waiting requests that do not fit): spill
        idle sessions — and, if still short, evict parked sessions'
        pinned pages — until `pages_short` pool pages came free.
        Returns pages actually freed; the caller re-checks its bound."""
        freed = self._free_pages(int(pages_short))
        if freed >= pages_short:
            return freed
        # async spills already in flight may land momentarily
        with self._lock:
            pending = [s for s in self._sessions.values()
                       if s.state == "spilling"]
        for s in pending:
            if s._spilled_ev.wait(wait_s):
                freed += s.last_freed
        return freed

    # -- spill machinery ------------------------------------------------

    def spill(self, s: TierSession, wait: bool = False) -> bool:
        """Queue one idle session for the spill writer (async device→
        host copy overlapped with decode).  ``wait=True`` blocks until
        the payload is parked and the pages are freed.  Returns False
        when the session was not spillable."""
        with self._lock:
            if s.state not in _SPILLABLE:
                return False
        self._spill_q.put(s)
        if wait:
            s._spilled_ev.wait(30.0)
        return True

    def spill_idle(self, older_than_s: Optional[float] = None,
                   wait: bool = False) -> int:
        """Proactive spill: queue every session idle longer than the
        threshold (None reads ``spill_after_s``; 0 = all idle).  The
        fleet's load signals call this when queue depth climbs."""
        cutoff = self.spill_after_s if older_than_s is None \
            else float(older_than_s)
        now = self._now()
        with self._lock:
            victims = [s for s in self._sessions.values()
                       if s.state in _SPILLABLE
                       and now - s.last_used >= cutoff]
        n = 0
        for s in victims:
            if self.spill(s, wait=wait):
                n += 1
        return n

    def _spill_loop(self) -> None:
        while True:
            s = self._spill_q.get()
            if s is None:
                return
            if not self._begin_spill(s):
                continue
            try:
                self._spill_one(s, why="writer")
            except Exception:  # noqa: BLE001 — writer must survive
                _log.exception("spill writer: session %d spill failed",
                               s.session_id)
                with self._lock:
                    if s.state == "spilling":
                        s.state = "idle"
                s._spilled_ev.set()

    def _begin_spill(self, s: TierSession) -> bool:
        with self._lock:
            if s.state not in _SPILLABLE:
                return False
            s.state = "spilling"
            s._spilled_ev.clear()
            return True

    def _spill_one(self, s: TierSession, why: str) -> int:
        """Export → park → free, in that order (ack discipline: device
        pages are freed only after the park returned).  The caller has
        CASed the session to ``spilling``.  Returns pool pages freed."""
        pool = self.pool
        with self._lock:
            seq = s.seq_id
            skip = 0
            keys: List[str] = []
            pages: List[int] = []
            if self.cache is not None and len(s.history) > 1:
                m = self.cache.match(s.history,
                                     adapter_id=s.adapter_id)
                full_pages = m.tokens // pool.page_size
                if full_pages:
                    pages = [int(p) for p in m.pages[:full_pages]]
                    keys = list(m.keys[:full_pages])
                    skip = full_pages * pool.page_size
                    pool.retain_pages(pages)
                    for p in pages:
                        self._pin_holds[p] = self._pin_holds.get(p, 0) + 1
        # export OUTSIDE the tier lock (ISSUE 20): the sequence is
        # quiescent (the caller CASed it to ``spilling``), its pin
        # bookkeeping is done, and the pool itself stages the D2H copy
        # off its own lock — so neither lock serializes concurrent
        # append_tokens (decode) or session admission behind the copy
        try:
            export = pool.export_seq(seq, skip_tokens=skip,
                                     adapter_id=s.adapter_id)
        except BaseException:
            with self._lock:
                self._release_pins(pages)
                s.state = "idle"
            s._spilled_ev.set()
            raise
        # park OUTSIDE the pool lock: the CRC pass + host copy must not
        # stall decode (the writer-thread overlap this tier exists for)
        nbytes = export.nbytes()
        try:
            self.tier.park(s.session_id, export)
        except HostTierFullError:
            if not self._evict_for(nbytes):
                with self._lock:
                    self._release_pins(pages)
                    s.state = "idle"
                    self._stats["spill_aborts"] += 1
                s._spilled_ev.set()
                _log.warning(
                    "session %d spill aborted: host tier cannot fit "
                    "%d bytes even after eviction", s.session_id, nbytes)
                return 0
            self.tier.park(s.session_id, export)
        with self._lock:
            freed = pool.free_seq(seq)
            s.seq_id = None
            s.pinned_keys, s.pinned_pages = keys, pages
            s.pinned_tokens = skip
            s.parked_bytes = nbytes
            s.last_freed = freed
            s.state = "parked"
            s.spills += 1
            self._stats["spills"] += 1
            if why == "pressure":
                self._stats["pressure_spills"] += 1
        s._spilled_ev.set()
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_tier_event("spill")
            _smetrics.record_tier_transfer(nbytes, "spill")
            _flight.default_flight().record(
                "spill", session=s.session_id, seq_id=seq, why=why,
                bytes=nbytes, skip_tokens=skip, pages_freed=freed,
                trace_id=s.last_trace_id)
            self._note_tier()
        return freed

    def _evict_for(self, nbytes: int) -> bool:
        """LRU-evict parked sessions until `nbytes` fit the tier (their
        next resume re-prefills — counted, never lost)."""
        while self.tier.capacity_bytes \
                and self.tier.capacity_bytes - self.tier.bytes_used \
                < nbytes:
            key = self.tier.lru_key()
            if key is None:
                return False
            with self._lock:
                victim = self._sessions.get(key)
                if victim is not None and victim.state == "parked":
                    self._discard_parked(victim, why="capacity")
                else:
                    self.tier.discard(key)
        return True

    # -- pressure / eviction helpers -----------------------------------

    def _free_pages(self, short: int) -> int:
        """Free >= `short` pool pages if the tiers allow: spill idle
        sessions LRU-first (inline — safe under the pool RLock), then
        evict parked sessions' pinned prefix pages."""
        freed = 0
        with self._lock:
            victims = sorted(
                (s for s in self._sessions.values()
                 if s.state in _SPILLABLE),
                key=lambda s: s.last_used)
        for s in victims:
            if freed >= short:
                return freed
            if self._begin_spill(s):
                freed += self._spill_one(s, why="pressure")
        if freed < short:
            with self._lock:
                parked = sorted(
                    (s for s in self._sessions.values()
                     if s.state == "parked" and s.pinned_pages),
                    key=lambda s: s.last_used)
                for s in parked:
                    if freed >= short:
                        break
                    freed += self._discard_parked(s, why="pressure")
        return freed

    def _reclaim(self, short: int) -> int:
        """The pool's pressure-reclaimer hook: ``append_tokens`` ran
        short mid-claim.  Runs UNDER the pool RLock on the claiming
        thread — the inline-spill arm (the reason the manager shares
        the pool's lock)."""
        return self._free_pages(int(short))

    def _discard_parked(self, s: TierSession, why: str) -> int:
        """Drop a parked session's payload + pinned pages (caller holds
        the lock); the session resets to fresh and its next turn
        re-prefills.  Returns pool pages freed by unpinning."""
        self.tier.discard(s.session_id)
        freed = self._unpin(s)
        s.state = "fresh"
        s.history = []
        s.parked_bytes = 0
        s.seq_id = None
        s.adapter_id = None
        self._stats["evictions"] += 1
        if why == "mismatch":
            self._stats["mismatch_resets"] += 1
        elif why == "adapter_mismatch":
            self._stats["adapter_mismatch_resets"] += 1
        if _flags._VALUES["FLAGS_observability"]:
            _smetrics.record_tier_event("evict")
            _flight.default_flight().record(
                "tier_evict", session=s.session_id, why=why,
                trace_id=s.last_trace_id)
        return freed

    def _reset_resident(self, s: TierSession, why: str) -> None:
        """Drop an idle session's residency (caller holds the lock)."""
        if s.seq_id is not None:
            self.pool.free_seq(s.seq_id)
        s.state = "fresh"
        s.seq_id = None
        s.history = []
        s.adapter_id = None
        if why == "adapter_mismatch":
            self._stats["adapter_mismatch_resets"] += 1
        else:
            self._stats["mismatch_resets"] += 1

    def _unpin(self, s: TierSession) -> int:
        """Release the session's pinned prefix holds (caller holds the
        lock); returns pages that actually came free."""
        freed = self._release_pins(s.pinned_pages)
        s.pinned_keys, s.pinned_pages, s.pinned_tokens = [], [], 0
        return freed

    def _release_pins(self, pages: Sequence[int]) -> int:
        if not pages:
            return 0
        for p in pages:
            n = self._pin_holds.get(p, 0) - 1
            if n <= 0:
                self._pin_holds.pop(p, None)
            else:
                self._pin_holds[p] = n
        return self.pool.release_pages(pages)

    # -- pool audit hooks ----------------------------------------------

    def _holds(self) -> Dict[int, int]:
        """External-owner hook: refcount holds the manager explains —
        pinned prefix pages of parked (and mid-spill) sessions.  To
        ``check_invariants`` a parked page is owned, not orphaned."""
        return dict(self._pin_holds)

    def _remap(self, remap: Dict[int, int]) -> None:
        """Defrag moved pages: pins follow."""
        self._pin_holds = {remap.get(p, p): n
                           for p, n in self._pin_holds.items()}
        for s in self._sessions.values():
            if s.pinned_pages:
                s.pinned_pages = [remap.get(p, p)
                                  for p in s.pinned_pages]

    # -- introspection --------------------------------------------------

    def combined_capacity_pages(self) -> int:
        """Total session-holding capacity in pages across both tiers —
        the COMBINED reservation ceiling (README sizing math)."""
        if not self.tier.capacity_bytes:
            return 1 << 62
        return self.pool.num_pages \
            + self.tier.capacity_bytes // self.pool.bytes_per_page()

    def parked_sessions(self) -> int:
        with self._lock:
            return sum(1 for s in self._sessions.values()
                       if s.state == "parked")

    def idle_sessions(self) -> int:
        with self._lock:
            return sum(1 for s in self._sessions.values()
                       if s.state in ("idle", "spilling"))

    def check_invariants(self) -> Dict:
        """Both tiers' audit in one report: the pool's page invariants
        (with the manager's pins explained through the owner hook) and
        the host tier's byte/CRC bookkeeping."""
        pool_report = self.pool.check_invariants()
        tier_report = self.tier.check_invariants()
        return {"ok": pool_report["ok"] and tier_report["ok"],
                "pool": pool_report, "tier": tier_report}

    def stats(self) -> Dict:
        with self._lock:
            st = dict(self._stats)
            st["sessions"] = len(self._sessions)
            st["idle_sessions"] = sum(
                1 for s in self._sessions.values()
                if s.state in ("idle", "spilling"))
            st["parked_sessions"] = sum(
                1 for s in self._sessions.values()
                if s.state == "parked")
        st["tier"] = self.tier.stats()
        return st

    # -- internals ------------------------------------------------------

    def _now(self) -> float:
        import time

        return time.monotonic()

    def _note_tier(self) -> None:
        """Tier gauges (callers gate on FLAGS_observability)."""
        pool = self.pool
        used = pool.used_pages
        _smetrics.record_tier_gauges(
            host_bytes=self.tier.bytes_used,
            host_utilization=self.tier.utilization(),
            parked_sessions=self.parked_sessions(),
            hbm_utilization=used / float(pool.num_pages)
            if pool.num_pages else 0.0)

    @staticmethod
    def _common_prefix(a: Sequence[int], b: Sequence[int]) -> int:
        """Longest common prefix of retained history `a` and the new
        prompt `b`, capped at len(b)-1 so at least one prompt token
        still runs through the model (the first-token logits source)."""
        limit = min(len(a), len(b) - 1)
        c = 0
        while c < limit and int(a[c]) == int(b[c]):
            c += 1
        return c
