"""Per-request sampling contract for the serving decode loop (ISSUE 13).

The decode tier was greedy-only: every caller got ``argmax`` and the
oracle parity suite pinned it.  Real traffic wants temperature /
top-k / top-p sampling, stop sequences, logit bias, and a per-request
generation cap — each a distinct serving scenario (serve_bench
``--sampling``) — WITHOUT forking the step function per request.  So
the contract is:

- :class:`SamplingParams` is an immutable per-request value object
  carried on ``DecodeRequest.sampling`` (and threaded from
  ``Engine.submit(sampling=)`` in pass-through mode).  ``temperature
  == 0`` (the default) is EXACT greedy — bit-identical to the
  pre-ISSUE-13 loop and to ``full_decode``, which is also the
  determinism condition speculative decoding verifies against, so
  greedy/temp=0 requests keep speculation ON and everything else
  degrades per-sequence to d=0 (see generate.py).
- :func:`sample_rows` is the ONE jitted sampling epilogue: the whole
  batch's next-token choice in a single fused call — per-row
  temperature scaling, top-k / top-p filtering, and a Gumbel-max draw
  keyed by (per-request seed, per-sequence token index) — the RNG
  stream never depends on batch composition, so an identical replay
  regenerates identical tokens (fp32 attention reduction order can
  still perturb a near-tied draw between DIFFERENT step shapes; the
  keys themselves cannot).  Greedy rows short-circuit host-side (the
  loop never pays a device round trip for pure-greedy batches,
  preserving the oracle's host-argmax arithmetic exactly).
- Logit bias applies BEFORE everything (greedy included): a biased
  greedy request is still deterministic, so its argmax surface is just
  shifted — ``apply_bias`` is the shared host helper.
- Stop sequences are a host-side suffix check (:func:`stop_hit`)
  applied after EVERY emitted token — including tokens emitted from
  inside an accepted draft block, the same contract as EOS.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["SamplingParams", "sample_rows", "apply_bias", "stop_hit"]


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Immutable per-request sampling knobs.

    temperature: 0.0 (default) = EXACT greedy (argmax; deterministic —
        keeps speculative verify on); > 0 samples from the scaled
        distribution.
    top_k: keep only the k highest-logit tokens before sampling
        (0 = off).  Ignored for greedy rows (argmax already is top-1).
    top_p: nucleus sampling — keep the smallest prefix of the
        probability-sorted vocab whose cumulative mass reaches p
        (1.0 = off; the top-1 token is always kept).
    stop: stop token sequences (any iterable of token iterables) — a
        sequence retires the moment its generated tokens END with one
        of them; the stop tokens stay in the output (the EOS
        convention).
    logit_bias: {token_id: additive bias} applied to every step's
        logits before argmax/sampling — greedy rows included.
    max_new: per-request generation cap; the effective cap is
        ``min(DecodeRequest.max_new_tokens, max_new)`` (None: the
        request's own cap stands).
    seed: per-request RNG stream for the Gumbel draw; the g-th
        generated token folds in g, so a retried request replays
        identically and batch composition cannot perturb it.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop: Tuple[Tuple[int, ...], ...] = ()
    logit_bias: Optional[Tuple[Tuple[int, float], ...]] = None
    max_new: Optional[int] = None
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p}")
        if self.max_new is not None and self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")
        if not 0 <= int(self.seed) < 2 ** 32:
            # the RNG key is a uint32: a negative seed would crash the
            # epilogue MID-BATCH (killing batch-mates) instead of
            # failing this one request's construction
            raise ValueError(
                f"seed must be a uint32 (0 <= seed < 2**32), got "
                f"{self.seed}")
        # normalize the container fields so the frozen instance is
        # hashable and order-stable (dicts/lists accepted at call sites)
        object.__setattr__(self, "stop", tuple(
            tuple(int(t) for t in s) for s in (self.stop or ())))
        if any(not s for s in self.stop):
            raise ValueError("stop sequences must be non-empty")
        bias = self.logit_bias
        if bias is not None:
            if isinstance(bias, dict):
                bias = bias.items()
            norm = tuple(sorted((int(t), float(b)) for t, b in bias))
            if norm and norm[0][0] < 0:
                raise ValueError(
                    f"logit_bias token ids must be >= 0, got "
                    f"{norm[0][0]}")
            object.__setattr__(self, "logit_bias", norm or None)

    def max_bias_token(self) -> int:
        """Largest biased token id (-1 when no bias) — the decode loop
        validates it against the model's vocab at admission, so an
        out-of-range id fails THAT request up front instead of
        crashing the shared batch mid-step."""
        return self.logit_bias[-1][0] if self.logit_bias else -1

    @property
    def greedy(self) -> bool:
        """True when this request's choice is deterministic argmax —
        the condition under which speculative verify stays enabled."""
        return self.temperature == 0.0


def apply_bias(row: np.ndarray,
               params: Optional[SamplingParams]) -> np.ndarray:
    """Host-side logit bias for one [V] row (a copy when bias applies;
    the input row otherwise) — shared by the greedy argmax path and the
    draft-acceptance walk so both see the same decision surface."""
    if params is None or not params.logit_bias:
        return row
    out = np.asarray(row, np.float32).copy()
    for tok, b in params.logit_bias:
        out[tok] += b
    return out


def stop_hit(tokens: Sequence[int],
             params: Optional[SamplingParams]) -> bool:
    """True when `tokens` (the generated tokens so far) ends with one of
    the request's stop sequences."""
    if params is None or not params.stop:
        return False
    for s in params.stop:
        n = len(s)
        if n <= len(tokens) and tuple(tokens[-n:]) == s:
            return True
    return False


@functools.lru_cache(maxsize=32)
def _sample_jit(vocab: int):
    """The jitted epilogue body, one compile per vocab width: [B, V]
    biased logits + per-row (temperature, top_k, top_p, key-fold data)
    -> [B] sampled token ids.  All three filters fuse into one call."""
    import jax
    import jax.numpy as jnp

    def body(logits, temps, top_ks, top_ps, seeds, steps):
        x = logits / jnp.maximum(temps, 1e-6)[:, None]
        # top-k: mask everything below the k-th largest logit (k=0/V
        # disables); ties at the threshold stay in, which only widens
        # the kept set — standard top-k semantics
        sorted_desc = jnp.sort(x, axis=-1)[:, ::-1]
        k = jnp.clip(jnp.where(top_ks > 0, top_ks, vocab), 1, vocab)
        kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None],
                                  axis=-1)  # [B, 1]
        x = jnp.where(x >= kth, x, -jnp.inf)
        # top-p over the filtered distribution: keep every token whose
        # PRECEDING cumulative mass is < p (the smallest prefix
        # reaching p; the top-1 always stays because its preceding
        # mass is 0).  Comparing the preceding mass — not the
        # inclusive cumsum — keeps top_p=1.0 a true no-op even when
        # the fp32 cumsum tops out at 0.9999999 and never reaches 1
        probs = jax.nn.softmax(x, axis=-1)
        p_desc = jnp.sort(probs, axis=-1)[:, ::-1]
        preceding = jnp.cumsum(p_desc, axis=-1) - p_desc
        kept = preceding < top_ps[:, None]
        p_min = jnp.min(jnp.where(kept, p_desc, jnp.inf), axis=-1,
                        keepdims=True)
        x = jnp.where(probs >= p_min, x, -jnp.inf)
        # Gumbel-max draw keyed (request seed, per-sequence token
        # index): batch composition cannot perturb a request's stream
        keys = jax.vmap(lambda s, g: jax.random.fold_in(
            jax.random.PRNGKey(s), g))(seeds, steps)
        gumbel = jax.vmap(
            lambda kk: jax.random.gumbel(kk, (vocab,)))(keys)
        return jnp.argmax(x + gumbel, axis=-1).astype(jnp.int32)

    return jax.jit(body)


def sample_rows(logits: np.ndarray, params: Sequence[SamplingParams],
                steps: Sequence[int]) -> np.ndarray:
    """The ONE jitted sampling epilogue: sample a next token for every
    row of `logits` [B, V] under its request's (non-greedy)
    SamplingParams; ``steps[i]`` is row i's per-sequence generated-token
    index (the RNG fold key).  Logit bias must already be applied
    (``apply_bias`` — the loop biases rows before both the greedy and
    sampled arms).  Greedy rows do NOT belong here — the loop resolves
    them host-side so the oracle argmax arithmetic is untouched."""
    logits = np.ascontiguousarray(np.asarray(logits, np.float32))
    if logits.ndim != 2:
        raise ValueError(f"sample_rows wants [B, V] rows, got "
                         f"{logits.shape}")
    B, V = logits.shape
    if len(params) != B or len(steps) != B:
        raise ValueError("params/steps must align with the logit rows")
    temps = np.asarray([p.temperature for p in params], np.float32)
    if (temps <= 0).any():
        raise ValueError(
            "greedy rows (temperature 0) must take the host argmax "
            "path, not the sampling epilogue")
    top_ks = np.asarray([p.top_k for p in params], np.int32)
    top_ps = np.asarray([p.top_p for p in params], np.float32)
    seeds = np.asarray([p.seed for p in params], np.uint32)
    steps = np.asarray(steps, np.uint32)
    return np.asarray(_sample_jit(V)(
        logits, temps, top_ks, top_ps, seeds, steps))
